"""Tenancy-aware admission: weighted fair queuing, quotas, rate
limits (docs/serving.md "Multi-tenant QoS").

One abusive client must not starve every other tenant, and overload
must degrade for the OFFENDER, not for everyone. The pieces:

* :class:`TenantQueue` — drop-in replacement for the single FIFO
  admission queue: per-tenant sub-queues scheduled by **stride
  scheduling** (the deterministic cousin of weighted fair queuing —
  each tenant carries a virtual ``pass``; the pop always serves the
  lowest pass and advances it by ``stride = K / weight``, so observed
  service share converges to configured weights under backlog while
  an idle tenant accumulates no credit). Priority classes are
  preserved WITHIN each tenant (higher ``ScanRequest.priority`` pops
  first, FIFO within a class); the coalescer downstream still batches
  across tenants freely — padding buckets don't care who owns a row,
  the queue only decides *ordering*.

* **Admission quotas** — per-tenant ``max_queued`` (queue slots) and
  ``max_inflight`` (admitted-but-unresolved requests, i.e. work
  volume in the pipeline). An over-quota tenant is answered with
  :class:`RateLimitedError` → HTTP 429 + ``Retry-After`` — the same
  language ``artifact/registry.py`` already speaks as a client — so
  it sheds its OWN load while compliant tenants' deadlines hold.
  Only genuine global exhaustion still raises
  :class:`~.queue.QueueFullError` → 503.

* **Token-bucket rate limits** — per-tenant ``rate``/``burst``;
  over-rate arrivals get 429 with a computed ``Retry-After``.

* :class:`TenantBook` — per-tenant admitted/rejected/shed counters
  and request-latency histograms, exported through
  ``ScanScheduler.stats()["tenants"]`` → ``/metrics`` (JSON and
  Prometheus text) as the fairness/autoscaling signal.

Tenant cardinality is bounded: beyond ``max_tenants`` distinct
UNCONFIGURED tenant ids, new ids fold into the shared anonymous
tenant — a client minting random tenant names must not explode the
queue's bookkeeping or the ``/metrics`` label space.
"""

from __future__ import annotations

import heapq
import json
import os
import threading
import time
from dataclasses import dataclass, field, fields, replace
from typing import Optional

from .metrics import LatencyHistogram
from .queue import (QueueFullError, ScanRequest, SchedError,
                    SchedulerClosed)

ANONYMOUS = "anon"

# stride scheduling constant: pass advances by _STRIDE1 / weight per
# pop, so a weight-4 tenant is served 4x as often as a weight-1
# tenant under backlog
_STRIDE1 = float(1 << 20)


class RateLimitedError(SchedError):
    """Per-tenant quota or rate-limit rejection — the tenant's own
    load is shed (HTTP 429 + Retry-After), unlike the global
    QueueFullError 503. Carries the hint the server sends back."""

    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 tenant: str = ""):
        super().__init__(msg)
        self.retry_after_s = max(0.0, float(retry_after_s))
        self.tenant = tenant


@dataclass(frozen=True)
class TenantConfig:
    """Per-tenant QoS knobs. Zero means unlimited."""

    name: str = ""
    weight: float = 1.0       # WFQ service share under backlog
    rate: float = 0.0         # token-bucket refill, requests/second
    burst: float = 0.0        # bucket capacity (default: max(rate,1))
    max_queued: int = 0       # admission quota: queued requests
    max_inflight: int = 0     # admission quota: unresolved requests


@dataclass(frozen=True)
class TenancyConfig:
    """The whole tenancy table: explicit tenants + the default
    template unknown tenants instantiate from."""

    tenants: dict = field(default_factory=dict)
    default: TenantConfig = field(default_factory=TenantConfig)
    anonymous: str = ANONYMOUS
    # cap on DYNAMICALLY discovered tenants (configured tenants are
    # always honored); overflow folds into the anonymous tenant
    max_tenants: int = 64

    def for_tenant(self, name: str) -> TenantConfig:
        cfg = self.tenants.get(name)
        if cfg is None:
            cfg = replace(self.default, name=name)
        return cfg


_TENANT_FIELDS = {f.name: f for f in fields(TenantConfig)
                  if f.name != "name"}


def _coerce_tenant_kv(key: str, raw: str):
    f = _TENANT_FIELDS[key]
    if f.type in ("int", int):
        return int(raw)
    return float(raw)


def parse_tenant_config(text) -> TenancyConfig:
    """``--tenant-config`` parser. Accepts either a JSON file path
    (``{"alice": {"weight": 4, "rate": 100}, "default": {...}}``) or
    an inline spec::

        alice:weight=4,rate=100,burst=200,max_queued=64;bob:weight=1
        default:rate=50,max_inflight=128

    Unknown keys and malformed values raise ValueError so a typo'd
    config fails the run up front instead of silently granting
    unlimited service."""
    if isinstance(text, TenancyConfig):
        return text
    text = (text or "").strip()
    if not text:
        return TenancyConfig()
    if os.path.isfile(text):
        with open(text, "r", encoding="utf-8") as f:
            try:
                doc = json.load(f)
            except ValueError as e:
                raise ValueError(
                    f"tenant config {text!r}: invalid JSON ({e})")
        if not isinstance(doc, dict):
            raise ValueError(
                f"tenant config {text!r}: want an object mapping "
                f"tenant -> settings")
        tenants: dict = {}
        default = TenantConfig()
        for name, kv in doc.items():
            if not isinstance(kv, dict):
                raise ValueError(
                    f"tenant {name!r}: want an object of settings")
            bad = set(kv) - set(_TENANT_FIELDS)
            if bad:
                raise ValueError(
                    f"tenant {name!r}: unknown keys {sorted(bad)} "
                    f"(choose from {sorted(_TENANT_FIELDS)})")
            cfg = TenantConfig(name=name, **{
                k: _coerce_tenant_kv(k, str(v))
                for k, v in kv.items()})
            if name == "default":
                default = replace(cfg, name="")
            else:
                tenants[name] = cfg
        return TenancyConfig(tenants=tenants, default=default)
    tenants = {}
    default = TenantConfig()
    for chunk in text.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, sep, rest = chunk.partition(":")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"bad tenant-config entry {chunk!r} "
                f"(want name:key=value,...)")
        kv: dict = {}
        for pair in rest.split(","):
            pair = pair.strip()
            if not pair:
                continue
            key, eq, raw = pair.partition("=")
            key = key.strip()
            if not eq or key not in _TENANT_FIELDS:
                raise ValueError(
                    f"bad tenant-config entry {pair!r} for "
                    f"{name!r} (choose from "
                    f"{sorted(_TENANT_FIELDS)})")
            try:
                kv[key] = _coerce_tenant_kv(key, raw.strip())
            except (TypeError, ValueError):
                raise ValueError(
                    f"bad tenant-config value for {name}.{key}: "
                    f"{raw!r}")
        cfg = TenantConfig(name=name, **kv)
        if name == "default":
            default = replace(cfg, name="")
        else:
            tenants[name] = cfg
    return TenancyConfig(tenants=tenants, default=default)


class TokenBucket:
    """Classic token bucket; ``take`` returns 0.0 on admit or the
    seconds until a token will be available (the Retry-After hint).
    Callers serialize access (the queue holds its lock)."""

    def __init__(self, rate: float, burst: float = 0.0):
        self.rate = max(1e-9, float(rate))
        self.burst = float(burst) if burst and burst > 0 \
            else max(self.rate, 1.0)
        self.tokens = self.burst
        self._t = time.monotonic()

    def take(self, n: float = 1.0) -> float:
        now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._t) * self.rate)
        self._t = now
        if self.tokens >= n:
            self.tokens -= n
            return 0.0
        return (n - self.tokens) / self.rate


class TenantBook:
    """Per-tenant counters + request-latency histograms. The books
    must balance: for every tenant, ``admitted`` equals
    ``ok + degraded + failed + timed_out + cancelled`` once the
    pipeline drains (rejections never count as admitted)."""

    OUTCOMES = ("ok", "degraded", "failed", "timed_out", "cancelled")
    REJECTIONS = ("rejected_rate", "rejected_quota",
                  "rejected_budget", "rejected_503")

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}      # tenant -> {event: n}
        self._hist: dict = {}          # tenant -> LatencyHistogram

    def _slot(self, tenant: str) -> dict:
        c = self._counters.get(tenant)
        if c is None:
            c = {k: 0 for k in
                 ("admitted",) + self.OUTCOMES + self.REJECTIONS}
            # lint: disable=unbounded-label-cardinality -- tenant
            # ids are pre-folded by TenantQueue._resolve: dynamic
            # overflow past max_tenants lands on the anonymous
            # tenant before any book call sees it
            self._counters[tenant] = c
        return c

    def inc(self, tenant: str, event: str, n: int = 1) -> None:
        with self._lock:
            slot = self._slot(tenant)
            # lint: disable=unbounded-label-cardinality -- event
            # names are code-literal outcome/rejection kinds
            slot[event] = slot.get(event, 0) + n

    def observe(self, tenant: str, seconds: float,
                trace_id: str = "") -> None:
        with self._lock:
            h = self._hist.get(tenant)
            if h is None:
                # lint: disable=unbounded-label-cardinality -- ids
                # pre-folded to max_tenants (anon) upstream
                h = self._hist[tenant] = LatencyHistogram()
            h.observe(seconds, exemplar=trace_id)

    def hist_snapshot(self) -> dict:
        """Raw per-tenant bucket counts for the Prometheus
        histogram family (obs/prom.py), with trace-id exemplars."""
        with self._lock:
            return {t: h.raw() for t, h in self._hist.items()}

    def snapshot(self, live: Optional[dict] = None) -> dict:
        """``{tenant: {counters, shed, latency, [depth/inflight/
        weight from ``live``]}}``. ``shed`` is the total load the
        tenant itself absorbed as 429s."""
        with self._lock:
            out = {}
            names = set(self._counters) | set(live or {})
            for t in names:
                c = dict(self._slot(t))
                h = self._hist.get(t)
                entry = {
                    "counters": c,
                    "shed": c["rejected_rate"] + c["rejected_quota"]
                    + c["rejected_budget"],
                    "latency": h.to_dict() if h is not None
                    else LatencyHistogram().to_dict(),
                }
                if live and t in live:
                    entry.update(live[t])
                out[t] = entry
            return out


class _Sub:
    """One tenant's sub-queue: a priority heap plus the stride and
    quota state. All fields are guarded by the TenantQueue lock."""

    __slots__ = ("cfg", "heap", "pass_value", "stride", "bucket",
                 "queued", "inflight")

    def __init__(self, cfg: TenantConfig, vtime: float):
        self.cfg = cfg
        self.heap: list = []      # (-priority, seq, req)
        self.pass_value = vtime
        self.stride = _STRIDE1 / max(cfg.weight, 1e-6)
        self.bucket = TokenBucket(cfg.rate, cfg.burst) \
            if cfg.rate > 0 else None
        self.queued = 0
        self.inflight = 0


class TenantQueue:
    """The tenancy-aware admission queue (put/get/depth/close).
    With the default TenancyConfig every request lands on one
    unlimited anonymous tenant and behavior reduces EXACTLY to the
    old bounded FIFO — the parity suites ride on that, and the
    package exports ``AdmissionQueue`` as an alias for it."""

    def __init__(self, maxsize: int = 256,
                 tenancy: Optional[TenancyConfig] = None):
        self.maxsize = max(1, int(maxsize))
        self.tenancy = tenancy or TenancyConfig()
        self.book = TenantBook()
        self._cv = threading.Condition()
        self._subs: dict = {}          # tenant -> _Sub
        self._total = 0
        self._vtime = 0.0              # pass of the last pop
        self._seq = 0
        self._closed = False
        # device-second budgets (--tenant-budget, obs/cost.py):
        # tenant -> TenantBudget, read against the cost ledger's
        # windowed books at admission
        self._budgets: dict = {}
        self._cost_ledger = None

    def configure_budgets(self, budgets: dict, ledger) -> None:
        """Arm budget admission: ``budgets`` maps tenant →
        :class:`~trivy_tpu.obs.cost.TenantBudget`; ``ledger`` is the
        :class:`~trivy_tpu.obs.cost.CostLedger` whose windowed
        device-second books the check reads."""
        self._budgets = dict(budgets or {})
        self._cost_ledger = ledger

    # --- tenant resolution (under lock) ---

    def _resolve(self, req: ScanRequest) -> tuple:
        tenant = getattr(req, "tenant", "") or self.tenancy.anonymous
        if tenant not in self._subs \
                and tenant not in self.tenancy.tenants \
                and tenant != self.tenancy.anonymous \
                and len(self._subs) >= self.tenancy.max_tenants:
            # tenant-cardinality bound: dynamic overflow folds into
            # the anonymous tenant (and shares its quotas) instead of
            # growing the books without bound
            tenant = self.tenancy.anonymous
        req.tenant = tenant
        sub = self._subs.get(tenant)
        if sub is None:
            sub = _Sub(self.tenancy.for_tenant(tenant), self._vtime)
            self._subs[tenant] = sub
        return tenant, sub

    # --- admission ---

    def put(self, req: ScanRequest, block: bool = False,
            timeout: Optional[float] = None) -> None:
        # admission accounting (TenantBook takes its own lock) is
        # booked AFTER the cv releases (lint: lock-discipline) —
        # the decision is made under the lock, the book entry
        # follows microseconds later, and books still balance
        # because every exit path below sets exactly one event
        tenant = ""
        event = ""
        try:
            # budget gate BEFORE the cv: the windowed-spend read
            # takes the cost ledger's own lock, and lock discipline
            # forbids acquiring another module's lock under ours.
            # The read is microseconds stale by admission time —
            # budgets are a 10s-bucketed signal, staleness within
            # one lock handoff is noise
            budget = self._budgets.get(
                getattr(req, "tenant", "")
                or self.tenancy.anonymous) \
                if self._budgets else None
            if budget is not None and self._cost_ledger is not None:
                spend = self._cost_ledger.window_device_s(
                    budget.tenant, budget.window_s)
                if spend >= budget.device_s:
                    if budget.action == "throttle":
                        tenant = budget.tenant
                        event = "rejected_budget"
                        e = RateLimitedError(
                            f"tenant {budget.tenant!r} over "
                            f"device-second budget "
                            f"({spend:.3f}s of {budget.device_s:g}s"
                            f" per {budget.window_s:g}s)",
                            retry_after_s=max(
                                1.0, min(budget.window_s / 4,
                                         10.0)),
                            tenant=budget.tenant)
                        e.book_event = "rejected_budget"
                        raise e
                    # deprioritize: admit, but at the budget's
                    # priority floor — the request yields inside
                    # its own tenant lane until the spend ages out
                    if int(getattr(req, "priority", 0) or 0) \
                            > budget.floor:
                        req.priority = budget.floor
            with self._cv:
                if self._closed:
                    raise SchedulerClosed("scheduler is closed")
                tenant, sub = self._resolve(req)
                cfg = sub.cfg
                # per-tenant gates FIRST: an over-limit tenant gets
                # its own 429 even when the queue is also globally
                # full — the shed must land on the offender
                if sub.bucket is not None:
                    wait = sub.bucket.take()
                    if wait > 0.0:
                        event = "rejected_rate"
                        raise RateLimitedError(
                            f"tenant {tenant!r} over rate limit "
                            f"({cfg.rate:g}/s)",
                            retry_after_s=wait, tenant=tenant)
                self._check_quotas(tenant, sub)
                if not block and self._total >= self.maxsize:
                    event = "rejected_503"
                    raise QueueFullError(
                        f"scan queue full "
                        f"({self.maxsize} pending)")
                deadline = (time.monotonic() + timeout
                            if timeout is not None else None)
                waited = False
                while self._total >= self.maxsize:
                    remaining = None if deadline is None else \
                        deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        event = "rejected_503"
                        raise QueueFullError(
                            f"scan queue full "
                            f"({self.maxsize} pending)")
                    self._cv.wait(remaining)
                    waited = True
                    if self._closed:
                        raise SchedulerClosed(
                            "scheduler is closed")
                if waited:
                    # re-check the quotas after any blocking wait:
                    # N waiters could all have passed the pre-wait
                    # check against the same headroom and overshoot
                    # the quota by N-1 once capacity frees
                    self._check_quotas(tenant, sub)
                if not sub.queued:
                    # (re)activation: an idle tenant resumes at the
                    # CURRENT virtual time — idleness earns no
                    # credit, so a returning tenant cannot
                    # monopolize the queue
                    sub.pass_value = max(sub.pass_value,
                                         self._vtime)
                self._seq += 1
                heapq.heappush(
                    sub.heap,
                    (-int(getattr(req, "priority", 0) or 0),
                     self._seq, req))
                sub.queued += 1
                sub.inflight += 1
                self._total += 1
                event = "admitted"
                self._cv.notify_all()
        except BaseException as e:
            # quota rejections raised inside _check_quotas carry
            # their book event; closed-scheduler exits book nothing
            event = getattr(e, "book_event", event)
            raise
        finally:
            if tenant and event:
                self.book.inc(tenant, event)

    def _check_quotas(self, tenant: str, sub: "_Sub") -> None:
        """Admission quotas, under the queue lock. Raises the typed
        429 — tagged with its book event, which ``put`` records
        once the lock is released — so the tenant sheds its own
        load."""
        cfg = sub.cfg
        if cfg.max_queued and sub.queued >= cfg.max_queued:
            e = RateLimitedError(
                f"tenant {tenant!r} queue quota reached "
                f"({cfg.max_queued} queued)",
                retry_after_s=self._quota_hint(cfg),
                tenant=tenant)
            e.book_event = "rejected_quota"
            raise e
        if cfg.max_inflight and sub.inflight >= cfg.max_inflight:
            e = RateLimitedError(
                f"tenant {tenant!r} in-flight quota reached "
                f"({cfg.max_inflight} unresolved)",
                retry_after_s=self._quota_hint(cfg),
                tenant=tenant)
            e.book_event = "rejected_quota"
            raise e

    def _quota_hint(self, cfg: TenantConfig) -> float:
        # Retry-After for a quota rejection: the time the tenant's
        # own rate limit needs to drain one slot, or a 1s default
        # when it has no rate limit (quota pressure clears with
        # service, which we cannot predict cheaply)
        if cfg.rate > 0:
            return max(0.05, 1.0 / cfg.rate)
        return 1.0

    # --- service (the WFQ pop) ---

    def get(self, timeout: Optional[float] = None)\
            -> Optional[ScanRequest]:
        with self._cv:
            if not self._total and (timeout is None or timeout > 0):
                self._cv.wait(timeout)
            if not self._total:
                return None
            best = None
            for sub in self._subs.values():
                if sub.queued and (best is None or
                                   sub.pass_value < best.pass_value):
                    best = sub
            _, _, req = heapq.heappop(best.heap)
            best.queued -= 1
            self._total -= 1
            self._vtime = best.pass_value
            best.pass_value += best.stride
            self._cv.notify_all()
            return req

    # --- resolution bookkeeping (scheduler calls exactly once) ---

    def note_done(self, req: ScanRequest, outcome: str,
                  latency_s: Optional[float] = None) -> None:
        """Release the request's in-flight quota slot and book its
        outcome + latency on its tenant. Idempotent per request —
        double resolution races count once."""
        tenant = getattr(req, "tenant", "") or self.tenancy.anonymous
        with self._cv:
            if getattr(req, "_tenant_released", False):
                return
            req._tenant_released = True
            sub = self._subs.get(tenant)
            if sub is not None and sub.inflight > 0:
                sub.inflight -= 1
        self.book.inc(tenant, outcome)
        if latency_s is not None:
            self.book.observe(tenant, latency_s,
                              trace_id=getattr(req, "trace_id",
                                               "") or "")

    # --- introspection ---

    def depth(self) -> int:
        with self._cv:
            return self._total

    def tenant_depths(self) -> dict:
        with self._cv:
            return {t: {"queue_depth": sub.queued,
                        "inflight": sub.inflight,
                        "weight": sub.cfg.weight}
                    for t, sub in self._subs.items()}

    def tenant_snapshot(self) -> dict:
        return self.book.snapshot(self.tenant_depths())

    # --- lifecycle ---

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed
