"""Scheduler metrics: queue depth, batch occupancy, padding waste,
host/device overlap, per-phase latency histograms.

Everything here is lock-protected counters — cheap enough to update
on every request — snapshotted into one JSON-able dict that both the
server's ``/metrics`` endpoint and the ``--sched-stats`` CLI dump
serve verbatim.

The overlap ratio is measured, not inferred: the device executor
brackets every kernel batch with ``device_begin``/``device_end`` and
every host worker brackets its work with ``host_begin``/``host_end``;
an accumulator integrates the wall-clock during which the device was
busy AND at least one host worker was busy. ``overlap_ratio =
that / device_busy`` — 0 means the strict host→device ladder the
round-5 mesh curve flattened on, 1 means the device never waited
alone.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left


def build_info(backend: str = "", sched: str = "") -> dict:
    """The ``trivy_tpu_build_info`` identity labels (value-1 info
    gauge on /metrics, mirrored into the /healthz JSON): enough for
    a fleet scrape to tell replica versions apart mid-rolling-
    deploy. jax is resolved lazily and tolerated missing — metrics
    must render on a box with no accelerator stack at all."""
    from .. import __version__
    try:
        import jax
        jax_version = getattr(jax, "__version__", "")
    except Exception:   # noqa: BLE001 — any import-time failure
        jax_version = ""
    return {"version": __version__,
            "jax_version": jax_version,
            "backend": str(backend or ""),
            "sched": str(sched or "")}


class LatencyHistogram:
    """Fixed-bound latency histogram (seconds) with quantile
    estimates by linear interpolation inside the winning bucket.

    Bucket search is a bisect over ``BOUNDS`` (O(log n), not the
    linear scan the observe hot path used to pay), and the ladder
    starts at 100µs/250µs/500µs so device-phase latencies spread
    over real buckets instead of collapsing into the first one."""

    BOUNDS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
              0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
              30.0, 60.0)

    def __init__(self):
        self.counts = [0] * (len(self.BOUNDS) + 1)
        self.total = 0
        self.sum = 0.0
        self.max = 0.0
        # bucket index -> (exemplar id, value, unix seconds): the
        # most recent trace id observed into each bucket, attached
        # as an OpenMetrics exemplar so a slow-bucket scrape links
        # straight to a representative trace (obs/prom.py renders
        # them only on the openmetrics content type)
        self.exemplars: dict = {}

    def observe(self, v: float, exemplar: str = "") -> None:
        # bisect_left finds the first bound >= v, i.e. the same
        # bucket the old `v <= b` scan chose; values past the last
        # bound land in the overflow slot
        i = bisect_left(self.BOUNDS, v)
        self.counts[i] += 1
        self.total += 1
        self.sum += v
        if v > self.max:
            self.max = v
        if exemplar:
            self.exemplars[i] = (exemplar, v, time.time())

    def quantile(self, q: float) -> float:
        if not self.total:
            return 0.0
        target = q * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= target and c:
                lo = self.BOUNDS[i - 1] if i else 0.0
                hi = self.BOUNDS[i] if i < len(self.BOUNDS) \
                    else self.max
                frac = (target - seen) / c
                return lo + (hi - lo) * min(1.0, frac)
            seen += c
        return self.max

    def to_dict(self) -> dict:
        mean = self.sum / self.total if self.total else 0.0
        return {
            "count": self.total,
            "mean_s": round(mean, 6),
            "p50_s": round(self.quantile(0.50), 6),
            "p90_s": round(self.quantile(0.90), 6),
            "p99_s": round(self.quantile(0.99), 6),
            "max_s": round(self.max, 6),
        }

    def raw(self) -> dict:
        """The exposition shape (obs/prom.py): raw bucket counts
        plus the per-bucket exemplars."""
        return {"bounds": list(self.BOUNDS),
                "counts": list(self.counts),
                "sum": self.sum, "count": self.total,
                "exemplars": dict(self.exemplars)}


class SchedMetrics:
    """One instance per scheduler; every method is thread-safe."""

    PHASES = ("queue_wait", "analyze", "device", "finish", "request")

    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {
            "submitted": 0, "completed": 0, "failed": 0,
            "rejected": 0, "rate_limited": 0, "timed_out": 0,
            "cancelled": 0, "batches": 0,
        }
        self.hist = {p: LatencyHistogram() for p in self.PHASES}
        # coalescer accounting
        self._batch_items = 0
        self._batch_bytes = 0
        self._batch_jobs = 0
        self._bucket_bytes = 0        # padded byte capacity booked
        self._bucket_jobs = 0
        # overlap accounting: device_active is a COUNTER — the
        # async slot runtime keeps several dispatches in flight, and
        # device busy wall is the union of their windows, not the
        # (double-counting) sum
        self._host_active = 0
        self._device_active = 0
        self._device_since = None
        self._host_busy_s = 0.0
        self._device_busy_s = 0.0
        # per-dispatch device-time INTEGRAL (sum of every dispatch
        # window's wall, overlaps double-counted): the measured side
        # of the cost-attribution balance identity — the ledger
        # attributes each dispatch's wall across its requests, so
        # attributed totals must equal this integral, not the union
        # busy wall (obs/cost.py)
        self._device_time_s = 0.0
        self._overlap_s = 0.0
        self._both_since = None
        self._depth_fn = None         # live queue-depth gauge
        self._depth_max = 0
        self._started = time.monotonic()

    # --- counters / histograms ---

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            # lint: disable=unbounded-label-cardinality -- counter
            # names are code-literal call sites (batch_bisects,
            # quarantined, ...), never request-derived strings
            self.counters[name] = self.counters.get(name, 0) + n

    def observe(self, phase: str, seconds: float,
                trace_id: str = "") -> None:
        with self._lock:
            self.hist[phase].observe(seconds, exemplar=trace_id)

    def in_flight(self) -> int:
        """Admitted but unresolved requests (drain watches this)."""
        with self._lock:
            c = self.counters
            resolved = (c["completed"] + c["failed"] +
                        c["timed_out"] + c["cancelled"])
            return max(0, c["submitted"] - resolved)

    def set_depth_gauge(self, fn) -> None:
        self._depth_fn = fn

    def note_depth(self, depth: int) -> None:
        with self._lock:
            if depth > self._depth_max:
                self._depth_max = depth

    # --- coalescer accounting ---

    def note_batch(self, items: int, cand_bytes: int, jobs: int,
                   bucket_bytes: int, bucket_jobs: int) -> None:
        with self._lock:
            self.counters["batches"] += 1
            self._batch_items += items
            self._batch_bytes += cand_bytes
            self._batch_jobs += jobs
            self._bucket_bytes += bucket_bytes
            self._bucket_jobs += bucket_jobs

    # --- overlap accounting ---

    def _update_both(self, now: float) -> None:
        both = self._device_active > 0 and self._host_active > 0
        if both and self._both_since is None:
            self._both_since = now
        elif not both and self._both_since is not None:
            self._overlap_s += now - self._both_since
            self._both_since = None

    def host_begin(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._host_active += 1
            self._update_both(now)
        return now

    def host_end(self, t0: float) -> None:
        now = time.monotonic()
        with self._lock:
            self._host_active -= 1
            self._host_busy_s += now - t0
            self._update_both(now)

    def device_begin(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._device_active += 1
            if self._device_active == 1:
                self._device_since = now
            self._update_both(now)
        return now

    def device_end(self, t0: float) -> float:
        now = time.monotonic()
        with self._lock:
            self._device_active -= 1
            self._device_time_s += now - t0
            if self._device_active == 0 and \
                    self._device_since is not None:
                # union accounting: busy wall accrues only when the
                # LAST overlapping dispatch window closes
                self._device_busy_s += now - self._device_since
                self._device_since = None
            self._update_both(now)
        # this dispatch's own wall — the executor attributes it
        # across the batch's requests (obs/cost.py)
        return now - t0

    def device_time_s(self) -> float:
        """The per-dispatch device-time integral so far."""
        with self._lock:
            return self._device_time_s

    # --- snapshot ---

    def hist_snapshot(self) -> dict:
        """Raw bucket counts per phase for Prometheus exposition
        (trivy_tpu/obs/prom.py) — the JSON snapshot only carries the
        derived quantiles."""
        with self._lock:
            return {p: h.raw() for p, h in self.hist.items()}

    def snapshot(self) -> dict:
        # the live queue-depth gauge is called OUTSIDE self._lock:
        # it takes the scheduler queue's lock, so calling it under
        # the (non-reentrant) metrics lock imposes a metrics→queue
        # lock order on every gauge implementation — and deadlocks
        # outright on a gauge that consults the metrics
        depth_fn = self._depth_fn
        depth = depth_fn() if depth_fn else 0
        with self._lock:
            now = time.monotonic()
            overlap = self._overlap_s
            if self._both_since is not None:
                overlap += now - self._both_since
            batches = self.counters["batches"]
            occupancy = (
                self._batch_bytes / self._bucket_bytes
                if self._bucket_bytes else
                (self._batch_jobs / self._bucket_jobs
                 if self._bucket_jobs else 0.0))
            padding_waste = 1.0 - occupancy if batches else 0.0
            out = {
                "counters": dict(self.counters),
                "queue_depth": depth,
                "queue_depth_max": self._depth_max,
                "batch": {
                    "count": batches,
                    "items_total": self._batch_items,
                    "mean_items": round(
                        self._batch_items / batches, 2)
                    if batches else 0.0,
                    "candidate_bytes": self._batch_bytes,
                    "interval_jobs": self._batch_jobs,
                    "bucket_bytes": self._bucket_bytes,
                    "bucket_jobs": self._bucket_jobs,
                    "occupancy": round(occupancy, 4),
                    "padding_waste": round(padding_waste, 4),
                },
                "host_busy_s": round(self._host_busy_s, 4),
                "device_busy_s": round(self._device_busy_s, 4),
                "device_time_s": round(self._device_time_s, 6),
                "overlap_s": round(overlap, 4),
                "overlap_ratio": round(
                    overlap / self._device_busy_s, 4)
                if self._device_busy_s else 0.0,
                "uptime_s": round(now - self._started, 2),
                "latency": {p: h.to_dict()
                            for p, h in self.hist.items()},
            }
        # dispatch-ring accounting (runtime/ring.py): current/max
        # dispatch depth, slot occupancy, and the overlap ratio the
        # async runtime buys — process-wide like the guard totals,
        # so sched-off direct scans report it too
        from ..runtime.ring import RING_METRICS
        out["dispatch"] = RING_METRICS.snapshot()
        # ingest-guard counters (trivy_tpu/guard): process-wide by
        # design — budgets are per-target and short-lived, the trip
        # totals are what an operator watches on /metrics
        from ..guard.budget import GUARD_METRICS
        out["guard"] = GUARD_METRICS.snapshot()
        # dispatch-path counters (docs/performance.md): job dedup,
        # constraint/purl cache hit rates, resident-DB upload
        # amortization — process-wide, like the guard totals
        from ..detect.metrics import DETECT_METRICS
        out["detect"] = DETECT_METRICS.snapshot()
        # secret-sieve counters (docs/performance.md "DFA engine"):
        # selectivity, verify tail, on-device vs host-fallback file
        # counts, DFA table upload amortization
        from ..secret.metrics import SECRET_METRICS
        out["secret"] = SECRET_METRICS.snapshot()
        # device-residency accounting: live HBM bytes + generation
        # per (table, placement) — advisory DB and DFA band alike
        # (trivy_tpu_resident_bytes on /metrics)
        from ..db.compiled import resident_snapshot
        out["resident"] = resident_snapshot()
        # findings-memo counters (docs/performance.md "Findings
        # memoization"): hit/miss/store/invalidation totals plus the
        # delta re-match accounting — process-wide like the rest
        from ..memo.metrics import MEMO_METRICS
        out["memo"] = MEMO_METRICS.snapshot()
        # watch/admission counters (docs/serving.md "Continuous
        # scanning & admission control"): push-event dispositions,
        # event lag, admission verdicts — process-wide singletons
        from ..watch.metrics import WATCH_METRICS
        out["watch"] = WATCH_METRICS.snapshot()
        return out
