"""The continuous-batching scan scheduler.

Topology (docs/serving.md has the full picture)::

    sources (RPC Scan / CLI fleet) ──submit──▶ AdmissionQueue
        │ intake thread (deadline sweep)
        ▼
    host worker pool ──analyze()──▶ Coalescer (volume buckets)
        │ device executor thread (one, serializes kernel work)
        ▼
    sieve dispatch ─▶ interval dispatch ─▶ sieve collect
        │ per-request finish() back on the worker pool
        ▼
    request futures resolve

The device executor owns ALL kernel dispatch, so device work is
serialized (one XLA stream, no interleaved compilation); the worker
pool runs every host phase. While the device chews batch N, the pool
analyzes batch N+1 and assembles batch N-1 — the host/device overlap
the round-5 mesh curve lacked. Iteration-level scheduling à la
Orca/vLLM: requests join whichever batch is forming when their host
analysis lands, not the batch they arrived with.

Async slot runtime (docs/performance.md §8): the executor LAUNCHES
each coalesced batch — segment pack, ``jax.device_put`` staging,
non-blocking donated-kernel enqueue — into a bounded dispatch ring
(``SchedConfig.dispatch_depth``, default 2) and immediately takes
the next batch, so batch N+1 packs and uploads while batch N
computes. The ring's drain thread COLLECTS slots in FIFO order
(materialize → decode → patch → finish fan-out); a full ring parks
the executor under a typed ``slot_wait`` span. Occupancy feedback:
when nothing is queued, analyzing, or pending coalesce, the
effective depth shrinks to 1 — an interactive admission verdict
never waits behind a speculative batch. A slot whose launch or
collect fails falls back to the synchronous bisect/quarantine
ladder, so poison isolation is unchanged.

Cross-request consistency: two concurrent requests can share a layer
blob (fleets share file trees). A request that analyzed a layer will
patch that blob's secrets only when its batch's sieve resolves; any
OTHER request whose final merge reads that blob must wait for the
patch. The scheduler tracks pending blob writes and hands each
request the set of patch events it depends on — the device thread
alone resolves them, so there is no cycle to deadlock on.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..obs.cost import COST_LEDGER, parse_budget_config
from ..obs.trace import get_tracer, trace_cause
from ..utils import get_logger
from .coalescer import Batch, Coalescer, SchedConfig
from .metrics import SchedMetrics
from .queue import (DeadlineExceeded, QueueFullError,
                    RequestCancelled, ScanRequest, SchedulerClosed)
from .tenant import RateLimitedError, TenantQueue

log = get_logger("sched")


def _annotate_degraded(result, faults: list):
    """Thread the request's survived faults into whatever shape the
    finish callable produced: objects expose ``apply_degraded``
    (BatchScanResult), RPC responses are plain dicts, anything else
    passes through unannotated (the caller still got a result)."""
    mark = getattr(result, "apply_degraded", None)
    if mark is not None:
        mark(faults)
    elif isinstance(result, dict):
        result["status"] = "degraded"
        result["failure_causes"] = [dict(f) for f in faults]
    return result


class ScanScheduler:
    """Owns the queue, the coalescer, the worker pool, and the
    device executor. One instance per process serves every request
    source; ``group`` keys keep incompatible dispatches apart."""

    def __init__(self, config: Optional[SchedConfig] = None,
                 backend: str = "tpu", mesh=None,
                 secret_scanner=None, tracer=None, slo=None):
        self.config = config or SchedConfig()
        self.backend = backend
        self.mesh = mesh
        self.secret_scanner = secret_scanner
        # fault_injector: optional trivy_tpu.faults.FaultInjector —
        # consulted at the top of every device dispatch so injected
        # device failures exercise the bisect/quarantine machinery
        self.fault_injector = None
        # tracer: trivy_tpu.obs.Tracer — every admitted request gets
        # a root span with per-stage children (docs/observability.md)
        self.tracer = tracer if tracer is not None else get_tracer()
        # slo: trivy_tpu.obs.SloEngine — burn-rate verdicts over the
        # admitted-request outcomes (GET /slo, trivy_tpu_slo_*
        # gauges); a tripped burn rate auto-dumps its worst recent
        # traces through this tracer's flight recorder. Pass a
        # configured engine (--slo-config) or let the defaults ride.
        if slo is None:
            from ..obs.slo import SloEngine, parse_slo_config
            cfg_slos = getattr(self.config, "slos", None)
            if cfg_slos is not None:
                # accept the --slo-config string grammar here too —
                # one parser, and a typo'd objective fails with its
                # ValueError instead of an AttributeError deep in
                # SloEngine
                cfg_slos = parse_slo_config(cfg_slos)
            slo = SloEngine(cfg_slos,
                            recorder=self.tracer.recorder)
        self.slo = slo
        self.metrics = SchedMetrics()
        # tenancy-aware admission (sched/tenant.py): with the default
        # (no TenancyConfig) this is exactly the old bounded FIFO —
        # one unlimited anonymous tenant
        self.queue = TenantQueue(self.config.max_queue,
                                 tenancy=getattr(self.config,
                                                 "tenancy", None))
        # per-tenant device-second budgets (--tenant-budget,
        # obs/cost.py): admission consults the windowed cost ledger
        # and throttles (429) or deprioritizes over-budget tenants
        budgets = getattr(self.config, "budgets", None)
        if budgets:
            self.queue.configure_budgets(
                parse_budget_config(budgets), COST_LEDGER)
        self.metrics.set_depth_gauge(self.queue.depth)
        self.coalescer = Coalescer(self.config)
        # dispatch ring (runtime/ring.py): bounds launched-but-
        # uncollected device slots and owns the collect drain thread
        from ..runtime.ring import DispatchRing
        self.ring = DispatchRing(
            depth=max(1, getattr(self.config, "dispatch_depth", 2)),
            name="sched")
        self._pool: Optional[ThreadPoolExecutor] = None
        self._threads: list = []
        self._cv = threading.Condition()
        self._analyzing = 0
        self._kernel_s = 0.0      # interval-kernel wall (all batches)
        # monotonic end of the last metered device dispatch — the
        # demand-gated idle baseline (goodput: device time between
        # "work was ready" and "dispatch started" is waste)
        self._last_device_end = None
        self._running = False
        self._draining = False
        self._batch_seq = 0       # device-thread only (batch ids)
        self._lock = threading.Lock()
        # blob id → patch event of the request that will write it
        self._blob_lock = threading.Lock()
        self._pending_blobs: dict = {}

    # --- lifecycle ---

    def start(self) -> "ScanScheduler":
        with self._lock:
            if self._running:
                return self
            if self.queue.closed:
                # a closed scheduler never revives — restarting the
                # threads against a permanently closed queue would
                # only leak them
                raise SchedulerClosed("scheduler is closed")
            self._running = True
            self._pool = ThreadPoolExecutor(
                max_workers=max(1, self.config.workers),
                thread_name_prefix="sched-host")
            for name, fn in (("sched-intake", self._intake_loop),
                             ("sched-device", self._device_loop)):
                t = threading.Thread(target=fn, name=name,
                                     daemon=True)
                t.start()
                self._threads.append(t)
        return self

    def close(self, wait: bool = True) -> None:
        with self._lock:
            if not self._running:
                return
            self._running = False
        self.queue.close()
        with self._cv:
            self._cv.notify_all()
        # anything not yet handed to the device fails typed
        while True:
            req = self.queue.get(timeout=0)
            if req is None:
                break
            self._fail(req, SchedulerClosed("scheduler closed"))
        for req in self.coalescer.drain():
            self._fail(req, SchedulerClosed("scheduler closed"))
        # drain the dispatch ring BEFORE the pool stops: in-flight
        # device slots complete (a deadline never cancels device
        # work already launched), their patches land, and their
        # finish tasks still find a live pool to run on — collected
        # even on wait=False, because an abandoned slot's requests
        # would never resolve
        self.ring.close(collect=True)
        if self._pool is not None:
            self._pool.shutdown(wait=wait)
        # a second drain AFTER the pool settles: an _analyze that was
        # mid-flight during the first drain may have added its
        # request to the coalescer since — without this, that future
        # would never resolve (and an RPC adapter's on_done release
        # would never run)
        for req in self.coalescer.drain():
            self._fail(req, SchedulerClosed("scheduler closed"))
        for t in self._threads:
            t.join(timeout=5 if wait else 0)
        self._threads = []

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown: refuse new admissions (submit raises
        SchedulerClosed, which the RPC layer answers 503), let the
        queued and in-flight requests run to completion, then close.
        Returns True when everything drained inside the timeout."""
        with self._lock:
            if not self._running:
                return True
            self._draining = True
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            if self.metrics.in_flight() == 0 \
                    and self.queue.depth() == 0 \
                    and self.coalescer.pending() == 0:
                with self._cv:
                    if self._analyzing == 0:
                        break
            time.sleep(0.02)
        drained = self.metrics.in_flight() == 0
        self.close()
        return drained

    def __enter__(self) -> "ScanScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # --- submission ---

    def submit(self, request: ScanRequest,
               block: bool = False) -> ScanRequest:
        """Admit one request. Raises QueueFullError (backpressure)
        unless ``block``, SchedulerClosed after close()."""
        if self._draining:
            raise SchedulerClosed("scheduler draining")
        if not self._running:
            self.start()
        if request.deadline is None and \
                self.config.default_deadline_s > 0:
            request.deadline = (request.submitted_at +
                                self.config.default_deadline_s)
        request.group = request.group or self.backend
        root = self.tracer.start_request(
            request.name, trace_id=request.trace_id,
            parent_span_id=getattr(request, "parent_span_id", ""))
        request.trace_id = root.trace_id
        request.span_root = root
        request.span_queue = self.tracer.child(root, "queue_wait")
        try:
            self.queue.put(request, block=block)
        except (QueueFullError, RateLimitedError) as e:
            self.metrics.inc("rejected")
            if isinstance(e, RateLimitedError):
                self.metrics.inc("rate_limited")
            # "rejected", not "failed": a backpressure 503/429
            # carries no diagnostic value, and the tracer only
            # crash-dumps degraded/failed traces — a rejection storm
            # (including a tenant flood's 429s) must never become a
            # disk-write storm
            request.span_queue.end("error")
            root.end("rejected")
            raise
        except SchedulerClosed:
            request.span_queue.end("error")
            root.end("rejected")
            raise
        self.metrics.inc("submitted")
        self.metrics.note_depth(self.queue.depth())
        with self._cv:
            self._cv.notify_all()
        return request

    def in_flight(self) -> int:
        """Admitted-but-unresolved requests. Open-loop submitters
        (the watch loop's in-flight watermarks, docs/serving.md
        "Continuous scanning") poll this instead of reaching into
        the metrics object."""
        return self.metrics.in_flight()

    def stats(self) -> dict:
        out = self.metrics.snapshot()
        out["config"] = {
            "max_queue": self.config.max_queue,
            "workers": self.config.workers,
            "flush_timeout_s": self.config.flush_timeout_s,
            "max_batch_bytes": self.config.max_batch_bytes,
            "max_batch_jobs": self.config.max_batch_jobs,
            "max_batch_items": self.config.max_batch_items,
        }
        out["backend"] = self.backend
        out["draining"] = self._draining
        # per-tenant fairness/QoS books (docs/serving.md
        # "Multi-tenant QoS"): queue depth, in-flight, admission and
        # shed counters, latency quantiles — the autoscaling signal
        out["tenants"] = self.queue.tenant_snapshot()
        # SLO verdicts (obs/slo.py): burn rates over the outcome
        # stream — the autoscaling/alerting signal GET /slo serves
        out["slo"] = self.slo.snapshot()
        with self._lock:
            out["interval_kernel_s"] = round(self._kernel_s, 4)
        # per-tenant cost books + the goodput reconciliation
        # (docs/observability.md "Cost attribution & goodput")
        out["cost"] = self.cost_snapshot()
        return out

    def cost_snapshot(self) -> dict:
        """The cost plane's replica-local view: per-tenant ledger
        (AOT compile wall amortized by device-second share), the
        measured per-dispatch device-time integral, and the
        accounting-identity verdict — served at ``GET /costs`` and
        inside ``stats()["cost"]``."""
        from ..obs.cost import balance
        from ..runtime.aot import COMPILE_CACHE_METRICS
        aot = COMPILE_CACHE_METRICS.snapshot()
        ledger = COST_LEDGER.snapshot(
            aot_compile_s=float(aot.get("seconds", 0.0) or 0.0))
        measured = self.metrics.device_time_s()
        out = dict(ledger)
        out["measured_device_s"] = round(measured, 6)
        out["balance"] = balance(ledger.get("device_s", 0.0),
                                 measured)
        return out

    # --- cross-request blob dependencies (called from analyze) ---

    def register_blob_writes(self, blob_ids: list,
                             request: ScanRequest) -> None:
        """This request's sieve results will patch these cache
        blobs; requests reading them must wait for the patch."""
        with self._blob_lock:
            for b in blob_ids:
                self._pending_blobs[b] = request.patched_event
        request._registered_blobs = list(blob_ids)

    def blob_deps(self, blob_ids: list,
                  request: ScanRequest) -> list:
        """Patch events (other requests') this request's final
        secret merge depends on."""
        with self._blob_lock:
            out = []
            for b in blob_ids:
                ev = self._pending_blobs.get(b)
                if ev is not None and \
                        ev is not request.patched_event:
                    out.append(ev)
            return out

    def _clear_blob_writes(self, request: ScanRequest) -> None:
        blobs = getattr(request, "_registered_blobs", ())
        with self._blob_lock:
            for b in blobs:
                if self._pending_blobs.get(b) is \
                        request.patched_event:
                    del self._pending_blobs[b]

    # --- resolution helpers ---

    def _end_trace(self, req: ScanRequest, status: str,
                   err=None) -> None:
        """Close the request's span tree: any stage span still open
        (a failure can resolve the request mid-stage), then the
        root — which completes the trace (flight-recorder ring,
        export, degraded-dump) in the tracer."""
        root = req.span_root
        if root is None or root.noop:
            return
        for name in ("span_queue", "span_coalesce"):
            sp = getattr(req, name, None)
            if sp is not None:
                sp.end("error" if status == "failed" else None)
        if err is not None:
            root.set("error", repr(err))
        if req.faults:
            root.set("faults", len(req.faults))
        root.end(status)

    def _note_slo(self, req: ScanRequest, outcome: str,
                  latency: float) -> None:
        self.slo.record(outcome, latency_s=latency,
                        tenant=getattr(req, "tenant", "") or "",
                        priority=int(getattr(req, "priority", 0)
                                     or 0),
                        trace_id=req.trace_id or "")

    def _complete(self, req: ScanRequest, result) -> None:
        self._clear_blob_writes(req)
        if req.set_result(result):
            latency = time.monotonic() - req.submitted_at
            self.metrics.inc("completed")
            self.metrics.observe("request", latency,
                                 trace_id=req.trace_id or "")
            COST_LEDGER.charge(getattr(req, "tenant", "") or "",
                               requests=1)
            status = "degraded" if req.faults else "ok"
            self.queue.note_done(req, status, latency)
            self._end_trace(req, status)
            self._note_slo(req, status, latency)

    def _fail(self, req: ScanRequest, err: BaseException) -> None:
        self._clear_blob_writes(req)
        if req.set_error(err):
            latency = time.monotonic() - req.submitted_at
            if isinstance(err, DeadlineExceeded):
                outcome = "timed_out"
            elif isinstance(err, RequestCancelled):
                outcome = "cancelled"
            else:
                outcome = "failed"
            self.metrics.inc(outcome)
            self.queue.note_done(req, outcome)
            self._end_trace(req, "failed", err)
            self._note_slo(req, outcome, latency)

    def _sweep(self, req: ScanRequest) -> bool:
        """True if the request is dead (expired/cancelled) and was
        resolved here."""
        if req.cancelled:
            self._fail(req, RequestCancelled(
                f"scan {req.name!r}: cancelled"))
            return True
        if req.expired():
            self._fail(req, DeadlineExceeded(
                f"scan {req.name!r}: deadline exceeded"))
            return True
        return False

    # --- stage 1: intake + host analyze ---

    def _intake_loop(self) -> None:
        # the admission queue is the ONLY wait buffer: intake stops
        # pulling once the pool has a small prefetch window in
        # flight, so a saturated pool backs pressure up into the
        # bounded queue (and from there into typed 503s) instead of
        # an unbounded executor backlog
        prefetch = max(2, self.config.workers * 2)
        while self._running:
            with self._cv:
                while self._running and self._analyzing >= prefetch:
                    self._cv.wait(0.05)
            if not self._running:
                break
            req = self.queue.get(timeout=0.05)
            if req is None:
                continue
            if req.span_queue is not None:
                req.span_queue.end()
            self.metrics.observe(
                "queue_wait", time.monotonic() - req.submitted_at,
                trace_id=req.trace_id or "")
            if self._sweep(req):
                continue
            with self._cv:
                self._analyzing += 1
            try:
                self._pool.submit(self._analyze, req)
            except RuntimeError:     # pool shut down under us
                with self._cv:
                    self._analyzing -= 1
                self._fail(req, SchedulerClosed("scheduler closed"))

    def _analyze(self, req: ScanRequest) -> None:
        t0 = self.metrics.host_begin()
        sp = self.tracer.child(req.span_root, "analyze")
        try:
            if not self._sweep(req):
                with sp.activate():
                    req.work = req.analyze(req)
                req.work.group = req.work.group or req.group
                sp.end()
                # the coalesce span opens BEFORE the request is
                # published to the device thread, which closes it
                # when the batch flushes
                req.span_coalesce = self.tracer.child(
                    req.span_root, "coalesce")
                self.coalescer.add(req)
            else:
                sp.end("error")
        except Exception as e:       # noqa: BLE001
            sp.end("error")
            log.warning("analyze %r failed: %r", req.name, e)
            self._fail(req, e)
        finally:
            self.metrics.host_end(t0)
            host_s = time.monotonic() - t0
            self.metrics.observe("analyze", host_s,
                                 trace_id=req.trace_id or "")
            work = getattr(req, "work", None)
            COST_LEDGER.charge(
                getattr(req, "tenant", "") or "",
                host_analyze_s=host_s,
                bytes_in=float(getattr(work, "candidate_bytes", 0)
                               or 0))
            with self._cv:
                self._analyzing -= 1
                self._cv.notify_all()

    # --- stage 2: device executor ---

    def _upstream_idle(self) -> bool:
        return self.queue.depth() == 0 and self._analyzing == 0

    def _device_loop(self) -> None:
        wait_s = min(0.1, max(0.005,
                              self.config.flush_timeout_s / 2))
        while self._running:
            group = self.coalescer.ready_group(self._upstream_idle())
            if group is None:
                with self._cv:
                    self._cv.wait(wait_s)
                continue
            batch = self.coalescer.take(group)
            if batch is None or not batch.requests:
                continue
            try:
                self._execute(batch)
            except Exception as e:   # noqa: BLE001
                log.warning("batch execution failed: %r", e)
                for r in batch.requests:
                    self._fail(r, e)
        # drain on shutdown
        for req in self.coalescer.drain():
            self._fail(req, SchedulerClosed("scheduler closed"))

    def _effective_depth(self) -> int:
        """Occupancy feedback for the dispatch ring: the configured
        depth while work is queued/analyzing/pending (speculative
        batches pay for themselves), shrunk to 1 when the pipeline
        upstream is empty — the next request to arrive gets the
        device as soon as the current batch drains, not after a
        speculative slot ahead of it."""
        cfg = max(1, getattr(self.config, "dispatch_depth", 2))
        if cfg > 1 and self._upstream_idle() \
                and self.coalescer.pending() == 0:
            return 1
        return cfg

    def _execute(self, batch: Batch) -> None:
        from ..runtime.ring import RingClosed
        reqs = [r for r in batch.requests if not self._sweep(r)]
        if not reqs:
            return
        self.metrics.note_batch(
            len(reqs), batch.candidate_bytes, batch.jobs,
            batch.bucket_bytes, batch.bucket_jobs)

        self._batch_seq += 1
        bid = self._batch_seq
        occ = round(batch.occupancy, 4)
        for r in reqs:
            sp = r.span_coalesce
            if sp is not None:
                if not sp.noop:
                    sp.set("batch", bid)
                    sp.set("items", len(reqs))
                    sp.set("bucket_bytes", batch.bucket_bytes)
                    sp.set("bucket_jobs", batch.bucket_jobs)
                    sp.set("occupancy", occ)
                sp.end()

        group = batch.group or self.backend
        try:
            # capacity first, then launch (pack + upload + enqueue)
            # on THIS thread, collect on the ring's drain thread —
            # the loop takes the next batch while this one computes.
            # The first request's root is active around the submit
            # so a ring-full park records its slot_wait span (the
            # timeline charges the stall to the batch it delayed)
            import contextlib
            root = reqs[0].span_root
            ctx = root.activate() if root is not None \
                and not root.noop else contextlib.nullcontext()
            launched: dict = {}

            def _do_launch():
                launched["slot"] = self._launch(reqs, group, bid)
                return launched["slot"]

            with ctx:
                self.ring.submit(
                    self._collect_slot,
                    depth=self._effective_depth(),
                    label=f"batch:{bid}",
                    launch=_do_launch)
        except RingClosed:
            slotp = launched.get("slot")
            if slotp is not None:
                # the ring closed between a SUCCESSFUL launch and
                # the slot append: device work is already enqueued,
                # so collect it inline — spans end, payload tags
                # restore, device accounting balances, and the
                # close(collect=True) "in-flight work completes"
                # contract holds
                self._collect_slot(slotp)
            else:
                for r in reqs:
                    self._fail(r,
                               SchedulerClosed("scheduler closed"))
        except Exception as e:       # noqa: BLE001 — a failed
            # launch (fault injection fires at dispatch, packing
            # errors) falls back to the synchronous isolated ladder:
            # bisect corners the poison exactly as before
            log.warning("async launch failed for %d requests "
                        "(%r); synchronous fallback", len(reqs), e)
            results = self._dispatch_isolated(reqs, group,
                                              batch_id=bid)
            self._resolve_batch(reqs, results)

    def _launch(self, reqs: list, group: str, bid: int) -> dict:
        """Non-blocking half of one batch dispatch: flatten + tag
        payloads, enqueue the sieve and the interval waves (donated
        per-batch buffers), return the slot payload the drain thread
        collects. Raises on launch failure with payload tags
        restored and device spans error-ended."""
        from ..detect.batch import dispatch_jobs_async

        spans = []
        for r in reqs:
            sp = self.tracer.child(r.span_root, "device")
            if not sp.noop:
                sp.set("batch", bid)
                sp.set("requests", len(reqs))
            spans.append(sp)
        slot = {"reqs": reqs, "spans": spans, "group": group,
                "bid": bid, "wrapped": [], "owner": [],
                "local": [], "sieve": None, "ih": None,
                "kstats": {}, "t0": None}
        try:
            if self.fault_injector is not None:
                self.fault_injector.on_device_dispatch(
                    [r.name for r in reqs])

            # flatten sieve candidates; owner map brings results
            # home by ENTRY INDEX (paths repeat across images — see
            # secret.batch)
            files = []
            for i, r in enumerate(reqs):
                for j, (path, content) in enumerate(
                        r.work.candidates):
                    files.append((path, content))
                    slot["owner"].append(i)
                    slot["local"].append(j)

            # payloads are tagged with the request's batch index for
            # the duration of the dispatch and restored at collect —
            # a failed slot restores before the sync fallback
            # re-tags against its own indices
            for i, r in enumerate(reqs):
                for job in r.work.jobs:
                    slot["wrapped"].append((job, job.payload))
                    job.payload = (i, job.payload)

            slot["t0"] = self.metrics.device_begin()
            # batch-shared phases (segment pack, H2D staging, wave
            # enqueue) record under the FIRST request's device span
            with spans[0].activate():
                if files and self.secret_scanner is not None:
                    slot["sieve"] = \
                        self.secret_scanner.dispatch_files(files)
                all_jobs = [job for job, _ in slot["wrapped"]]
                if all_jobs:
                    slot["ih"] = dispatch_jobs_async(
                        all_jobs, backend=group, mesh=self.mesh,
                        stats=slot["kstats"])
            return slot
        except Exception as e:       # noqa: BLE001
            self._unwind_slot(slot, error=e)
            raise

    def _meter_dispatch(self, reqs: list, t0, wall_s: float,
                        kstats: dict, sieved: bool) -> None:
        """Book one device dispatch's wall into the cost plane:

        * goodput — the dispatch wall is useful device time; the gap
          between this dispatch's start and max(previous dispatch
          end, earliest submit in the batch) is DEMAND-GATED idle
          (the device sat while admitted work waited) — both feed
          every ``kind=efficiency`` SLO book;
        * attribution — the wall splits by kernel family (the
          interval bucket-ladder's measured ``device_s`` vs the DFA
          sieve remainder) and lands on each request's tenant
          proportionally to its work volume (candidate bytes +
          interval jobs), so per-tenant books sum back to the
          measured dispatch integral by construction.

        Called on every path that closed a device_begin — success,
        unwind, and the sync bisect ladder — so failed dispatches
        are billed too and the identity holds through quarantine."""
        if t0 is None or not reqs:
            return
        wall_s = max(0.0, wall_s)
        gate = min((r.submitted_at for r in reqs), default=t0)
        with self._lock:
            if self._last_device_end is not None:
                gate = max(gate, self._last_device_end)
                idle_s = max(0.0, t0 - gate)
            else:
                # first dispatch of the process: warm-up, not waste
                idle_s = 0.0
            end = t0 + wall_s
            if self._last_device_end is None \
                    or end > self._last_device_end:
                self._last_device_end = end
        self.slo.record_device(wall_s, idle_s=idle_s)
        if not COST_LEDGER.enabled:
            return
        interval_s = min(wall_s, max(0.0, float(
            (kstats or {}).get("device_s", 0.0) or 0.0)))
        if sieved:
            dfa_s = wall_s - interval_s
        else:
            # no sieve in this dispatch: the whole wall is the
            # interval ladder (enqueue + materialize included)
            interval_s, dfa_s = wall_s, 0.0
        weights = []
        for r in reqs:
            w = getattr(r, "work", None)
            weights.append(
                float(getattr(w, "candidate_bytes", 0) or 0)
                + float(len(getattr(w, "jobs", ()) or ())))
        total_w = sum(weights)
        n = len(reqs)
        for r, w in zip(reqs, weights):
            share = (w / total_w) if total_w > 0 else (1.0 / n)
            COST_LEDGER.charge(
                getattr(r, "tenant", "") or "",
                device_interval_s=interval_s * share,
                device_dfa_s=dfa_s * share)

    def _unwind_slot(self, slot: dict, error=None) -> None:
        """Restore payload tags + close accounting for a slot that
        will not produce results itself (launch/collect failure —
        the sync fallback re-dispatches from a clean state)."""
        for job, orig in slot["wrapped"]:
            job.payload = orig
        if slot["t0"] is not None:
            wall = self.metrics.device_end(slot["t0"])
            self._meter_dispatch(slot["reqs"], slot["t0"], wall,
                                 slot["kstats"],
                                 slot["sieve"] is not None)
        for sp in slot["spans"]:
            if error is not None:
                sp.event("device_failed", error=repr(error))
            sp.end("error" if error is not None else None)

    def _collect_slot(self, slot: dict) -> None:
        """Drain-thread half: materialize the interval waves (the
        device wall passes here), collect the sieve, then patch +
        finish fan-out. A collect failure falls back to the
        synchronous bisect/quarantine ladder."""
        from ..detect.batch import collect_dispatch

        reqs = slot["reqs"]
        spans = slot["spans"]
        try:
            with spans[0].activate():
                detected_by: dict = {}
                if slot["ih"] is not None:
                    for i, payload in collect_dispatch(slot["ih"]):
                        detected_by.setdefault(i, []).append(
                            payload)
                    with self._lock:
                        self._kernel_s += slot["kstats"].get(
                            "device_s", 0.0)
                found_by: dict = {}
                if slot["sieve"] is not None:
                    for idx, secret in self.secret_scanner.collect(
                            slot["sieve"]):
                        found_by.setdefault(
                            slot["owner"][idx], []).append(
                            (slot["local"][idx], secret))
        except Exception as e:       # noqa: BLE001
            log.warning("slot collect failed for %d requests "
                        "(%r); synchronous fallback", len(reqs), e)
            self._unwind_slot(slot, error=e)
            results = self._dispatch_isolated(
                reqs, slot["group"], batch_id=slot["bid"])
            self._resolve_safe(reqs, results)
            return
        for job, orig in slot["wrapped"]:
            job.payload = orig
        wall = self.metrics.device_end(slot["t0"])
        self._meter_dispatch(reqs, slot["t0"], wall,
                             slot["kstats"],
                             slot["sieve"] is not None)
        self.metrics.observe("device",
                             time.monotonic() - slot["t0"],
                             trace_id=reqs[0].trace_id or "")
        for sp in spans:
            sp.end()
        results = {id(r): (found_by.get(i, []),
                           detected_by.get(i, []))
                   for i, r in enumerate(reqs)}
        self._resolve_safe(reqs, results)

    def _resolve_safe(self, reqs: list, results: dict) -> None:
        """_resolve_batch, but a raising resolution can never leak a
        request: on the drain thread nobody reads the slot's error
        (results flow through the requests themselves), so anything
        unresolved fails typed here."""
        try:
            self._resolve_batch(reqs, results)
        except Exception as e:       # noqa: BLE001
            log.warning("batch resolution failed: %r", e)
            for r in reqs:
                self._fail(r, e)

    def _resolve_batch(self, reqs: list, results: dict) -> None:
        # patch + event-set happen HERE, on the collecting thread
        # (ring drain, or the executor on the sync fallback), so
        # every patch event is resolved without touching the worker
        # pool — a finish waiting on another request's patch can
        # never starve the work that would satisfy it
        for r in reqs:
            out = results.get(id(r))
            if out is None:
                continue             # quarantine already failed it
            if self._sweep(r):
                # the deadline passed while the batch ran on device:
                # the collect is abandoned (sweep resolved it 408)
                self.metrics.inc("expired_inflight")
                continue
            found, detected = out
            try:
                if r.work.patch is not None:
                    r.work.patch(found)
            except Exception as e:   # noqa: BLE001
                log.warning("patch %r failed: %r", r.name, e)
                self._fail(r, e)
                continue
            r.patched_event.set()
            self._clear_blob_writes(r)
            try:
                self._pool.submit(self._finish, r, found, detected)
            except RuntimeError:     # pool shut down under us
                self._fail(r, SchedulerClosed("scheduler closed"))

    # --- poison-image isolation (docs/robustness.md) ---

    def _dispatch(self, reqs: list, group: str, depth: int = 0,
                  batch_id: int = 0,
                  attempt: str = "batch") -> dict:
        """One coalesced device dispatch over ``reqs`` →
        ``{id(req): (sieve_found, detected)}``. Raises on device
        failure — isolation happens in _dispatch_isolated. Every
        request gets a ``device`` span per attempt, so bisect halves
        and quarantine retries appear as sibling spans in the
        trace."""
        from ..detect.batch import dispatch_jobs

        spans = []
        for r in reqs:
            sp = self.tracer.child(r.span_root, "device")
            if not sp.noop:
                sp.set("batch", batch_id)
                sp.set("requests", len(reqs))
                if depth:
                    sp.set("bisect_depth", depth)
                if attempt != "batch":
                    sp.set("attempt", attempt)
            spans.append(sp)
        try:
            if self.fault_injector is not None:
                self.fault_injector.on_device_dispatch(
                    [r.name for r in reqs])

            # the batch-shared phases (segment packing, H2D upload,
            # resident-DB staging) record their pack/h2d_upload/
            # db_upload spans under the FIRST request's device span
            # — they happen once per batch, not once per request
            batch_ctx = spans[0].activate()

            # flatten sieve candidates; owner map brings results
            # home by ENTRY INDEX (paths repeat across images — see
            # secret.batch)
            files, owner, local = [], [], []
            for i, r in enumerate(reqs):
                for j, (path, content) in enumerate(
                        r.work.candidates):
                    files.append((path, content))
                    owner.append(i)
                    local.append(j)

            # payloads are tagged with the request's batch index for
            # the duration of the dispatch and restored after — a
            # bisect retry re-tags against ITS OWN indices, so a
            # failed dispatch must never leave its wrapping behind
            wrapped = []
            for i, r in enumerate(reqs):
                for job in r.work.jobs:
                    wrapped.append((job, job.payload))
                    job.payload = (i, job.payload)

            kstats: dict = {}        # per-batch, not global
            sieve_handle = None
            t0 = self.metrics.device_begin()
            try:
                with batch_ctx:
                    if files and self.secret_scanner is not None:
                        # async enqueue: the device sieves while the
                        # interval dispatch below compiles/queues
                        # behind
                        sieve_handle = \
                            self.secret_scanner.dispatch_files(files)

                    all_jobs = [job for job, _ in wrapped]
                    detected_by: dict = {}
                    if all_jobs:
                        for i, payload in dispatch_jobs(
                                all_jobs, backend=group,
                                mesh=self.mesh, stats=kstats):
                            detected_by.setdefault(i, []).append(
                                payload)
                        with self._lock:
                            self._kernel_s += kstats.get(
                                "device_s", 0.0)

                    found_by: dict = {}
                    if sieve_handle is not None:
                        for idx, secret in \
                                self.secret_scanner.collect(
                                    sieve_handle):
                            found_by.setdefault(
                                owner[idx], []).append(
                                (local[idx], secret))
            finally:
                for job, orig in wrapped:
                    job.payload = orig
                wall = self.metrics.device_end(t0)
                # billed even when the dispatch raised: the device
                # wall was spent either way, and the bisect ladder's
                # halves re-bill their own walls — the accounting
                # identity survives poison isolation
                self._meter_dispatch(reqs, t0, wall, kstats,
                                     sieve_handle is not None)
            self.metrics.observe("device", time.monotonic() - t0,
                                 trace_id=reqs[0].trace_id or "")
        except Exception as e:       # noqa: BLE001
            for sp in spans:
                sp.event("device_failed", error=repr(e))
                sp.end("error")
            raise
        for sp in spans:
            sp.end()
        return {id(r): (found_by.get(i, []), detected_by.get(i, []))
                for i, r in enumerate(reqs)}

    def _dispatch_isolated(self, reqs: list, group: str,
                           depth: int = 0,
                           batch_id: int = 0) -> dict:
        """Dispatch with failure isolation: a raising batch is
        bisected until the poison request(s) are cornered alone,
        retried bounded, then quarantined to the exact host path —
        the rest of the batch completes normally. Only a request
        whose host fallback ALSO fails resolves with an error."""
        try:
            return self._dispatch(reqs, group, depth=depth,
                                  batch_id=batch_id)
        except Exception as e:       # noqa: BLE001
            if len(reqs) == 1:
                return self._quarantine(reqs[0], group, e,
                                        depth=depth,
                                        batch_id=batch_id)
            log.warning("device dispatch failed for %d requests "
                        "(%r); bisecting", len(reqs), e)
            self.metrics.inc("batch_bisects")
            for r in reqs:
                if r.span_root is not None:
                    r.span_root.event("batch_bisect",
                                      depth=depth + 1,
                                      requests=len(reqs))
            mid = (len(reqs) + 1) // 2
            out = self._dispatch_isolated(reqs[:mid], group,
                                          depth + 1, batch_id)
            out.update(self._dispatch_isolated(reqs[mid:], group,
                                               depth + 1, batch_id))
            return out

    def _quarantine(self, req: ScanRequest, group: str,
                    err: BaseException, depth: int = 0,
                    batch_id: int = 0) -> dict:
        """Single failing request: bounded on-device retries (a
        transient may clear), then the host-fallback path."""
        for _ in range(max(0, self.config.quarantine_retries)):
            try:
                return self._dispatch([req], group, depth=depth,
                                      batch_id=batch_id,
                                      attempt="quarantine_retry")
            except Exception as e:   # noqa: BLE001
                err = e
        self.metrics.inc("quarantined")
        log.warning("quarantining %r after device failure: %r",
                    req.name, err)
        if req.span_root is not None:
            req.span_root.event("quarantined", error=repr(err))
        req.record_fault(
            "device", "quarantined",
            f"device dispatch failed, completed on host: {err}")
        sp = self.tracer.child(req.span_root, "host_fallback")
        try:
            with sp.activate():
                out = self._host_fallback(req)
            sp.end()
            self.metrics.inc("host_fallbacks")
            return out
        except Exception as e2:      # noqa: BLE001
            sp.end("error")
            log.warning("host fallback for %r failed: %r",
                        req.name, e2)
            req.record_fault("host", "fallback_failed", str(e2))
            self._fail(req, e2)
            return {}

    def _host_fallback(self, req: ScanRequest) -> dict:
        """The exact host path for one quarantined request: a
        whole-file CPU secret scan (reference engine — identical
        findings to the sieve by construction) and the cpu-ref
        interval evaluation (detect/batch.py host fallback)."""
        from ..detect.batch import dispatch_jobs

        work = req.work
        found = []
        base = getattr(self.secret_scanner, "scanner", None)
        if work.candidates and base is not None:
            for j, (path, content) in enumerate(work.candidates):
                secret = base.scan(path, content)
                if secret.findings:
                    found.append((j, secret))
        detected = []
        if work.jobs:
            wrapped = [(job, job.payload) for job in work.jobs]
            for job, orig in wrapped:
                job.payload = (0, orig)
            try:
                for _i, payload in dispatch_jobs(
                        work.jobs, backend="cpu-ref", mesh=None,
                        stats={}):
                    detected.append(payload)
            finally:
                for job, orig in wrapped:
                    job.payload = orig
        return {id(req): (found, detected)}

    # --- stage 3: host finish ---

    def _finish(self, req: ScanRequest, found: list,
                detected: list) -> None:
        t0 = self.metrics.host_begin()
        sp = self.tracer.child(req.span_root, "report")
        try:
            work = req.work
            if work.deps and not sp.noop:
                sp.event("deps_wait", n=len(work.deps))
            for ev in work.deps:
                # deps are resolved by the device thread; they cannot
                # wait on this request, so a bounded wait only guards
                # against scheduler shutdown mid-flight
                while not ev.wait(timeout=1.0):
                    if not self._running:
                        sp.end("error")
                        self._fail(req, SchedulerClosed(
                            "scheduler closed"))
                        return
                    if self._sweep(req):
                        sp.end("error")
                        return
            if self._sweep(req):
                # expired after the device batch resolved but before
                # assembly — abandon, the 408 already went out
                self.metrics.inc("expired_inflight")
                sp.end("error")
                return
            with sp.activate():
                result = work.finish(found, detected)
                if req.faults:
                    if not sp.noop:
                        # the degraded report references its trace so
                        # the operator can pull the span tree
                        # (GET /trace/<id> / flight-recorder dump)
                        req.faults.append(trace_cause(
                            self.tracer, req.trace_id))
                    result = _annotate_degraded(result, req.faults)
            # the report span closes BEFORE the root resolves so the
            # completed trace's children nest inside the root
            sp.end()
            self._complete(req, result)
        except Exception as e:       # noqa: BLE001
            sp.end("error")
            log.warning("finish %r failed: %r", req.name, e)
            self._fail(req, e)
        finally:
            self.metrics.host_end(t0)
            host_s = time.monotonic() - t0
            self.metrics.observe("finish", host_s,
                                 trace_id=req.trace_id or "")
            COST_LEDGER.charge(getattr(req, "tenant", "") or "",
                               host_finish_s=host_s)
