"""Flag/config system (reference: pkg/flag/options.go:19-92,
pkg/flag/global_flags.go).

Precedence matches the reference's viper wiring: explicit CLI flag >
``TRIVY_<FLAG>`` environment variable > config file (``trivy.yaml``)
> built-in default. Env names are the flag name upper-cased with
dashes as underscores (options.go:154-156); config keys are the flag
names. ``--timeout`` mirrors global_flags.go:51-55 (5m default) and
aborts the scan when exceeded.
"""

from __future__ import annotations

import contextlib
import os
import re
import signal
import sys

from .utils import get_logger

log = get_logger("flag")

ENV_PREFIX = "TRIVY_"
DEFAULT_CONFIG_FILE = "trivy.yaml"

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|h|m|s)")


def parse_duration(s) -> float:
    """Go-style duration ('5m0s', '1h30m', '300ms') or bare
    seconds → seconds."""
    if isinstance(s, (int, float)):
        return float(s)
    s = s.strip()
    if not s:
        raise ValueError("empty duration")
    if s.replace(".", "", 1).isdigit():
        return float(s)
    total = 0.0
    pos = 0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration: {s!r}")
        value, unit = float(m.group(1)), m.group(2)
        total += value * {"h": 3600, "m": 60, "s": 1,
                          "ms": 0.001}[unit]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration: {s!r}")
    return total


def _load_config_file(argv) -> dict:
    """--config <path> pre-pass; default trivy.yaml when present."""
    path = None
    for i, a in enumerate(argv):
        if a in ("--config", "-c") and i + 1 < len(argv):
            path = argv[i + 1]
        elif a.startswith("--config="):
            path = a.split("=", 1)[1]
        elif a.startswith("-c") and len(a) > 2 and \
                not a.startswith("--"):
            path = a[2:]
    explicit = path is not None
    path = path or DEFAULT_CONFIG_FILE
    if not os.path.exists(path):
        if explicit:
            print(f"error: config file not found: {path}",
                  file=sys.stderr)
            raise SystemExit(2)
        return {}
    import yaml
    try:
        with open(path, encoding="utf-8") as f:
            doc = yaml.safe_load(f) or {}
    except (OSError, yaml.YAMLError) as e:
        print(f"error: failed to read config file {path}: {e}",
              file=sys.stderr)
        raise SystemExit(2)
    if not isinstance(doc, dict):
        return {}
    log.debug("loaded config file %s", path)
    return doc


def _convert(action, raw):
    """String from env/yaml → the action's value type."""
    import argparse
    if isinstance(action, (argparse._StoreTrueAction,
                           argparse._StoreFalseAction)):
        if isinstance(raw, bool):
            return raw
        return str(raw).strip().lower() in ("1", "true", "yes", "on")
    if isinstance(raw, list):
        raw = ",".join(str(x) for x in raw)
    value = action.type(raw) if action.type is not None else str(raw)
    if action.choices is not None and value not in action.choices:
        raise ValueError(
            f"{value!r} (choose from "
            f"{', '.join(map(str, action.choices))})")
    return value


def _walk_parsers(parser):
    yield parser
    for action in parser._actions:
        choices = getattr(action, "choices", None)
        if isinstance(choices, dict):
            for sub in choices.values():
                if hasattr(sub, "_actions"):
                    yield from _walk_parsers(sub)


def apply_external_defaults(parser, argv) -> None:
    """Rewrite parser defaults from env + config file so explicit CLI
    flags still win (viper's layering, options.go:140-162)."""
    config = _load_config_file(argv or [])
    for p in _walk_parsers(parser):
        for action in p._actions:
            if not action.option_strings:
                continue
            longs = [o for o in action.option_strings
                     if o.startswith("--")]
            if not longs:
                continue
            flag_name = longs[0][2:]
            if flag_name in ("help", "version", "config"):
                continue
            env_name = ENV_PREFIX + flag_name.upper()\
                .replace("-", "_")
            raw = os.environ.get(env_name)
            if raw is None and flag_name in config:
                raw = config[flag_name]
            if raw is None:
                continue
            source = env_name if os.environ.get(env_name) is not None \
                else f"config key {flag_name!r}"
            try:
                action.default = _convert(action, raw)
            except (ValueError, TypeError) as e:
                print(f"error: invalid value for {source}: {e}",
                      file=sys.stderr)
                raise SystemExit(2)


class ScanTimeout(Exception):
    pass


@contextlib.contextmanager
def scan_deadline(seconds: float):
    """Abort the scan after ``seconds`` (ref --timeout applied at
    run.go:343). SIGALRM-based; no-op off the main thread or on
    platforms without setitimer."""
    if seconds <= 0 or not hasattr(signal, "setitimer"):
        yield
        return
    try:
        old = signal.signal(signal.SIGALRM, _raise_timeout)
    except ValueError:          # not in the main thread
        yield
        return
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


def _raise_timeout(signum, frame):
    raise ScanTimeout("scan timeout exceeded")
