"""trivy_tpu — a TPU-native security-scanning framework.

A brand-new framework with the capabilities of the reference scanner
(aquasecurity/trivy, Go): container image / filesystem / repo / SBOM /
Kubernetes scanning for vulnerabilities, secrets, misconfigurations and
licenses — with the two hot loops re-designed TPU-first:

* secret detection: a batched literal/anchor sieve over flattened,
  segment-padded byte buffers (``trivy_tpu.ops.keywords``) plus a
  class-run gate kernel (``trivy_tpu.ops.runs``), with sparse
  host-side verification for exact span/group parity;
* vulnerability detection: package→advisory version-constraint matching
  as vectorized fixed-width version-key interval intersection
  (``trivy_tpu.ops.vercmp``) over a flattened advisory table.

Host-side (Python) does the irregular work: tar walking, parsers,
caching, report writing — mirroring the reference's layering
(see SURVEY.md §1) but organized for a batch-dispatch TPU runtime.
"""

__version__ = "0.1.0"

SCHEMA_VERSION = 2
