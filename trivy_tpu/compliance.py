"""Compliance reporting (reference: pkg/compliance/{spec,report}).

A YAML spec maps controls to check IDs (misconfig rule IDs or
vulnerability IDs); scan results group under each control, producing
an ``all`` report (per-control findings) or a ``summary`` (pass/fail
totals per control). The built-in ``nsa`` spec covers the NSA/CISA
Kubernetes hardening controls the reference embeds.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .utils import get_logger

log = get_logger("compliance")

try:
    import yaml as yaml_mod
except ImportError:              # pragma: no cover
    yaml_mod = None


@dataclass
class Control:
    id: str = ""
    name: str = ""
    description: str = ""
    checks: list = field(default_factory=list)      # check ids
    severity: str = ""
    default_status: str = ""


@dataclass
class Spec:
    id: str = ""
    title: str = ""
    description: str = ""
    version: str = ""
    related_resources: list = field(default_factory=list)
    controls: list = field(default_factory=list)


@dataclass
class ControlResult:
    """One control's outcome (ref ControlCheck + per-control
    findings)."""

    control: Control = None
    status: str = "PASS"
    pass_total: int = 0
    fail_total: int = 0
    findings: list = field(default_factory=list)   # dicts

    def to_dict(self) -> dict:
        d = {"ID": self.control.id, "Name": self.control.name,
             "Severity": self.control.severity,
             "Status": self.status,
             "PassTotal": self.pass_total,
             "FailTotal": self.fail_total}
        if self.findings:
            d["Findings"] = self.findings
        return d


@dataclass
class ComplianceReport:
    spec: Spec = None
    controls: list = field(default_factory=list)   # ControlResult

    def to_dict(self) -> dict:
        return {"ID": self.spec.id, "Title": self.spec.title,
                "Version": self.spec.version,
                "Controls": [c.to_dict() for c in self.controls]}


# NSA/CISA Kubernetes Hardening Guidance v1.0 — the subset whose
# checks this framework's policy set implements (the reference embeds
# the full spec; controls whose checks are absent report via
# defaultStatus, same as the reference's FAIL/WARN defaults).
NSA_SPEC = {
    "spec": {
        "id": "nsa",
        "title": "National Security Agency - Kubernetes Hardening "
                 "Guidance v1.0",
        "description": "National Security Agency - Kubernetes "
                       "Hardening Guidance",
        "version": "1.0",
        "controls": [
            {"id": "1.0", "name": "Non-root containers",
             "checks": [{"id": "KSV012"}], "severity": "MEDIUM"},
            {"id": "1.2", "name": "Immutable container file systems",
             "checks": [{"id": "KSV014"}], "severity": "LOW"},
            {"id": "1.4", "name": "Privileged",
             "checks": [{"id": "KSV017"}], "severity": "HIGH"},
            {"id": "1.6", "name": "Run with root privileges or with "
             "root group membership",
             "checks": [{"id": "KSV029"}], "severity": "LOW"},
            {"id": "1.7", "name": "hostPath mount",
             "checks": [{"id": "KSV006"}], "severity": "MEDIUM"},
            {"id": "1.9", "name": "Privilege escalation",
             "checks": [{"id": "KSV001"}], "severity": "MEDIUM"},
        ],
    },
}


def load_spec(name_or_path: str) -> Spec:
    """Built-in spec name or a YAML file (ref spec/compliance.go
    GetComplianceSpec)."""
    if name_or_path == "nsa":
        doc = NSA_SPEC
    else:
        try:
            with open(name_or_path, encoding="utf-8") as f:
                doc = yaml_mod.safe_load(f) or {}
        except yaml_mod.YAMLError as e:
            raise ValueError(f"invalid spec yaml: {e}")
    if not isinstance(doc, dict):
        raise ValueError("spec yaml must be a mapping with a "
                         "top-level 'spec' key")
    raw = doc.get("spec") or {}
    controls = []
    for c in raw.get("controls") or []:
        controls.append(Control(
            id=str(c.get("id", "")),
            name=c.get("name", ""),
            description=c.get("description", ""),
            checks=[chk.get("id", "") for chk in
                    c.get("checks") or []],
            severity=c.get("severity", ""),
            default_status=c.get("defaultStatus", "")))
    return Spec(id=raw.get("id", ""), title=raw.get("title", ""),
                description=raw.get("description", ""),
                version=str(raw.get("version", "")),
                related_resources=raw.get("relatedResources") or [],
                controls=controls)


def _collect_findings(results) -> tuple:
    """→ ({check_id: [finding dicts]}, {check_id: pass_count})."""
    fails: dict = {}
    passes: dict = {}
    for r in results:
        for m in r.misconfigurations:
            cid = getattr(m, "id", "")
            if getattr(m, "status", "") == "FAIL":
                fails.setdefault(cid, []).append(
                    {"Target": r.target, "ID": cid,
                     "Severity": getattr(m, "severity", ""),
                     "Message": getattr(m, "message", "")})
            else:
                passes[cid] = passes.get(cid, 0) + 1
        for v in r.vulnerabilities:
            fails.setdefault(v.vulnerability_id, []).append(
                {"Target": r.target, "ID": v.vulnerability_id,
                 "Severity": v.severity,
                 "Message": v.pkg_name})
    return fails, passes


def build_report(spec: Spec, results: list) -> ComplianceReport:
    """Map scan results onto the spec's controls
    (ref spec/mapper.go)."""
    fails, passes = _collect_findings(results)
    out = ComplianceReport(spec=spec)
    for control in spec.controls:
        cr = ControlResult(control=control)
        matched = False
        for cid in control.checks:
            if cid in fails:
                cr.findings.extend(fails[cid])
                cr.fail_total += len(fails[cid])
                matched = True
            if cid in passes:
                cr.pass_total += passes[cid]
                matched = True
        if cr.fail_total:
            cr.status = "FAIL"
        elif not matched and control.default_status:
            cr.status = control.default_status
            if control.default_status == "FAIL":
                cr.fail_total = 1
        out.controls.append(cr)
    return out


def render_summary(report: ComplianceReport) -> str:
    from .report.writer import _table
    lines = [f"Summary Report for compliance: {report.spec.title}",
             ""]
    rows = [("ID", "Severity", "Control Name", "Status", "Issues")]
    for cr in report.controls:
        rows.append((cr.control.id, cr.control.severity,
                     cr.control.name, cr.status,
                     str(cr.fail_total)))
    lines.extend(_table(rows))
    return "\n".join(lines) + "\n"


def write_compliance(report: ComplianceReport, fmt: str = "table",
                     output=None) -> None:
    import json
    import sys
    out = output or sys.stdout
    if fmt == "json":
        json.dump(report.to_dict(), out, indent=2)
        out.write("\n")
    else:
        out.write(render_summary(report))
