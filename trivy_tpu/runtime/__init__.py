"""Batch scan runtime — the north-star path (BASELINE.json: scan N
cached container images, secrets + vulns, sharded across a TPU mesh).

The reference scans images one at a time, with goroutine parallelism
inside each scan (k8s fleet scans are a sequential loop per artifact —
SURVEY.md §2.6). Here the batch IS the unit: every image's secret
candidates share one sieve dispatch, every image's (package, advisory)
pairs share one interval dispatch, and a mesh shards both over chips.
"""

from .batch import BatchScanRunner, BatchScanResult

__all__ = ["BatchScanRunner", "BatchScanResult"]
