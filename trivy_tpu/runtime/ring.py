"""Bounded dispatch ring: the double-buffered device-slot runtime
(docs/performance.md "Async device runtime").

The synchronous ladder — pack → upload → compute → collect — leaves
the device idle while the host packs the next batch and leaves the
host idle while the device computes (the r05 ``interval_dispatch_s``
≈ 2× ``interval_device_s`` defect). The ring splits every dispatch
into a LAUNCH half (pack + ``jax.device_put`` into a fresh slot's
buffers + non-blocking jitted enqueue, run on the submitting thread)
and a COLLECT half (block on the lazy arrays, decode, fan results
out, run on the ring's own drain thread), bounded at ``depth``
slots in flight:

* ``depth == 1`` degenerates to the synchronous ladder — submit
  blocks until the previous slot drained, so latency-sensitive
  callers (admission verdicts) never wait behind a speculative
  batch;
* ``depth >= 2`` is double buffering — slot N+1 launches while slot
  N computes, and the drain thread's blocking materialize is where
  the device wall actually passes (it brackets ``device_compute``
  spans itself via the caller's collect callable).

A submit that finds the ring full parks under a ``slot_wait`` span
(a typed idle cause in obs/timeline.py: the device pipeline is
gated on collection, not on new work). Slots ALWAYS collect in FIFO
submission order — collection order is a correctness surface (secret
patches must land before dependents' merges), not a scheduling
choice.

``RING_METRICS`` is the process-wide accounting every ring reports
into (mirroring GUARD_METRICS et al.): current/high-water dispatch
depth, the time-integral slot occupancy, and the overlap ratio —
share of slot-active wall during which ≥ 2 slots were in flight —
surfaced on ``/metrics`` in both sched modes as
``trivy_tpu_dispatch_depth`` / ``trivy_tpu_slot_occupancy`` /
``trivy_tpu_dispatch_overlap_ratio``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ..utils import get_logger

log = get_logger("runtime.ring")


class RingMetrics:
    """Process-wide slot accounting; every method thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {
            "slots_launched": 0, "slots_collected": 0,
            "slot_errors": 0, "slot_waits": 0,
        }
        self._wait_s = 0.0
        self._active = 0              # slots currently in flight
        self._depth_limit = 1         # widest configured depth seen
        self._depth_max = 0           # high-water in-flight count
        self._since = None            # 0→1 transition instant
        self._overlap_since = None    # 1→2 transition instant
        self._busy_s = 0.0            # wall with >= 1 slot in flight
        self._overlap_s = 0.0         # wall with >= 2 slots in flight
        self._active_integral = 0.0   # ∫ active dt (occupancy)
        self._last_edge = None

    def note_depth_limit(self, depth: int) -> None:
        with self._lock:
            if depth > self._depth_limit:
                self._depth_limit = depth

    def note_wait(self, seconds: float) -> None:
        with self._lock:
            self.counters["slot_waits"] += 1
            self._wait_s += seconds

    def _edge(self, now: float) -> None:
        # accumulate the occupancy integral at every transition so
        # the time-weighted mean is exact, not sampled
        if self._last_edge is not None:
            self._active_integral += \
                self._active * (now - self._last_edge)
        self._last_edge = now

    def slot_begin(self) -> None:
        now = time.monotonic()
        with self._lock:
            self._edge(now)
            self.counters["slots_launched"] += 1
            self._active += 1
            if self._active > self._depth_max:
                self._depth_max = self._active
            if self._active == 1:
                self._since = now
            elif self._active == 2:
                self._overlap_since = now

    def slot_end(self, error: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            self._edge(now)
            self.counters["slots_collected"] += 1
            if error:
                self.counters["slot_errors"] += 1
            self._active -= 1
            if self._active == 1 and self._overlap_since is not None:
                self._overlap_s += now - self._overlap_since
                self._overlap_since = None
            if self._active == 0 and self._since is not None:
                self._busy_s += now - self._since
                self._since = None

    def snapshot(self) -> dict:
        with self._lock:
            now = time.monotonic()
            busy = self._busy_s
            overlap = self._overlap_s
            integral = self._active_integral
            if self._last_edge is not None and self._active:
                integral += self._active * (now - self._last_edge)
            if self._since is not None:
                busy += now - self._since
            if self._overlap_since is not None:
                overlap += now - self._overlap_since
            return {
                "counters": dict(self.counters),
                "depth": self._active,
                "depth_limit": self._depth_limit,
                "depth_max": self._depth_max,
                "slot_wait_s": round(self._wait_s, 4),
                "slot_busy_s": round(busy, 4),
                "slot_overlap_s": round(overlap, 4),
                # share of in-flight wall during which >= 2 slots
                # overlapped: 0 = the strict serial ladder, → 1 =
                # the device never waited for a launch
                "dispatch_overlap_ratio": round(overlap / busy, 4)
                if busy > 0 else 0.0,
                # time-weighted mean in-flight slots over the
                # in-flight wall, normalized by the configured
                # depth: 1.0 = the ring is always as full as allowed
                "slot_occupancy": round(
                    integral / (busy * self._depth_limit), 4)
                if busy > 0 and self._depth_limit else 0.0,
            }


RING_METRICS = RingMetrics()


class TeeRingMetrics:
    """Fan one ring's accounting into several sinks — a per-scan
    RingMetrics (exact numbers for THIS scan's stats, immune to
    concurrent scans' rings) plus the process-wide RING_METRICS
    (the /metrics books)."""

    def __init__(self, *sinks: RingMetrics):
        self.sinks = sinks

    def note_depth_limit(self, depth: int) -> None:
        for s in self.sinks:
            s.note_depth_limit(depth)

    def note_wait(self, seconds: float) -> None:
        for s in self.sinks:
            s.note_wait(seconds)

    def slot_begin(self) -> None:
        for s in self.sinks:
            s.slot_begin()

    def slot_end(self, error: bool = False) -> None:
        for s in self.sinks:
            s.slot_end(error=error)


DEFAULT_DISPATCH_DEPTH = 2


def resolve_dispatch_depth(depth: int = 0) -> int:
    """One resolution rule for every entry point (runner arg,
    --dispatch-depth flag, SchedConfig): explicit positive value
    wins, 0 falls back to ``TRIVY_TPU_DISPATCH_DEPTH`` then the
    default, floor 1."""
    import os
    if not depth:
        try:
            depth = int(os.environ.get(
                "TRIVY_TPU_DISPATCH_DEPTH", "")
                or DEFAULT_DISPATCH_DEPTH)
        except ValueError:
            log.warning("bad TRIVY_TPU_DISPATCH_DEPTH ignored")
            depth = DEFAULT_DISPATCH_DEPTH
    return max(1, int(depth))


class RingClosed(RuntimeError):
    """submit() after close()."""


class Slot:
    """One in-flight dispatch: launched, awaiting its FIFO collect."""

    __slots__ = ("label", "payload", "collect", "done", "result",
                 "error")

    def __init__(self, label: str, payload, collect: Callable):
        self.label = label
        self.payload = payload
        self.collect = collect
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None

    def wait(self, timeout: Optional[float] = None):
        """Block until this slot drained; returns the collect
        result or re-raises the collect error."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"slot {self.label!r} not collected")
        if self.error is not None:
            raise self.error
        return self.result


class DispatchRing:
    """Bounded FIFO of in-flight device slots with a dedicated
    drain thread. ``submit`` blocks (under a ``slot_wait`` span)
    once ``depth`` slots are launched-but-uncollected; the drain
    thread pops the oldest slot and runs its collect callable."""

    def __init__(self, depth: int = 2, name: str = "ring",
                 metrics: Optional[RingMetrics] = None):
        self.depth = max(1, int(depth))
        self.name = name
        self.metrics = metrics if metrics is not None \
            else RING_METRICS
        self.metrics.note_depth_limit(self.depth)
        self._cv = threading.Condition()
        self._slots: deque = deque()      # launched, not collected
        self._collecting: Optional[Slot] = None
        self._reserved = 0                # capacity held by launches
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle ---

    def _ensure_thread(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._drain_loop,
                name=f"ring-{self.name}", daemon=True)
            self._thread.start()

    def close(self, collect: bool = True) -> None:
        """Stop accepting slots. ``collect=True`` drains every slot
        already launched (device work in flight completes — the
        scheduler's shutdown contract); False abandons them with
        RingClosed."""
        abandoned = []
        with self._cv:
            self._closed = True
            if not collect:
                # only slots still queued are abandoned — the one
                # mid-collection (if any) finishes on the drain
                # thread, which owns its bookkeeping
                while self._slots:
                    slot = self._slots.popleft()
                    slot.error = RingClosed("ring closed")
                    abandoned.append(slot)
            self._cv.notify_all()
        for slot in abandoned:
            # waiters wake and metrics book outside the cv — the
            # metric sink takes its own lock (lint: lock-discipline)
            slot.done.set()
            self.metrics.slot_end(error=True)
        t = self._thread
        if t is not None:
            t.join(timeout=30)

    # --- submission ---

    def submit(self, collect: Callable, payload=None,
               depth: Optional[int] = None,
               label: str = "",
               launch: Optional[Callable] = None) -> Slot:
        """Launch one slot. Without ``launch`` the caller has
        ALREADY enqueued its device work and ``payload`` carries its
        handle; with ``launch`` the ring first waits for capacity,
        then runs ``launch()`` on the calling thread to produce the
        payload — so pack/upload of slot N+1 never starts before a
        ring position frees (the bound covers staged HBM, not just
        queued bookkeeping). ``collect(payload)`` runs on the drain
        thread when the slot reaches the head of the ring.

        ``depth`` overrides the ring bound for this submit — the
        scheduler's occupancy feedback passes 1 when the queue is
        empty, so an interactive request never parks behind a
        speculative batch."""
        from ..obs.trace import phase_span
        bound = self.depth if depth is None else max(1, int(depth))
        waited_s = 0.0
        try:
            with self._cv:
                if self._closed:
                    raise RingClosed("ring closed")
                if self._in_flight_locked() + self._reserved \
                        >= bound:
                    t0 = time.monotonic()
                    # a full ring is a typed stall: the pipeline is
                    # gated on the drain thread, and the timeline
                    # attributes device idle under this span to
                    # slot_wait (obs/timeline.py)
                    with phase_span("slot_wait", ring=self.name,
                                    depth=bound):
                        while self._in_flight_locked() + \
                                self._reserved >= bound and \
                                not self._closed:
                            self._cv.wait(0.1)
                    waited_s = time.monotonic() - t0
                    if self._closed:
                        raise RingClosed("ring closed")
                self._reserved += 1
        finally:
            if waited_s:
                # the metric sink takes its own lock — book the
                # wait outside the cv (lint: lock-discipline)
                self.metrics.note_wait(waited_s)
        try:
            if launch is not None:
                # heavy work OUTSIDE the lock; a raising launch
                # releases the reservation and consumes no slot
                payload = launch()
        except BaseException:
            with self._cv:
                self._reserved -= 1
                self._cv.notify_all()
            raise
        # slot_begin BEFORE the slot becomes drainable: the drain
        # thread's slot_end must never run first, and the metric
        # sink's own lock must not nest under the cv (lint:
        # lock-discipline). A close() racing in below books the
        # phantom slot closed again (launched == collected holds).
        self.metrics.slot_begin()
        booked = False
        try:
            with self._cv:
                self._reserved -= 1
                if self._closed:
                    self._cv.notify_all()
                    raise RingClosed("ring closed")
                slot = Slot(label, payload, collect)
                self._slots.append(slot)
                booked = True
                self._cv.notify_all()
        finally:
            if not booked:
                self.metrics.slot_end(error=True)
        self._ensure_thread()
        return slot

    def _in_flight_locked(self) -> int:
        return len(self._slots) + \
            (1 if self._collecting is not None else 0)

    def in_flight(self) -> int:
        with self._cv:
            return self._in_flight_locked()

    def flush(self, timeout_s: float = 60.0) -> bool:
        """Wait until every launched slot collected."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._in_flight_locked():
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(0.1, left))
        return True

    # --- the drain thread ---

    def _drain_loop(self) -> None:
        while True:
            with self._cv:
                while not self._slots and not self._closed:
                    self._cv.wait(0.1)
                if not self._slots:
                    if self._closed:
                        return
                    continue
                # the slot keeps occupying ring capacity until its
                # collect finished — depth bounds launched work, not
                # merely queued work
                slot = self._slots.popleft()
                self._collecting = slot
            try:
                slot.result = slot.collect(slot.payload)
            except BaseException as e:    # noqa: BLE001 — the
                # error belongs to the slot's owner; the drain
                # thread must survive to collect the slots behind it
                slot.error = e
            with self._cv:
                self._collecting = None
                self._cv.notify_all()
            self.metrics.slot_end(error=slot.error is not None)
            slot.done.set()
