"""Multi-image batch scanner.

Pipeline per batch of images:

  1. host: load each image, compute cache keys, walk MISSING layers
     through the non-secret analyzers; secret candidates accumulate
     across all images tagged (image, layer);
  2. TPU dispatch #1: one literal-sieve pass over every candidate
     byte of every image (trivy_tpu.secret.batch);
  3. host: PutBlob per layer, ApplyLayers per image, advisory name
     join per package across all images;
  4. TPU dispatch #2: one interval-membership pass over every
     (package, advisory) pair of every image (trivy_tpu.detect.batch);
  5. host: per-image result assembly, enrichment.

Cached images skip 1-2 entirely (content-addressed MissingBlobs —
the reference's resume mechanism, SURVEY.md §5). Two kernel dispatches
per BATCH — not per image — amortize dispatch latency across the
whole fleet (the reference's k8s scanner loops artifacts sequentially,
SURVEY.md §2.6).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..artifact.artifact import ArtifactOption, ImageArtifact
from ..artifact.cache import MemoryCache
from ..artifact.image import load_image
from ..db import AdvisoryStore
from ..detect.batch import dispatch_jobs
from ..scan.local import LocalScanner, ScanTarget
from ..types import Metadata, Report, ScanOptions
from ..utils import get_logger

log = get_logger("runtime.batch")


@dataclass
class BatchScanResult:
    name: str
    report: Optional[Report] = None
    error: str = ""
    # degraded-mode status (docs/robustness.md): ok | degraded |
    # failed, with machine-readable FailureCause records. A slot
    # with ``error`` set is failed; a slot that completed through a
    # fault (device quarantine → host fallback) is degraded.
    status: str = "ok"
    causes: list = field(default_factory=list)

    def apply_degraded(self, causes: list) -> None:
        from ..types.report import FailureCause
        fc = [FailureCause.coerce(c) for c in causes]
        self.causes.extend(fc)
        if self.status != "failed":
            self.status = "degraded"
        if self.report is not None:
            self.report.mark_degraded(fc)

    def mark_failed(self, stage: str, kind: str,
                    message: str) -> "BatchScanResult":
        from ..types.report import FailureCause
        self.status = "failed"
        self.causes.append(FailureCause(stage=stage, kind=kind,
                                        message=message))
        if self.report is not None:
            self.report.mark_degraded(self.causes[-1:],
                                      status="failed")
        return self


class BatchScanRunner:
    def __init__(self, store: Optional[AdvisoryStore] = None,
                 cache=None, backend: str = "tpu", mesh=None,
                 secret_scanner=None, sched="off",
                 sched_config=None, artifact_option=None,
                 fault_injector=None, tracer=None, memo=None,
                 dispatch_depth: int = 0):
        from ..obs.trace import get_tracer
        from .ring import resolve_dispatch_depth
        self.store = store or AdvisoryStore()
        self.cache = cache if cache is not None else MemoryCache()
        # dispatch_depth: bound on in-flight interval waves on the
        # direct (sched=off) path — the double-buffered slot runtime
        # (docs/performance.md §8). 0 = TRIVY_TPU_DISPATCH_DEPTH or
        # the default 2; 1 restores the synchronous ladder
        self.dispatch_depth = resolve_dispatch_depth(dispatch_depth)
        # memo: trivy_tpu.memo.FindingsMemo (or None) — per-layer
        # detection-verdict memoization threaded into every
        # LocalScanner this runner constructs, on both execution
        # paths (docs/performance.md "Findings memoization")
        self.memo = memo
        self.backend = backend
        self.mesh = mesh
        # tracer: trivy_tpu.obs.Tracer — per-request span trees on
        # both execution paths (docs/observability.md); the bench's
        # differential arm passes Tracer(enabled=False)
        self.tracer = tracer if tracer is not None else get_tracer()
        if secret_scanner is None:
            from ..secret.batch import BatchSecretScanner
            secret_scanner = BatchSecretScanner(
                backend="cpu-ref" if backend == "cpu-ref" else "tpu",
                mesh=mesh)
        self.secret_scanner = secret_scanner
        self.artifact_option = artifact_option
        # fault_injector: trivy_tpu.faults.FaultInjector (or None) —
        # threads into the scheduler's device dispatch and this
        # runner's host phases (--fault-spec / bench faults config)
        self.fault_injector = fault_injector
        # sched: "off" = the direct single-batch ladder below;
        # "on"/SchedConfig/ScanScheduler = continuous batching with
        # pipelined host/device overlap (trivy_tpu.sched)
        self.sched_config = sched_config
        self._scheduler = None
        self._owns_scheduler = False
        if hasattr(sched, "submit"):       # a ScanScheduler
            self._scheduler = sched        # shared — caller closes
            self.sched = "on"
        elif sched not in (None, "off", False):
            self.sched = "on"
            from ..sched import SchedConfig
            if isinstance(sched, SchedConfig):
                self.sched_config = sched
        else:
            self.sched = "off"
        self.last_stats: dict = {}   # phase timings of the last batch

    # --- scheduler plumbing ---

    @property
    def scheduler(self):
        if self._scheduler is None:
            from ..sched import ScanScheduler, SchedConfig
            cfg = self.sched_config
            if cfg is None:
                # propagate the runner's slot depth so --sched on
                # and off honor the same --dispatch-depth knob
                cfg = SchedConfig(
                    dispatch_depth=self.dispatch_depth)
            self._scheduler = ScanScheduler(
                config=cfg, backend=self.backend,
                mesh=self.mesh, secret_scanner=self.secret_scanner,
                tracer=self.tracer)
            self._scheduler.fault_injector = self.fault_injector
            self._owns_scheduler = True
        return self._scheduler

    def close(self) -> None:
        # only tear down a scheduler this runner constructed — an
        # externally provided one may serve other request sources
        if self._scheduler is not None and self._owns_scheduler:
            self._scheduler.close()
            self._scheduler = None

    def _store_view(self) -> tuple:
        """``(db, release|None)``: a SwappableStore holder (the
        server's hot-swap contract, now honored by embedders — the
        watch runtime and the admission webhook front long-lived
        runners whose advisory DB updates underneath them) is
        acquired per scan so a ``db update`` swap waits for
        in-flight work; plain stores pass through untouched."""
        s = self.store
        if hasattr(s, "acquire") and hasattr(s, "release"):
            return s.acquire(), s.release
        return s, None

    def scan_paths(self, paths: list,
                   options: Optional[ScanOptions] = None) -> list:
        if self.sched == "on":
            # lazy image load inside analyze() — tar walking is host
            # work that should overlap device execution too
            return self._scan_scheduled(
                [(p, None) for p in paths], options)
        import tarfile as _tarfile
        images, failures = [], {}
        for i, p in enumerate(paths):
            try:
                if self.fault_injector is not None:
                    self.fault_injector.on_image_load(p)
                images.append((i, load_image(
                    p, budget=self._ingest_budget(p))))
            except (OSError, ValueError, _tarfile.TarError) as e:
                failures[i] = _failed_slot(p, e)
        results = self.scan_images([img for _, img in images],
                                   options)
        out = dict(failures)
        for (i, _), res in zip(images, results):
            out[i] = res
        return [out[i] for i in range(len(paths))]

    def scan_images(self, images: list,
                    options: Optional[ScanOptions] = None) -> list:
        if self.sched == "on":
            return self._scan_scheduled(
                [(getattr(img, "name", ""), img) for img in images],
                options)
        from ..utils import defer_gc
        with defer_gc():
            return self._scan_images(images, options)

    def blob_keyer(self, scan_secrets: bool = True):
        """Warm-layer probe keyer for ``artifact.stream.stream_image``:
        computes the SAME ``(artifact_id, blob_ids, base)`` this
        runner's inspect will scan under — same artifact option, same
        secret-rules fingerprint — from image *metadata* alone, so the
        streaming path can skip the blob GET for every layer the
        cache already holds. A mismatched keyer would skip layers
        inspect then reports missing (a failed scan), which is why
        this lives on the runner instead of the stream module."""
        opt = self._image_opt(scan_secrets)

        def keyer(img):
            a = ImageArtifact(img, self.cache, opt,
                              budget=getattr(img, "ingest_budget",
                                             None))
            return a.cache_keys()

        return keyer

    def scan_registry_refs(self, refs: list, client=None,
                           options: Optional[ScanOptions] = None,
                           streaming: bool = True) -> list:
        """Scan images straight from a registry — the cold-wall path
        (docs/performance.md §9). With ``streaming`` (the default)
        each ref becomes a :class:`~..artifact.stream.\
StreamingImageSource`: layer blobs decompress into the scan as they
        arrive, warm layers skip their GET entirely, and the per-layer
        pipeline overlaps the fleet's device work on both execution
        paths. ``streaming=False`` is the materialize-first baseline
        (``DistributionClient.pull``) the bench compares against."""
        from ..artifact.registry import DistributionClient
        from ..artifact.stream import stream_image
        if client is None:
            client = DistributionClient()
        # the registry stream is a failure domain of its own
        # (registry-flaky scenario): thread the runner's injector
        # into the blob fetch engine
        client.fault_injector = self.fault_injector
        options = options or ScanOptions(backend=self.backend)
        scan_secrets = "secret" in options.security_checks
        keyer = self.blob_keyer(scan_secrets)

        def load(ref, budget):
            if not streaming:
                return client.pull(ref, budget=budget)
            return stream_image(client, ref, cache=self.cache,
                                keyer=keyer, budget=budget)

        if self.sched == "on":
            return self._scan_scheduled([(r, None) for r in refs],
                                        options, loader=load)
        sources, failures = [], {}
        for i, ref in enumerate(refs):
            try:
                if self.fault_injector is not None:
                    self.fault_injector.on_image_load(ref)
                sources.append((i, load(
                    ref, self._ingest_budget(ref))))
            except (OSError, ValueError) as e:
                # RegistryError is a ValueError; GuardError keeps its
                # typed ingest stage/kind through _failed_slot
                failures[i] = _failed_slot(ref, e)
        try:
            results = self.scan_images(
                [src for _, src in sources], options)
        finally:
            for _, src in sources:
                try:
                    src.close()
                except Exception:   # noqa: BLE001 — cleanup only
                    log.debug("source close failed",
                              exc_info=True)
        out = dict(failures)
        for (i, _), res in zip(sources, results):
            out[i] = res
        return [out[i] for i in range(len(refs))]

    def _ingest_budget(self, name: str):
        """Fresh per-target ResourceBudget (docs/robustness.md), or
        None when the runner's artifact option disabled the guards
        (``--no-ingest-guards``)."""
        from ..guard.budget import make_budget
        opt = self.artifact_option
        enabled = opt.ingest_guards if opt is not None else True
        return make_budget(
            getattr(opt, "ingest_limits", None) if opt else None,
            enabled=enabled, name=name)

    def _image_opt(self, scan_secrets: bool) -> ArtifactOption:
        """Per-scan artifact option: the runner-level template (CLI
        skip dirs / file patterns) with secret scanning routed to the
        batch sieve instead of a per-artifact scanner."""
        from ..secret.batch import rules_fingerprint
        if self.artifact_option is None:
            return ArtifactOption(
                scan_secrets=scan_secrets,
                secret_rules_fp=rules_fingerprint(
                    self.secret_scanner))
        import copy
        opt = copy.copy(self.artifact_option)
        opt.scan_secrets = scan_secrets and \
            self.artifact_option.scan_secrets
        opt.secret_scanner = None
        # blob keys must reflect the sieve that ACTUALLY produces
        # this runner's secret findings (the shared batch scanner,
        # not the per-option default)
        if not opt.secret_rules_fp:
            opt.secret_rules_fp = rules_fingerprint(
                self.secret_scanner)
        return opt

    # --- the scheduled (continuous-batching) route ---

    def _scan_scheduled(self, items: list,
                        options: Optional[ScanOptions] = None,
                        loader=None) -> list:
        """``items``: [(name, image-or-None)] — None loads the path
        (or, with ``loader``, the registry ref) lazily inside
        analyze(). Submits one request per image to the scheduler and
        gathers results in input order; per-request failures (load
        errors, deadline expiry) fail their own slot, never the
        fleet."""
        import time as _time

        from ..sched import RateLimitedError

        options = options or ScanOptions(backend=self.backend)
        sched = self.scheduler
        reqs = []
        for name, img in items:
            req = self._image_request(sched, name, img, options,
                                      loader=loader)
            while True:
                try:
                    reqs.append(sched.submit(req, block=True))
                    break
                except RateLimitedError as e:
                    # closed-loop fleet semantics: block=True means
                    # "wait for capacity", and a tenant rate limit
                    # is capacity too — sleep the shed hint and
                    # retry instead of killing the whole fleet.
                    # Serving callers (submit_path) still surface
                    # the 429 to the client.
                    _time.sleep(min(max(e.retry_after_s, 0.01),
                                    5.0))
        out = []
        for (name, _), req in zip(items, reqs):
            try:
                out.append(req.result())
            except Exception as e:       # noqa: BLE001 — one slot's
                # failure (typed or not) must never crash the fleet
                # gather; the cause lands in the slot's report
                out.append(_failed_slot(name, e,
                                        trace_id=req.trace_id,
                                        tracer=self.tracer))
        self.last_stats = {"images": len(items),
                           "sched": sched.stats()}
        for k, v in self.last_stats["sched"].items():
            if k.endswith("_s") or k == "overlap_ratio":
                self.last_stats[k] = v
        return out

    def submit_path(self, path: str,
                    options: Optional[ScanOptions] = None,
                    tenant: str = "", priority: int = 0,
                    trace_id: str = "", parent_span_id: str = ""):
        """Serving-mode entry: enqueue ONE image scan through the
        scheduler and return its ScanRequest future (``.result()``
        blocks; raises QueueFullError on backpressure, or
        RateLimitedError when the ``tenant`` is over its quota or
        rate limit — docs/serving.md "Multi-tenant QoS"). The batch
        composition is the scheduler's business — concurrent
        submitters share device dispatches across tenants."""
        options = options or ScanOptions(backend=self.backend)
        sched = self.scheduler
        return sched.submit(
            self._image_request(sched, path, None, options,
                                tenant=tenant, priority=priority,
                                trace_id=trace_id,
                                parent_span_id=parent_span_id))

    def _image_request(self, sched, name: str, image, options,
                       tenant: str = "", priority: int = 0,
                       trace_id: str = "", parent_span_id: str = "",
                       loader=None):
        from ..sched import AnalyzedWork, ScanRequest

        scan_secrets = "secret" in options.security_checks

        def analyze(req):
            inj = self.fault_injector
            if inj is not None:
                # host failure domains: corrupt layer tar fails this
                # slot only; a slow-host stall eats into the deadline
                inj.on_host_analyze(name)
                inj.on_image_load(name)
            db, release = self._store_view()
            if release is not None:
                # the reader is held from analyze to resolution so a
                # DB hot swap waits for this scan (the server's
                # acquire/release contract); chained AFTER any
                # caller-provided on_done, released exactly once at
                # whatever resolution path fires first
                prev = req.on_done

                def _done(r, _prev=prev, _rel=release):
                    try:
                        if _prev is not None:
                            _prev(r)
                    finally:
                        _rel()
                req.on_done = _done
            budget = self._ingest_budget(name)
            # loader: registry seam (scan_registry_refs) — builds a
            # StreamingImageSource (or a pulled one) instead of
            # opening a local tar; either way the image is loaded
            # INSIDE analyze so manifest/config fetches overlap
            # device execution like tar walking does
            if image is not None:
                img = image
            elif loader is not None:
                img = loader(name, budget)
            else:
                img = load_image(name, budget=budget)
            owns_img = image is None
            opt = self._image_opt(scan_secrets)
            a = _SchedImageArtifact(img, self.cache, opt,
                                    budget=budget)
            # register pending blob writes BEFORE the analyzed blobs
            # land in the cache (the _batch_secrets hook fires between
            # analysis and put_blob), so a concurrent request can
            # never observe an unpatched blob without also seeing the
            # dependency that guards it
            a._sched = sched
            a._sched_req = req
            try:
                ref = a.inspect()
            finally:
                if owns_img:
                    # after inspect every analyzed byte lives in the
                    # cache; release the source now (a streaming
                    # source's layer spool can be whole decompressed
                    # layers on disk, and a fleet of leaked spools
                    # outlives the scan)
                    try:
                        img.close()
                    except Exception:   # noqa: BLE001 — cleanup
                        log.debug("source close failed for %r",
                                  name, exc_info=True)
            a.reference = ref
            if a.budget is not None:
                # survivable hostile input (e.g. a corrupt rpmdb):
                # the slot completes but reports status=degraded
                # with ingest-stage causes
                for kind, msg in a.budget.soft_faults:
                    req.record_fault("ingest", kind, msg)
            scanner = LocalScanner(self.cache, db,
                                   memo=self.memo)
            prepared = scanner.prepare(
                ScanTarget(name=ref.name, artifact_id=ref.id,
                           blob_ids=ref.blob_ids), options)
            candidates = []
            patch = None
            deps = []
            if scan_secrets:
                # collected paths already carry the image '/' prefix
                candidates = [(path, content)
                              for _, path, content in a.collected]
                deps = sched.blob_deps(ref.blob_ids, req)
                if a.collected:
                    patch = _make_patch(self.cache, a)

            def finish(found, detected):
                if scan_secrets:
                    from ..applier import merge_layer_secrets
                    blobs = [self.cache.get_blob(b)
                             for b in ref.blob_ids]
                    prepared.detail.secrets = \
                        merge_layer_secrets(blobs)
                results, os_found = scanner.finish(prepared,
                                                   detected)
                return BatchScanResult(
                    name=ref.name,
                    report=Report(
                        artifact_name=ref.name,
                        artifact_type="container_image",
                        metadata=Metadata(
                            os=os_found,
                            image_id=ref.image_metadata.id,
                            diff_ids=ref.image_metadata.diff_ids,
                            repo_tags=ref.image_metadata.repo_tags,
                            image_config=ref.image_metadata
                            .image_config,
                        ),
                        results=results,
                    ))

            return AnalyzedWork(candidates=candidates,
                                jobs=prepared.jobs, patch=patch,
                                finish=finish, deps=deps)

        if not trace_id and not parent_span_id:
            # ambient fleet context (obs/propagate.py): a scan
            # submitted under an active span — the simhost root, a
            # watch event's propagated context — joins that trace
            # instead of starting an unlinked one
            from ..obs.propagate import current_context
            ctx = current_context()
            if ctx is not None:
                trace_id = ctx.trace_id
                parent_span_id = ctx.parent_span_id
        return ScanRequest(name=name or getattr(image, "name", ""),
                           analyze=analyze,
                           deadline_s=getattr(options, "deadline_s",
                                              0.0) or 0.0,
                           tenant=tenant, priority=priority,
                           trace_id=trace_id[:64],
                           parent_span_id=parent_span_id[:64])

    def _scan_images(self, images: list,
                     options: Optional[ScanOptions] = None) -> list:
        db, release = self._store_view()
        try:
            return self._scan_images_db(db, images, options)
        finally:
            if release is not None:
                release()

    def _scan_images_db(self, db, images: list,
                        options: Optional[ScanOptions] = None) \
            -> list:
        import time as _time
        options = options or ScanOptions(backend=self.backend)
        scan_secrets = "secret" in options.security_checks

        # ---- phase 1: analyze missing layers, collect candidates,
        # squash + join PER IMAGE ----
        # tracing (docs/observability.md): the direct path has no
        # queue, so each image's span tree is analyze → device (the
        # fleet-shared dispatch window) → report
        tracer = self.tracer
        from ..obs.trace import activate_or_null, phase_span
        # ambient fleet context (obs/propagate.py): scans launched
        # under an active span (the simhost root, a propagated watch
        # submission) join that trace — per-image roots become its
        # remote-style children; with no ambient span the behavior
        # is byte-identical to the single-process path
        from ..obs.propagate import current_context
        amb = current_context()
        slots, failures = [], {}     # [(input idx, artifact)]
        roots: dict = {}             # input idx -> root span
        opt = self._image_opt(scan_secrets)
        scanner = LocalScanner(self.cache, db, memo=self.memo)
        prepared = []                # aligned with slots
        analyze_s = join_s = 0.0
        for idx, img in enumerate(images):
            name = getattr(img, "name", "")
            root = tracer.start_request(
                name,
                trace_id=amb.trace_id if amb else "",
                parent_span_id=amb.parent_span_id if amb else "")
            roots[idx] = root
            a = _CollectingImageArtifact(img, self.cache, opt)
            sp = tracer.child(root, "analyze")
            t1 = _time.perf_counter()
            try:
                with sp.activate():
                    a.reference = a.inspect()
            except Exception as e:   # noqa: BLE001 — a hostile or
                # broken artifact fails ITS slot with a typed cause;
                # the fleet keeps scanning (same isolation the
                # scheduled path gets from per-request analyze)
                sp.end("error")
                root.set("error", repr(e))
                root.end("failed")
                failures[idx] = _failed_slot(
                    name, e, trace_id=root.trace_id, tracer=tracer)
                continue
            analyze_s += _time.perf_counter() - t1
            # squash + advisory join for THIS image immediately,
            # instead of a fleet-wide barrier after every analyze:
            # with streaming sources, later images' layer fetches
            # are still in flight on the hostpool while this join
            # runs — the ISSUE's fetch/join overlap. The join span
            # keeps the phase visible to idle attribution
            # (host_pack_bound).
            t1 = _time.perf_counter()
            ref = a.reference
            # prepare emits its own "join" phase span (scan/local.py)
            with sp.activate():
                prepared.append(scanner.prepare(
                    ScanTarget(name=ref.name,
                               artifact_id=ref.id,
                               blob_ids=ref.blob_ids),
                    options))
            join_s += _time.perf_counter() - t1
            sp.end()
            slots.append((idx, a))
        artifacts = [a for _, a in slots]
        # one shared device window per surviving image: the sieve
        # and interval dispatches below serve the whole fleet, so
        # every slot's "device" span brackets the same wall interval
        dev_spans = {idx: tracer.child(roots[idx], "device",
                                       shared=True)
                     for idx, _ in slots}

        # ---- phase 2a: ENQUEUE the sieve dispatch (async) ----
        # the packing + enqueue runs on the host pool so the interval
        # enqueue below overlaps the SEGMENT PACKING too, not just
        # the device execution behind it; results are collected in
        # 2b — apply_layers' secret merge is re-derived afterwards
        # via applier.merge_layer_secrets, which is exactly the
        # secret part of the squash
        from .hostpool import get_host_pool
        t0 = _time.perf_counter()
        collected = [c for a in artifacts for c in a.collected]
        sec_stats: dict = {}       # only this batch's, never stale
        sieve_handle = sieve_future = None
        # pack/h2d_upload/db_upload phase spans attach under the
        # fleet's first shared device span (they bracket work done
        # once for the whole batch)
        sp0 = next(iter(dev_spans.values()), None)

        def _enqueue_sieve(files):
            if sp0 is None:
                return self.secret_scanner.dispatch_files(files)
            with sp0.activate():
                return self.secret_scanner.dispatch_files(files)

        if scan_secrets and collected:
            pool = get_host_pool()
            files = [(p, c) for _, p, c in collected]
            if pool is not None:
                sieve_future = pool.submit(_enqueue_sieve, files)
            else:
                sieve_handle = _enqueue_sieve(files)
        secret_s = _time.perf_counter() - t0

        # (the old phase-3 fleet-wide squash/join barrier now runs
        # per image inside phase 1, overlapping in-flight fetches)

        # ---- phase 4a: ENQUEUE the interval waves (async) ----
        # the slot runtime (docs/performance.md §8): dedup + wave
        # packing + donated-buffer uploads run here, every wave is
        # enqueued non-blocking into a bounded dispatch ring, and
        # the ring's drain thread materializes wave N while wave N+1
        # packs — so the device computes THROUGH the sieve collect
        # below instead of serializing after it. Joined AFTER the
        # sieve enqueue so device work stays enqueue-ordered on this
        # thread (the sched executor invariant).
        from ..detect.batch import collect_dispatch, \
            dispatch_jobs_async
        from .ring import (RING_METRICS, DispatchRing, RingMetrics,
                           TeeRingMetrics)
        t0 = _time.perf_counter()
        if sieve_future is not None:
            sieve_handle = sieve_future.result()
            secret_s += _time.perf_counter() - t0
            t0 = _time.perf_counter()
        all_jobs = []
        for idx, p in enumerate(prepared):
            for job in p.jobs:
                job.payload = (idx, job.payload)
                all_jobs.append(job)
        detected_by_image: dict = {}
        kstats: dict = {}          # this batch's dispatch counters
        ring = None
        # per-scan books: the ring reports into its own RingMetrics
        # (exact for THIS scan even when concurrent scans run their
        # own rings in-process) AND the process-wide RING_METRICS
        # the /metrics endpoint serves
        scan_rm = RingMetrics()
        if all_jobs and options.backend != "cpu-ref" \
                and self.dispatch_depth > 1:
            ring = DispatchRing(depth=self.dispatch_depth,
                                name="interval",
                                metrics=TeeRingMetrics(
                                    scan_rm, RING_METRICS))
        try:
            with activate_or_null(sp0):
                ih = dispatch_jobs_async(all_jobs,
                                         backend=options.backend,
                                         mesh=self.mesh,
                                         stats=kstats, ring=ring)
            interval_s = _time.perf_counter() - t0

            # ---- phase 2b: sieve collect + late secret merge ----
            # overlaps the interval waves still computing/draining
            t0 = _time.perf_counter()
            if sieve_handle is not None:
                from ..applier import merge_layer_secrets
                with activate_or_null(sp0):
                    # collect emits its own dfa_scan(fetch)/decode/
                    # verify phase spans; the blob patch + re-merge
                    # is collect-side host work too
                    found = self.secret_scanner.collect(sieve_handle)
                    with phase_span("decode", stage="patch"):
                        _patch_blobs(self.cache, artifacts, found)
                        sec_stats = dict(getattr(self.secret_scanner,
                                                 "stats", {}))
                        # re-merge EVERY artifact: a patched blob may
                        # be shared with artifacts whose own
                        # `collected` is empty (fleets share layers —
                        # the cached-layer case), and their prepare()
                        # ran before the patch landed. Nothing found
                        # → nothing patched → prepare()'s merge
                        # already stands.
                        if found:
                            for a, p in zip(artifacts, prepared):
                                blobs = [self.cache.get_blob(b)
                                         for b in
                                         a.reference.blob_ids]
                                p.detail.secrets = \
                                    merge_layer_secrets(blobs)
            secret_s += _time.perf_counter() - t0

            # ---- phase 4b: collect the interval waves ----
            t0 = _time.perf_counter()
            with activate_or_null(sp0):
                detected_pairs = collect_dispatch(ih)
            for idx, payload in detected_pairs:
                detected_by_image.setdefault(idx, []).append(payload)
            interval_s += _time.perf_counter() - t0
        finally:
            if ring is not None:
                ring.close()
        for sp in dev_spans.values():
            sp.end()

        ring1 = scan_rm.snapshot()
        ring_busy = ring1["slot_busy_s"]
        ring_overlap = ring1["slot_overlap_s"]
        jobs_in = kstats.get("jobs_in", len(all_jobs))
        self.last_stats = {
            "images": len(images),
            "analyze_s": round(analyze_s, 4),
            "secret_batch_s": round(secret_s, 4),
            "squash_join_s": round(join_s, 4),
            "interval_dispatch_s": round(interval_s, 4),
            "interval_device_s": round(
                kstats.get("device_s", 0.0), 4),
            "interval_jobs": len(all_jobs),
            "interval_jobs_unique": kstats.get("jobs_unique", 0),
            "interval_dedup_ratio": round(
                1.0 - kstats.get("jobs_unique", 0) / jobs_in, 4)
            if jobs_in else 0.0,
            # slot-runtime accounting for THIS scan (deltas of the
            # process-wide ring books): how much of the in-flight
            # wall ran >= 2 waves deep
            "dispatch_depth": self.dispatch_depth,
            "interval_waves": ih.waves,
            "dispatch_overlap_ratio": round(
                ring_overlap / ring_busy, 4) if ring_busy > 0
            else 0.0,
            "secret": sec_stats,
        }

        # ---- phase 5: assemble per image ----
        out = dict(failures)
        for local, ((idx, a), p) in enumerate(zip(slots, prepared)):
            sp = tracer.child(roots[idx], "report")
            with sp.activate():
                results, os_found = scanner.finish(
                    p, detected_by_image.get(local, []))
                ref = a.reference
                res = BatchScanResult(
                    name=ref.name,
                    report=Report(
                        artifact_name=ref.name,
                        artifact_type="container_image",
                        metadata=Metadata(
                            os=os_found,
                            image_id=ref.image_metadata.id,
                            diff_ids=ref.image_metadata.diff_ids,
                            repo_tags=ref.image_metadata.repo_tags,
                            image_config=ref.image_metadata
                            .image_config,
                        ),
                        results=results,
                    ))
            sp.end()
            root = roots[idx]
            b = getattr(a, "budget", None)
            degraded = b is not None and b.soft_faults
            if degraded:
                causes = [{"stage": "ingest", "kind": k,
                           "message": m} for k, m in b.soft_faults]
                if not root.noop:
                    from ..obs.trace import trace_cause
                    causes.append(trace_cause(tracer,
                                              root.trace_id))
                res.apply_degraded(causes)
            root.end("degraded" if degraded else "ok")
            out[idx] = res
        return [out[i] for i in range(len(images))]


    def scan_boms(self, boms: list,
                  options: Optional[ScanOptions] = None) -> list:
        """Batch-scan SBOM documents: ``boms`` is a list of
        (name, raw-bytes). BASELINE config #4's shape — no tar
        walking, no analyzers: decode → name-join → ONE interval
        dispatch for the whole fleet against the resident advisory
        tables."""
        if self.sched == "on":
            return self._scan_boms_scheduled(boms, options)
        from ..utils import defer_gc
        with defer_gc():
            return self._scan_boms(boms, options)

    def _scan_boms_scheduled(self, boms: list,
                             options: Optional[ScanOptions] = None)\
            -> list:
        options = options or ScanOptions(
            backend=self.backend, security_checks=["vuln"])
        sched = self.scheduler
        reqs = [sched.submit(self._bom_request(name, data, options),
                             block=True)
                for name, data in boms]
        out = []
        for (name, _), req in zip(boms, reqs):
            try:
                out.append(req.result())
            except Exception as e:       # noqa: BLE001
                out.append(_failed_slot(name, e))
        self.last_stats = {"sboms": len(boms),
                           "sched": sched.stats()}
        return out

    def _bom_request(self, name: str, data: bytes, options):
        from ..sched import AnalyzedWork, ScanRequest

        def analyze(req):
            from ..artifact.sbom import decode_to_blob
            db, release = self._store_view()
            if release is not None:
                prev = req.on_done

                def _done(r, _prev=prev, _rel=release):
                    try:
                        if _prev is not None:
                            _prev(r)
                    finally:
                        _rel()
                req.on_done = _done
            # a malformed document fails its own slot, never the
            # fleet (ValueError resolves this request only)
            atype, decoded, blob, blob_id = decode_to_blob(data)
            self.cache.put_blob(blob_id, blob)
            scanner = LocalScanner(self.cache, db,
                                   memo=self.memo)
            prepared = scanner.prepare(
                ScanTarget(name=name, artifact_id=blob_id,
                           blob_ids=[blob_id]), options)

            def finish(found, detected):
                results, os_found = scanner.finish(prepared,
                                                   detected)
                return BatchScanResult(
                    name=name,
                    report=Report(artifact_name=name,
                                  artifact_type=atype,
                                  metadata=Metadata(os=os_found),
                                  results=results,
                                  cyclonedx=decoded.cyclonedx))

            return AnalyzedWork(jobs=prepared.jobs, finish=finish)

        return ScanRequest(name=name, analyze=analyze,
                           deadline_s=getattr(options, "deadline_s",
                                              0.0) or 0.0)

    def _scan_boms(self, boms: list,
                   options: Optional[ScanOptions] = None) -> list:
        db, release = self._store_view()
        try:
            return self._scan_boms_db(db, boms, options)
        finally:
            if release is not None:
                release()

    def _scan_boms_db(self, db, boms: list,
                      options: Optional[ScanOptions] = None) -> list:
        import time as _time

        from ..artifact.sbom import decode_to_blob

        options = options or ScanOptions(
            backend=self.backend, security_checks=["vuln"])

        # ---- phase 1: decode + blob (host, pooled) ----
        # decode is the dominant host phase at fleet scale (BENCH_r05:
        # 4.2s of the 7.99s SBOM bench): json parse + purl decode per
        # component. The host pool spreads document decodes over the
        # spare cores in ≥64-doc slabs — per-doc tasks made pool
        # dispatch overhead the visible cost in the hostpool stats —
        # and repeated purl strings short-circuit in the purl parse
        # cache (docs/performance.md). A malformed document still
        # fails only its own slot.
        from .hostpool import map_in_pool
        t0 = _time.perf_counter()
        scanner = LocalScanner(self.cache, db, memo=self.memo)

        def decode_one(item):
            name, data = item
            try:
                return decode_to_blob(data)
            except ValueError as e:
                return e

        decodes = map_in_pool(decode_one, list(boms), chunk=64)
        prepared, metas, failures = [], [], {}
        for i, ((name, _data), dec) in enumerate(zip(boms,
                                                     decodes)):
            if isinstance(dec, ValueError):
                failures[i] = _failed_slot(name, dec)
                continue
            atype, decoded, blob, blob_id = dec
            self.cache.put_blob(blob_id, blob)
            prepared.append((i, scanner.prepare(
                ScanTarget(name=name, artifact_id=blob_id,
                           blob_ids=[blob_id]), options)))
            metas.append((i, name, atype, decoded))
        decode_s = _time.perf_counter() - t0

        # ---- phase 2: ONE interval dispatch over all SBOMs ----
        t0 = _time.perf_counter()
        all_jobs = []
        for idx, (_, p) in enumerate(prepared):
            for job in p.jobs:
                job.payload = (idx, job.payload)
                all_jobs.append(job)
        detected: dict = {}
        kstats: dict = {}
        for idx, payload in dispatch_jobs(all_jobs,
                                          backend=options.backend,
                                          mesh=self.mesh,
                                          stats=kstats):
            detected.setdefault(idx, []).append(payload)
        interval_s = _time.perf_counter() - t0

        # ---- phase 3: assemble ----
        out = dict(failures)
        for idx, ((i, p), (_, name, atype, decoded)) in \
                enumerate(zip(prepared, metas)):
            results, os_found = scanner.finish(
                p, detected.get(idx, []))
            out[i] = BatchScanResult(
                name=name,
                report=Report(artifact_name=name,
                              artifact_type=atype,
                              metadata=Metadata(os=os_found),
                              results=results,
                              cyclonedx=decoded.cyclonedx))
        jobs_in = kstats.get("jobs_in", len(all_jobs))
        self.last_stats = {
            "sboms": len(boms),
            "decode_s": round(decode_s, 4),
            "interval_dispatch_s": round(interval_s, 4),
            "interval_device_s": round(
                kstats.get("device_s", 0.0), 4),
            "interval_jobs": len(all_jobs),
            "interval_jobs_unique": kstats.get("jobs_unique", 0),
            "interval_dedup_ratio": round(
                1.0 - kstats.get("jobs_unique", 0) / jobs_in, 4)
            if jobs_in else 0.0,
        }
        return [out[i] for i in range(len(boms))]


class _CollectingImageArtifact(ImageArtifact):
    """ImageArtifact that defers secret scanning to the batch: its
    _batch_secrets records (layer, path, content) and returns nothing;
    the runner patches blobs once the global dispatch resolves."""

    def inspect(self):
        self.collected = []        # per-instance, even when cached
        return super().inspect()

    def _batch_secrets(self, candidates: list) -> dict:
        self.collected = [(li, "/" + path, content)
                          for li, path, content in candidates]
        return {}


class _SchedImageArtifact(_CollectingImageArtifact):
    """Collecting artifact that additionally announces which cache
    blobs this request will patch — registered with the scheduler
    BEFORE put_blob runs, so concurrent requests sharing a layer
    always see either the patched blob or the pending-write event."""

    _sched = None
    _sched_req = None

    def _inspect_layers(self, todo, blob_ids, base):
        self._sched_blob_ids = blob_ids
        return super()._inspect_layers(todo, blob_ids, base)

    def _batch_secrets(self, candidates: list) -> dict:
        if candidates and self._sched is not None and \
                self.opt.scan_secrets:
            ids = sorted({self._sched_blob_ids[li]
                          for li, _, _ in candidates})
            self._sched.register_blob_writes(ids, self._sched_req)
        return super()._batch_secrets(candidates)


def _failed_slot(name: str, err: BaseException, trace_id: str = "",
                 tracer=None) -> BatchScanResult:
    """One failed fleet slot with a machine-readable cause: the
    typed scheduler errors map to distinct kinds so a caller can
    tell backpressure (retryable) from deadline (not) from a broken
    image. When the slot was traced, a trailing ``obs/trace`` cause
    references the flight-recorder dump (the primary cause stays
    first — callers key off ``causes[0]``)."""
    import tarfile as _tarfile

    from ..guard.budget import GuardError
    from ..sched import (DeadlineExceeded, QueueFullError,
                         SchedulerClosed)
    if isinstance(err, GuardError):
        # ingest-guard trip (docs/robustness.md): resource-budget
        # (bombs, floods, deadlines) or malformed-archive
        # (traversal, truncation, undecodable names)
        stage, kind = err.stage, err.kind
    elif isinstance(err, DeadlineExceeded):
        stage, kind = "sched", "deadline_exceeded"
    elif isinstance(err, QueueFullError):
        stage, kind = "sched", "queue_full"
    elif isinstance(err, SchedulerClosed):
        stage, kind = "sched", "shutdown"
    elif isinstance(err, (OSError, ValueError,
                          _tarfile.TarError)):
        stage, kind = "host", "load_failed"
    else:
        stage, kind = "sched", "error"
    res = BatchScanResult(name=name, error=str(err)).mark_failed(
        stage, kind, str(err))
    if trace_id and tracer is not None:
        from ..obs.trace import trace_cause
        from ..types.report import FailureCause
        res.causes.append(
            FailureCause.coerce(trace_cause(tracer, trace_id)))
    return res


def _make_patch(cache, artifact):
    """Per-request secret patch: map batch sieve results back to this
    artifact's layers by the LOCAL candidate index and rewrite the
    affected cached blobs (the one-artifact slice of _patch_blobs)."""

    def patch(found: list) -> None:
        by_layer: dict = {}
        for idx, s in found:
            li = artifact.collected[idx][0]
            by_layer.setdefault(li, []).append(s)
        for li, secrets in by_layer.items():
            blob_id = artifact.reference.blob_ids[li]
            blob = cache.get_blob(blob_id)
            if blob is not None:
                secrets.sort(key=lambda s: s.file_path)
                for s in secrets:
                    s.findings.sort(key=lambda f: (f.rule_id,
                                                   f.start_line))
                blob.secrets = secrets
                cache.put_blob(blob_id, blob)

    return patch


def _patch_blobs(cache, artifacts, found) -> None:
    """Map batch results back to (artifact, layer) by the entry index
    scan_files returns and rewrite the affected cached blobs. Path
    strings are never consulted: fleets share file trees, so identical
    paths across images/layers are the common case, not the exception."""
    owners = []
    for a in artifacts:
        for li, _path, _ in a.collected:
            owners.append((a, li))
    by_blob: dict = {}
    for idx, s in found:
        a, li = owners[idx]
        by_blob.setdefault((a, li), []).append(s)
    for (a, li), secrets in by_blob.items():
        blob_id = a.reference.blob_ids[li]
        blob = cache.get_blob(blob_id)
        if blob is not None:
            secrets.sort(key=lambda s: s.file_path)
            for s in secrets:
                s.findings.sort(key=lambda f: (f.rule_id,
                                               f.start_line))
            blob.secrets = secrets
            cache.put_blob(blob_id, blob)
