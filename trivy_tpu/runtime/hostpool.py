"""Bounded host worker pool for packing and decode work
(docs/performance.md "host/device overlap").

One process-wide pool, sized to the host's spare cores, reserved for
tasks that NEVER block on scheduler events: segment-buffer packing
(secret/batch.py), SBOM decode (runtime/batch.py), and the direct
path's sieve enqueue. Keeping it separate from the scheduler's
worker pool is load-bearing, not stylistic — the scheduler pool runs
``finish`` tasks that wait on patch events only the device thread
resolves, so routing a pack task there while the device thread
blocks on its future could deadlock the pipeline. Tasks here are
pure compute with no cross-task waits, so the pool can be saturated
safely from any thread.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor

from ..utils import get_logger

log = get_logger("runtime.hostpool")

_POOL = None
_LOCK = threading.Lock()


def pool_size() -> int:
    """Bounded: the spare cores past the two the device thread and
    main loop keep busy, capped at 8 — which disables the pool
    entirely on 1-2 core hosts, where extra threads only add GIL
    contention. ``TRIVY_TPU_HOST_POOL`` overrides (0 disables)."""
    env = os.environ.get("TRIVY_TPU_HOST_POOL", "")
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            log.warning("bad TRIVY_TPU_HOST_POOL=%r ignored", env)
    return min(8, max(0, (os.cpu_count() or 1) - 2))


def get_host_pool():
    """The shared packing/decode pool, or None when disabled."""
    global _POOL
    if _POOL is None:
        with _LOCK:
            if _POOL is None:
                n = pool_size()
                if n == 0:
                    return None
                _POOL = ThreadPoolExecutor(
                    max_workers=n,
                    thread_name_prefix="trivy-hostpool")
    return _POOL


def map_in_pool(fn, items: list, chunk: int = 1) -> list:
    """``[fn(x) for x in items]`` spread over the pool (input order
    preserved). Falls back to the inline loop when the pool is
    disabled, the batch is too small to amortize the hops, or the
    CALLER is itself a pool worker — a task that blocks on
    ``pool.map`` of its own pool deadlocks the moment every worker
    is such a task (the direct path's sieve enqueue runs here and
    then packs segments through here again). ``fn`` must capture
    its own errors — a raising task would abandon the batch.

    ``chunk > 1`` batches that many items per pool task. Per-item
    submission made task-dispatch overhead the visible cost of the
    10k-document SBOM decode (BENCH_r05 ``decode_s``): a worker did
    ~0.4 ms of json parsing per ~hop. Decode callers pass 64 so
    every hop amortizes over a real slab of work."""
    from ..detect.metrics import DETECT_METRICS
    on_pool_thread = threading.current_thread().name.startswith(
        "trivy-hostpool")
    pool = get_host_pool() \
        if len(items) > max(8, chunk) and not on_pool_thread \
        else None
    if pool is None:
        return [fn(x) for x in items]
    if chunk > 1:
        slabs = [items[i:i + chunk]
                 for i in range(0, len(items), chunk)]
        DETECT_METRICS.inc("pack_tasks", len(slabs))
        out: list = []
        for part in pool.map(lambda slab: [fn(x) for x in slab],
                             slabs):
            out.extend(part)
        return out
    DETECT_METRICS.inc("pack_tasks", len(items))
    return list(pool.map(fn, items))
