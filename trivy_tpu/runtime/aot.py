"""AOT shape precompile + persistent compilation cache
(docs/serving.md "Elastic lifecycle").

PR 6 measured ~1.3 s × shapes × devices of first-hit kernel compile;
a scale-up pays that right in the middle of the SLO burn that
triggered it. This module makes the compile spike a boot cost, and a
cheap one:

* :func:`enable_persistent_cache` points jax's persistent
  compilation cache at an on-disk directory, so an executable
  compiled by ANY earlier boot of the same (jax version, backend)
  is deserialized instead of rebuilt — measured 0.34 s → 0.11 s per
  shape on the CPU sim.
* :func:`precompile_interval_shapes` / :func:`precompile_dfa_shapes`
  walk the SAME shape ladders the serving path buckets into
  (``ops/keywords._bucket`` for segment buffers,
  ``detect/batch._job_bucket`` for pair rows) and execute each
  jitted kernel once on zero inputs — populating the in-process jit
  cache (the first real request never traces) AND the persistent
  cache (the next replica's boot never rebuilds).
* a JSON **manifest** in the cache dir, keyed by
  ``sha256(jax version | backend | kind | shape | table hash)``,
  records which keyed shapes earlier boots compiled — the
  ``trivy_tpu_compile_cache_{hits,misses}`` split. Any component of
  the key changing (jax upgrade, backend change, new rule set / DB
  ladder) misses cleanly into a fresh entry; stale entries are
  inert, never wrong.

Zero inputs are safe for every kernel here: pad rows are inert by
construction (flags=0 matches nothing, zero segments hit no
pattern), which is the same property the serving-path pad ladder
already relies on.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Iterable, Optional, Tuple

from ..utils import get_logger

log = get_logger("runtime.aot")

MANIFEST_NAME = "trivy_tpu_aot_manifest.json"

# default ladder rungs warmed at boot: the small end, where first
# requests actually land (a cold fleet's first scans are small
# batches; the big rungs amortize their own compile once traffic
# exists to fill them)
DEFAULT_PAIR_BUCKETS = (64, 128, 256)
DEFAULT_SEG_BUCKETS = (256, 512)


class CompileCacheMetrics:
    """Cumulative compile-cache counters, one singleton per
    process. ``bytes`` is computed at snapshot time from the cache
    directory (the persistent cache is shared state on disk, not an
    in-process accumulator)."""

    _KEYS = ("hits", "misses", "precompiled")

    def __init__(self):
        self._lock = threading.Lock()
        self._c = {k: 0 for k in self._KEYS}
        self._dir = ""
        self._seconds = 0.0

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] += n

    def add_seconds(self, seconds: float) -> None:
        with self._lock:
            self._seconds += max(0.0, seconds)

    def set_dir(self, path: str) -> None:
        with self._lock:
            self._dir = path

    def reset(self) -> None:
        """Test hook — production code never calls this."""
        with self._lock:
            for k in self._c:
                self._c[k] = 0
            self._dir = ""
            self._seconds = 0.0

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["dir"] = self._dir
            out["seconds"] = round(self._seconds, 6)
        out["bytes"] = _dir_bytes(out["dir"])
        return out


COMPILE_CACHE_METRICS = CompileCacheMetrics()


def _dir_bytes(path: str) -> int:
    if not path or not os.path.isdir(path):
        return 0
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                # racing eviction/rewrite — a size gauge tolerates it
                continue
    return total


def enable_persistent_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``
    (created if missing), with the thresholds dropped so every
    kernel here qualifies. Returns False — and leaves the process on
    in-memory compilation only — if this jax build lacks the cache
    knobs; AOT warm-calling still works without it."""
    if not cache_dir:
        return False
    import jax
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
    except (AttributeError, ValueError, OSError) as e:
        log.warning("persistent compile cache unavailable: %r", e)
        return False
    COMPILE_CACHE_METRICS.set_dir(cache_dir)
    log.info("persistent compile cache at %s", cache_dir)
    return True


def cache_key(kind: str, shape_sig: str, table_hash: str = "") -> str:
    """Manifest key: jax version × backend × kernel kind × shape ×
    rule-set/table hash — the invalidation domain. Any component
    changing misses into a fresh entry."""
    import jax
    backend = jax.default_backend()
    raw = f"{jax.__version__}|{backend}|{kind}|{shape_sig}|" \
          f"{table_hash}"
    return hashlib.sha256(raw.encode()).hexdigest()[:32]


class _Manifest:
    """The keyed-shape manifest beside the cache entries. Read once,
    appended per precompile, written atomically — two replicas
    racing a boot at worst both compile (correct, just not free)."""

    def __init__(self, cache_dir: str):
        self.path = os.path.join(cache_dir, MANIFEST_NAME) \
            if cache_dir else ""
        self.entries: dict = {}
        if self.path and os.path.exists(self.path):
            try:
                with open(self.path, encoding="utf-8") as f:
                    doc = json.load(f)
                if isinstance(doc, dict):
                    self.entries = doc
            except (OSError, ValueError) as e:
                log.warning("unreadable AOT manifest %s: %r",
                            self.path, e)

    def seen(self, key: str) -> bool:
        return key in self.entries

    def note(self, key: str, meta: dict) -> None:
        self.entries[key] = meta
        if not self.path:
            return
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.entries, f, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError as e:
            log.warning("AOT manifest write failed: %r", e)


def _warm_call(fn, args, key: str, manifest: _Manifest,
               meta: dict) -> float:
    """Execute one jitted kernel on inert inputs, booking the
    manifest hit/miss and the compile wall. Returns seconds."""
    t0 = time.monotonic()
    if manifest.seen(key):
        COMPILE_CACHE_METRICS.inc("hits")
    else:
        COMPILE_CACHE_METRICS.inc("misses")
    out = fn(*args)
    # materialize: jit dispatch is async, and the point is to pay
    # the whole compile HERE, not on the first request
    try:
        import jax
        jax.block_until_ready(out)
    except (TypeError, ValueError):
        log.debug("non-blockable AOT output for %s", meta)
    dt = time.monotonic() - t0
    manifest.note(key, dict(meta, seconds=round(dt, 4)))
    COMPILE_CACHE_METRICS.inc("precompiled")
    COMPILE_CACHE_METRICS.add_seconds(dt)
    return dt


def precompile_interval_shapes(
        buckets: Iterable[int] = DEFAULT_PAIR_BUCKETS,
        cache_dir: str = "") -> dict:
    """Warm the classic interval kernel over the pair-row ladder
    (``detect/batch._job_bucket`` rungs). Zero rows are inert
    (flags=0 ⇒ not vulnerable), so execution is a no-op
    semantically; the value is the populated jit + persistent
    caches."""
    import numpy as np

    from ..ops.intervals import MAX_INTERVALS, interval_hits
    manifest = _Manifest(cache_dir)
    out = {"kernel": "interval", "shapes": [], "seconds": 0.0}
    for p in sorted(set(int(b) for b in buckets if int(b) > 0)):
        rank = np.zeros(p, np.int32)
        iv = np.zeros((p, MAX_INTERVALS), np.int32)
        flags = np.zeros(p, np.int32)
        key = cache_key("interval", f"P{p}xM{MAX_INTERVALS}")
        dt = _warm_call(interval_hits,
                        (rank, iv, iv, iv, iv, flags),
                        key, manifest,
                        {"kernel": "interval", "P": p})
        out["shapes"].append(p)
        out["seconds"] += dt
    out["seconds"] = round(out["seconds"], 4)
    return out


def precompile_dfa_shapes(table, run_specs: tuple = (),
                          buckets: Iterable[int] =
                          DEFAULT_SEG_BUCKETS,
                          cache_dir: str = "",
                          platform: str = "") -> dict:
    """Warm the DFA fused sieve over the segment-buffer ladder
    (``ops/keywords._bucket`` rungs × SEG_LEN columns), staging the
    table's resident arrays as a side effect — exactly the prewarm
    staging order a joining replica wants. Keyed on the table's
    ``rules_hash`` so a custom rule set misses into its own
    entries."""
    import jax
    import numpy as np

    from ..secret.batch import SEG_LEN
    platform = platform or jax.default_backend()
    manifest = _Manifest(cache_dir)
    out = {"kernel": "dfa_fused", "shapes": [], "seconds": 0.0}
    tbl = table.device_tables()
    fn = table.fused_sieve(tuple(run_specs), platform)
    for b in sorted(set(int(x) for x in buckets if int(x) > 0)):
        # the sieve donates its segment buffer; hand it a fresh one
        seg = jax.device_put(np.zeros((b, SEG_LEN), np.uint8))
        key = cache_key("dfa_fused", f"B{b}xL{SEG_LEN}",
                        table.rules_hash)
        dt = _warm_call(fn, (seg,) + tuple(tbl), key, manifest,
                        {"kernel": "dfa_fused", "B": b,
                         "rules_hash": table.rules_hash})
        out["shapes"].append(b)
        out["seconds"] += dt
    out["seconds"] = round(out["seconds"], 4)
    return out


def boot_precompile(cache_dir: str = "",
                    dfa_table=None,
                    run_specs: tuple = (),
                    pair_buckets: Optional[Tuple[int, ...]] = None,
                    seg_buckets: Optional[Tuple[int, ...]] = None,
                    ) -> dict:
    """The boot-time glue the server/CLI calls once: enable the
    persistent cache, then warm the interval and (when a table is
    supplied) DFA ladders. Never raises — a broken cache dir costs
    compile time, not the boot."""
    t0 = time.monotonic()
    persistent = enable_persistent_cache(cache_dir)
    summary = {"cache_dir": cache_dir, "persistent": persistent,
               "kernels": []}
    try:
        summary["kernels"].append(precompile_interval_shapes(
            pair_buckets or DEFAULT_PAIR_BUCKETS, cache_dir))
        if dfa_table is not None:
            summary["kernels"].append(precompile_dfa_shapes(
                dfa_table, run_specs,
                seg_buckets or DEFAULT_SEG_BUCKETS, cache_dir))
    except (RuntimeError, OSError, ValueError) as e:
        # AOT warmth is an optimization: a failed precompile means
        # the first request pays the compile, like before this PR
        log.warning("boot precompile degraded: %r", e)
        summary["error"] = repr(e)
    summary["seconds"] = round(time.monotonic() - t0, 4)
    log.info("boot precompile: %d kernels in %.2fs "
             "(persistent=%s)", len(summary["kernels"]),
             summary["seconds"], persistent)
    return summary
