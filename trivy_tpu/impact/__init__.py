"""Fleet-wide inverted findings index: "which of my images did
CVE-X just break?" (docs/serving.md "CVE impact queries & push
re-scans").

* :mod:`impact.index` — the (package, CVE) → layers → images index,
  maintained write-through from the findings memo;
* :mod:`impact.federate` — the router-side fan-out that unions
  replica slices into a fleet answer with Federator semantics;
* :mod:`impact.push` — hot-swap delta → high-priority re-scan
  events on the watch loop;
* :mod:`impact.metrics` — process-wide counters on ``GET /metrics``.
"""

from .federate import federated_impact, fetch_impact
from .index import (IMPACT_KEY_PREFIX, ImpactIndex,
                    brute_force_invert, entry_postings,
                    image_key, is_impact_key)
from .metrics import IMPACT_METRICS
from .push import IMPACT_RESCAN_PRIORITY, ImpactPusher

__all__ = [
    "IMPACT_KEY_PREFIX", "IMPACT_METRICS", "IMPACT_RESCAN_PRIORITY",
    "ImpactIndex", "ImpactPusher", "brute_force_invert",
    "entry_postings", "federated_impact", "fetch_impact",
    "image_key", "is_impact_key",
]
