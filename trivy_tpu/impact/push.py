"""Hot-swap push re-scans (docs/serving.md "CVE impact queries &
push re-scans").

When ``db update`` hot-swaps a new advisory generation in, the memo's
delta re-match already knows EXACTLY which layers picked up new
verdicts. This module turns that knowledge into a push stream: the
index maps the newly-affected layers to their images/tenants, and
the pusher enqueues one high-priority, tenant-scoped
:class:`watch.source.PushEvent` per image onto the watch source the
server already runs. The event digest uses the same formula as the
registry/synthetic sources (``sha256(path)``), so a swap-storm push
folds into any pending or in-flight scan of the same image via the
loop's existing debounce — no duplicate device work.
"""

from __future__ import annotations

import hashlib
import os
import threading

from ..utils import get_logger
from ..watch.metrics import WATCH_METRICS
from ..watch.source import PushEvent

log = get_logger("impact.push")

# above default watch traffic (0): a swap's re-scans answer "am I
# still compliant?" and jump the queue over routine pushes
IMPACT_RESCAN_PRIORITY = 50


class ImpactPusher:
    """Feeds newly-affected images into a watch source as
    high-priority re-scan events."""

    def __init__(self, source, priority: int = IMPACT_RESCAN_PRIORITY,
                 traceparent: str = ""):
        self.source = source
        self.priority = priority
        self.traceparent = traceparent
        self._lock = threading.Lock()
        self._n = 0

    def push(self, images) -> int:
        """``[(image_path, tenant), ...]`` → events on the source.
        Returns the number enqueued; counts into
        ``trivy_tpu_watch_impact_rescans_total``."""
        events = []
        with self._lock:
            for path, tenant in images:
                events.append(PushEvent(
                    digest="sha256:" + hashlib.sha256(
                        path.encode()).hexdigest(),
                    ref=os.path.basename(str(path)),
                    path=str(path),
                    tenant=tenant,
                    priority=self.priority,
                    event_id=f"impact-{self._n}",
                    traceparent=self.traceparent))
                self._n += 1
        if not events:
            return 0
        WATCH_METRICS.inc("impact_rescans", len(events))
        self.source.push_events(events)
        log.info("impact push: %d re-scan events queued",
                 len(events))
        return len(events)
