"""Federated CVE impact queries (docs/serving.md "CVE impact
queries & push re-scans").

The router front answers ``GET /impact?cve=`` by fanning the query
out to every replica's local slice and unioning the answers —
Federator semantics throughout (obs/federate.py): bounded fan-in,
per-peer timeout, and a ``complete`` flag that goes False the moment
ANY peer is down or answered from a degraded index. A partial fleet
gives a partial answer, never an error: ring slices partition the
layer-digest space, so the union over the replicas that did answer
is exact for the slices they own.
"""

from __future__ import annotations

import json
import threading

from ..utils import get_logger

log = get_logger("impact.federate")


def fetch_impact(url: str, cve: str, token: str = "",
                 token_header: str = "Trivy-Token",
                 timeout_s: float = 2.0) -> dict:
    """One replica's slice — raises on transport/decode failure (the
    caller's fan-out absorbs it into a down row)."""
    import urllib.parse
    import urllib.request
    req = urllib.request.Request(
        url.rstrip("/") + "/impact?cve="
        + urllib.parse.quote(cve, safe=""))
    if token:
        req.add_header(token_header, token)
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        doc = json.loads(resp.read().decode("utf-8"))
    if not isinstance(doc, dict):
        raise ValueError("impact answer is not a JSON object")
    return doc


def federated_impact(replicas, cve: str, token: str = "",
                     token_header: str = "Trivy-Token",
                     timeout_s: float = 2.0, fan_in: int = 8,
                     fetch=None) -> dict:
    """Union of every replica's owned slice for one CVE.

    ``replicas`` is ``[(name, url), ...]`` (the router ring's handle
    list); ``fetch(url, cve) -> dict`` is injectable so unit tests
    exercise the merge without sockets. Never raises."""
    fetch = fetch or (lambda u, c: fetch_impact(
        u, c, token=token, token_header=token_header,
        timeout_s=timeout_s))
    replicas = list(replicas)
    rows: list = [None] * len(replicas)
    sem = threading.Semaphore(max(1, int(fan_in)))

    def work(i: int, name: str, url: str) -> None:
        with sem:
            try:
                doc = fetch(url, cve)
            except Exception as e:  # noqa: BLE001 — a down peer is
                # the condition federation exists to absorb: mark it,
                # answer partially
                rows[i] = {"replica": name, "up": False,
                           "complete": False, "error": repr(e)}
                return
            rows[i] = {"replica": name, "up": True,
                       "complete": bool(doc.get("complete", True)),
                       "error": "", "answer": doc}

    threads = [threading.Thread(target=work, args=(i, n, u),
                                daemon=True)
               for i, (n, u) in enumerate(replicas)]
    for t in threads:
        t.start()
    for t in threads:
        # second-layer backstop over the per-fetch timeout, so a
        # wedged socket cannot wedge the query
        t.join(timeout_s * 2 + 1.0)
    for i, (name, _url) in enumerate(replicas):
        if rows[i] is None:
            rows[i] = {"replica": name, "up": False,
                       "complete": False, "error": "query timeout"}

    packages: set = set()
    layers: set = set()
    images: dict = {}
    for row in rows:
        answer = row.get("answer")
        if not answer:
            continue
        packages.update(a for a in answer.get("packages", ())
                        if isinstance(a, str))
        layers.update(a for a in answer.get("layers", ())
                      if isinstance(a, str))
        for pair in answer.get("images", ()):
            if isinstance(pair, (list, tuple)) and len(pair) == 2:
                images[str(pair[0])] = str(pair[1])
    complete = all(r["up"] and r["complete"] for r in rows) \
        if rows else True
    return {
        "cve": cve,
        "packages": sorted(packages),
        "layers": sorted(layers),
        "images": sorted([i, t] for i, t in images.items()),
        "complete": complete,
        "replicas": [{k: r[k] for k in
                      ("replica", "up", "complete", "error")}
                     for r in rows],
    }
