"""Impact-index metrics (docs/serving.md "CVE impact queries &
push re-scans").

Process-wide singleton like ``memo.metrics.MEMO_METRICS``: one
impact index serves every scanner in a replica, and the numbers an
operator watches (update/query/rebuild counters, cumulative
maintenance wall time for the <2% write-through overhead budget) are
totals on ``GET /metrics`` — JSON and Prometheus text alike.
"""

from __future__ import annotations

import threading


class ImpactMetrics:
    """Cumulative counters + maintenance wall-clock for the inverted
    findings index."""

    _KEYS = (
        # index maintenance (write-through side effects of memo
        # stores, corrupt drops, and hot-swap migrations);
        # image_updates counts image-record changes, distinct from
        # the live-image gauge ImpactIndex.stats() reports as images
        "updates", "drops", "renames", "image_updates",
        # image-record persistence to the shared memo tier (skips
        # are unchanged records — the swap-storm dedupe)
        "persist_puts", "persist_skips",
        # query traffic (local slice lookups, not federated fan-outs)
        "queries",
        # rebuild/recovery passes (reshard, cold start); degraded =
        # the backing scan_keys reported an incomplete iteration
        "rebuilds", "rebuild_entries", "rebuild_degraded",
        # hot-swap push stream: batches emitted, images queued
        "push_batches", "push_images",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._c = {k: 0 for k in self._KEYS}
        self._maintenance_s = 0.0

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            # lint: disable=unbounded-label-cardinality -- counter
            # names are code-literal call sites, never
            # request-derived strings
            self._c[name] = self._c.get(name, 0) + n

    def add_maintenance(self, seconds: float) -> None:
        with self._lock:
            self._maintenance_s += max(0.0, seconds)

    def reset(self) -> None:
        """Test hook — production code never calls this."""
        with self._lock:
            for k in self._c:
                self._c[k] = 0
            self._maintenance_s = 0.0

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["maintenance_s"] = round(self._maintenance_s, 6)
        return out


IMPACT_METRICS = ImpactMetrics()
