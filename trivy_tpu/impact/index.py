"""The inverted findings index: (package, CVE) → affected layer
digests → images/tenants (docs/serving.md "CVE impact queries &
push re-scans").

The memo tier (PR 9) already holds, per content-addressed layer, the
exact detection verdicts a scan served — as *indices* into the
candidate-advisory rows a generation compiles. This module inverts
that: :func:`entry_postings` rebuilds a memo entry's candidate rows
exactly the way the delta re-match does (detect/rematch.py), reads
the verdict indices back as ``(bucket, pkg, Advisory)`` row metadata,
and yields the ``(package, CVE)`` pairs the layer is affected by.
One function drives BOTH the incremental write-through (memo store /
hot-swap hooks in memo/findings.py) and the brute-force inversion
(:func:`brute_force_invert`), so the property test's byte-identity
holds by construction, not by luck.

Sharding: the index carries an optional ``owns(layer_digest)``
predicate — the router's consistent-hash ring slice. Ingest is
unfiltered (a replica indexes what its memo sees), queries and
snapshots filter to the owned slice, and the fleet answer is the
federated union of slices (impact/federate.py). On a reshard the
successor re-arms ``owns`` with its new slice and :meth:`rebuild`\\ s
from the shared memo tier — exactness is the kill-one-replica test.

Image records (image → tenant + layer set) are persisted write-
through to the same memo store under ``impact-``-prefixed keys with
their own checksummed envelope, so a rebuilt replica recovers the
layer→image join without re-scanning anything.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Optional

from ..utils import get_logger
from .metrics import IMPACT_METRICS

log = get_logger("impact")

# memo keys are 40-hex (memo/keys.make_key); this prefix can never
# collide with one, and stays fs-store path-safe (alnum + dash)
IMPACT_KEY_PREFIX = "impact-"
IMPACT_SCHEMA = 1


def is_impact_key(key: str) -> bool:
    return key.startswith(IMPACT_KEY_PREFIX)


def image_key(image: str) -> str:
    """Store key for one image record — content-addressed so the
    same image always lands on the same key (idempotent put)."""
    h = hashlib.sha256(image.encode("utf-8", "replace")).hexdigest()
    return IMPACT_KEY_PREFIX + h[:40]


def _rec_checksum(payload: dict) -> str:
    data = json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(data).hexdigest()[:16]


def encode_image_record(image: str, tenant: str,
                        blobs: list) -> bytes:
    payload = {"v": IMPACT_SCHEMA, "image": image, "tenant": tenant,
               "blobs": sorted(blobs)}
    return json.dumps({"rec": payload,
                       "sum": _rec_checksum(payload)},
                      sort_keys=True,
                      separators=(",", ":")).encode()


def decode_image_record(raw: bytes) -> Optional[dict]:
    """None on any corruption — a torn record degrades to 'image
    unknown until next scan', never an error."""
    try:
        doc = json.loads(raw.decode("utf-8"))
        payload = doc["rec"]
        if doc.get("sum") != _rec_checksum(payload):
            raise ValueError("impact record checksum mismatch")
        if payload.get("v") != IMPACT_SCHEMA:
            raise ValueError("impact record schema mismatch")
        if not isinstance(payload.get("image"), str) or \
                not isinstance(payload.get("blobs"), list):
            raise ValueError("impact record shape")
        return payload
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
        return None


def entry_postings(entry: dict, cdb) -> tuple:
    """One memo entry → sorted ``((pkg, cve), ...)`` pairs its layer
    is affected by under generation ``cdb``.

    Candidate rows rebuild EXACTLY as detect/rematch.py builds its
    re-match jobs (same driver gating, same ordering), so the stored
    verdict indices address the same rows the live scan's jobs came
    from. Non-compiled stores (fixture AdvisoryStore) have no row
    tables — they yield no postings and the index simply stays empty
    for them."""
    if not hasattr(cdb, "rows_meta"):
        return ()
    from ..detect.rematch import _os_rows
    pairs = set()
    for sub in entry.get("subs", {}).values():
        hits = sub.get("hits") or ()
        if not hits:
            continue
        if sub.get("kind") == "os":
            rows = _os_rows(cdb, sub)
            if rows is None:
                continue
        else:
            rows = cdb.candidate_rows_prefix(sub.get("bucket", ""),
                                             sub.get("name", ""))
        for i in hits:
            if not isinstance(i, int) or not 0 <= i < len(rows):
                continue
            _bucket, pkg, adv = cdb.rows_meta[rows[i]]
            cve = getattr(adv, "vulnerability_id", "")
            if cve:
                pairs.add((pkg, cve))
    return tuple(sorted(pairs))


class ImpactIndex:
    """One replica's slice of the fleet-wide inverted index.

    All state lives under one re-entrant lock; maintenance calls are
    O(entry postings) — they ride the scan/finish path, so the <2%
    overhead budget (bench ``--config impact``) is the design
    constraint, not an afterthought."""

    def __init__(self, store=None, owns=None, name: str = "",
                 pusher=None):
        # store: the shared memo tier (ResilientMemoStore or raw) —
        # image records persist write-through so a successor replica
        # recovers the layer→image join; None = in-memory only
        self.store = store
        self.owns = owns              # ring slice predicate, or None
        self.name = name
        self.pusher = pusher          # impact.push.ImpactPusher
        self.complete = True          # last rebuild's coverage flag
        self._lock = threading.RLock()
        self._entries: dict = {}      # memo key -> (blob, postings)
        self._post: dict = {}         # (pkg, cve) -> {blob: refcount}
        self._cves: dict = {}         # cve -> set(pkg)
        self._images: dict = {}       # image -> (tenant, blobs tuple)
        self._by_blob: dict = {}      # blob -> set(image)

    # ---- ownership ----

    def _owned(self, blob: str) -> bool:
        return self.owns is None or bool(self.owns(blob))

    def set_owner(self, owns) -> None:
        """Re-arm the ring slice (reshard). Postings stay resident —
        only the query-time filter moves, so handing a slice over
        needs no index surgery on the survivor."""
        with self._lock:
            self.owns = owns

    # ---- write-through maintenance ----

    def _unref(self, pair: tuple, blob: str) -> None:
        m = self._post.get(pair)
        if m is None:
            return
        n = m.get(blob, 0) - 1
        if n > 0:
            m[blob] = n
            return
        m.pop(blob, None)
        if not m:
            del self._post[pair]
            pkgs = self._cves.get(pair[1])
            if pkgs is not None:
                pkgs.discard(pair[0])
                if not pkgs:
                    del self._cves[pair[1]]

    def set_entry(self, key: str, blob: str, postings) -> tuple:
        """Install one memo entry's postings; returns the ``(pkg,
        cve)`` pairs that became NEWLY present for ``blob`` (refcount
        0 → 1) — the hot-swap push stream's trigger set. Diffs
        against the prior postings under the same key, so re-storing
        an unchanged entry adds nothing."""
        t0 = time.perf_counter()
        postings = tuple(sorted({tuple(p) for p in postings}))
        added = []
        with self._lock:
            old = self._entries.get(key)
            if old is not None and old[0] != blob:
                # a key can't change blobs (the key encodes it), but
                # defend: fully retire the stale attribution
                for pair in old[1]:
                    self._unref(pair, old[0])
                old = None
            old_set = set(old[1]) if old is not None else set()
            new_set = set(postings)
            for pair in old_set - new_set:
                self._unref(pair, blob)
            for pair in new_set - old_set:
                m = self._post.setdefault(pair, {})
                n = m.get(blob, 0)
                m[blob] = n + 1
                if n == 0:
                    added.append(pair)
                self._cves.setdefault(pair[1], set()).add(pair[0])
            if postings:
                self._entries[key] = (blob, postings)
            else:
                self._entries.pop(key, None)
        IMPACT_METRICS.inc("updates")
        IMPACT_METRICS.add_maintenance(time.perf_counter() - t0)
        return tuple(sorted(added))

    def drop_entry(self, key: str) -> None:
        """Memo entry evicted (corrupt drop, old-generation delete):
        release its postings."""
        t0 = time.perf_counter()
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                for pair in old[1]:
                    self._unref(pair, old[0])
        if old is not None:
            IMPACT_METRICS.inc("drops")
            IMPACT_METRICS.add_maintenance(time.perf_counter() - t0)

    def rename_entry(self, old_key: str, new_key: str) -> None:
        """Hot-swap migration of a delta-untouched entry: same blob,
        same advisory content, new context key — postings carry over
        byte-identically, no re-derivation."""
        if old_key == new_key:
            return
        with self._lock:
            rec = self._entries.pop(old_key, None)
            if rec is not None:
                self._entries[new_key] = rec
        if rec is not None:
            IMPACT_METRICS.inc("renames")

    def observe_image(self, image: str, blob_ids, tenant: str = "",
                      persist: bool = True) -> None:
        """Record (or refresh) one image → layer-set edge. Unchanged
        records skip the store put — a swap-storm of re-scans does
        no redundant tier writes."""
        if not image:
            return
        t0 = time.perf_counter()
        rec = (tenant, tuple(sorted(set(blob_ids))))
        if not rec[1]:
            return
        with self._lock:
            old = self._images.get(image)
            if old == rec:
                changed = False
            else:
                changed = True
                if old is not None:
                    for b in old[1]:
                        imgs = self._by_blob.get(b)
                        if imgs is not None:
                            imgs.discard(image)
                            if not imgs:
                                del self._by_blob[b]
                self._images[image] = rec
                for b in rec[1]:
                    self._by_blob.setdefault(b, set()).add(image)
        if changed:
            IMPACT_METRICS.inc("image_updates")
        if persist and self.store is not None:
            if changed:
                self.store.put(image_key(image),
                               encode_image_record(image, tenant,
                                                   list(rec[1])))
                IMPACT_METRICS.inc("persist_puts")
            else:
                IMPACT_METRICS.inc("persist_skips")
        IMPACT_METRICS.add_maintenance(time.perf_counter() - t0)

    # ---- queries ----

    def query(self, cve: str) -> dict:
        """This replica's slice of "which layers/images does CVE-X
        affect": layers filtered to the owned ring slice, images that
        carry at least one such layer. ``complete`` mirrors the last
        rebuild's coverage — Federator semantics, never an error."""
        IMPACT_METRICS.inc("queries")
        with self._lock:
            blobs = set()
            pkgs = set()
            for pkg in self._cves.get(cve, ()):
                for b in self._post.get((pkg, cve), ()):
                    if self._owned(b):
                        blobs.add(b)
                        pkgs.add(pkg)
            images = {}
            for b in blobs:
                for img in self._by_blob.get(b, ()):
                    images[img] = self._images[img][0]
            complete = self.complete
        return {"cve": cve,
                "packages": sorted(pkgs),
                "layers": sorted(blobs),
                "images": sorted([i, t] for i, t in images.items()),
                "complete": complete}

    def images_for_blobs(self, blobs) -> list:
        """Owned-slice images carrying any of ``blobs`` →
        ``[(image, tenant), ...]`` — the hot-swap push stream's
        payload."""
        with self._lock:
            out = {}
            for b in blobs:
                if not self._owned(b):
                    continue
                for img in self._by_blob.get(b, ()):
                    out[img] = self._images[img][0]
        return sorted(out.items())

    def emit_push(self, blobs) -> int:
        """Newly-affected blobs (a hot swap's delta) → high-priority
        re-scan push events via the attached pusher. No pusher, no
        push — the index itself stays passive."""
        if self.pusher is None or not blobs:
            return 0
        images = self.images_for_blobs(blobs)
        if not images:
            return 0
        n = self.pusher.push(images)
        IMPACT_METRICS.inc("push_batches")
        IMPACT_METRICS.inc("push_images", n)
        return n

    # ---- snapshots / rebuild ----

    def postings_snapshot(self) -> dict:
        """Canonical owned-slice view for byte-identity checks:
        stable ordering, no refcounts (they are maintenance detail,
        not answers)."""
        with self._lock:
            postings = []
            for (pkg, cve), m in sorted(self._post.items()):
                owned = sorted(b for b in m if self._owned(b))
                if owned:
                    postings.append([pkg, cve, owned])
            images = sorted(
                [img, t, list(bs)]
                for img, (t, bs) in self._images.items())
        return {"postings": postings, "images": images}

    def stats(self) -> dict:
        with self._lock:
            out = {"entries": len(self._entries),
                   "pairs": len(self._post),
                   "cves": len(self._cves),
                   "images": len(self._images),
                   "complete": self.complete}
        out.update(IMPACT_METRICS.snapshot())
        return out

    def rebuild(self, memo, db) -> dict:
        """Recover this replica's slice from the shared memo tier:
        walk ``scan_keys``, re-derive every current-generation
        entry's postings via :func:`entry_postings`, reload persisted
        image records. An incomplete key scan (tier outage mid-walk)
        degrades to a partial index flagged ``complete=False`` —
        queries answer partially, mirroring Federator semantics."""
        t0 = time.perf_counter()
        keys, complete = memo.store.scan_keys("")
        ctx = memo.ctx_for(db)
        with self._lock:
            self._entries.clear()
            self._post.clear()
            self._cves.clear()
            self._images.clear()
            self._by_blob.clear()
        n_entries = n_images = 0
        for key in keys:
            if is_impact_key(key):
                raw = memo.store.get(key)
                rec = decode_image_record(raw) \
                    if raw is not None else None
                if rec is None:
                    continue
                self.observe_image(rec["image"], rec["blobs"],
                                   tenant=rec.get("tenant", ""),
                                   persist=False)
                n_images += 1
                continue
            entry = memo._load(key)
            if entry is None or entry.get("ctx") != ctx:
                continue
            self.set_entry(key, entry.get("blob", ""),
                           entry_postings(entry, db))
            n_entries += 1
        with self._lock:
            self.complete = complete
        IMPACT_METRICS.inc("rebuilds")
        IMPACT_METRICS.inc("rebuild_entries", n_entries)
        if not complete:
            IMPACT_METRICS.inc("rebuild_degraded")
        wall = time.perf_counter() - t0
        log.info("impact rebuild%s: %d entries, %d image records "
                 "in %.3fs (complete=%s)",
                 f" [{self.name}]" if self.name else "",
                 n_entries, n_images, wall, complete)
        return {"entries": n_entries, "images": n_images,
                "complete": complete, "wall_s": round(wall, 4)}


def brute_force_invert(memo, db, owns=None) -> dict:
    """Ground truth for the property test: a FRESH index rebuilt
    from the store, same ownership filter — the incremental index
    must match this snapshot byte-for-byte."""
    idx = ImpactIndex(owns=owns)
    idx.rebuild(memo, db)
    return idx.postings_snapshot()
