"""Advisory database: the trivy-db bucket schema, flattened for batch
detection.

Reference schema (SURVEY.md §2.3 / trivy-db): top-level buckets per
source (``alpine 3.10``, ``debian 11``, ``pip::…``) → nested bucket
per package → key = CVE id, value = JSON advisory; plus a
``vulnerability`` bucket keyed by CVE id with severity/CVSS detail,
and ``data-source`` metadata.
"""

from .store import Advisory, AdvisoryStore, VulnerabilityDetail
from .fixtures import load_fixtures
from .compiled import CompiledDB, SwappableStore

__all__ = ["Advisory", "AdvisoryStore", "VulnerabilityDetail",
           "load_fixtures", "CompiledDB", "SwappableStore"]
