"""Pure-Python BoltDB (bbolt) reader — read-only, mmap-based.

trivy-db ships as a single BoltDB file inside an OCI artifact
(reference: pkg/db/db.go:90-120 downloads it; trivy-db's schema is
top-level buckets per source → nested bucket per package → key=CVE,
value=JSON advisory; usage pkg/detector/library/driver.go:83-91).
This reader implements the on-disk format directly — meta pages,
branch/leaf pages, inline buckets, overflow pages — so advisory
ingestion needs no Go toolchain.

Format (bbolt db.go / page.go):
  page header:  id u64 | flags u16 | count u16 | overflow u32
  meta page:    header + magic 0xED0CDAED u32 | version u32 |
                pageSize u32 | flags u32 | root bucket{pgid u64,
                sequence u64} | freelist u64 | pgid u64 | txid u64 |
                checksum u64
  branch elem:  pos u32 | ksize u32 | pgid u64
  leaf elem:    flags u32 | pos u32 | ksize u32 | vsize u32
  bucket value: root pgid u64 | sequence u64 [+ inline page if root=0]
"""

from __future__ import annotations

import mmap
import struct
from typing import Iterator, Optional

MAGIC = 0xED0CDAED
PAGE_HEADER = 16          # id(8) flags(2) count(2) overflow(4)
LEAF_ELEM = 16            # flags(4) pos(4) ksize(4) vsize(4)
BRANCH_ELEM = 16          # pos(4) ksize(4) pgid(8)
BUCKET_HEADER = 16        # root(8) sequence(8)

FLAG_BRANCH = 0x01
FLAG_LEAF = 0x02
FLAG_META = 0x04
FLAG_FREELIST = 0x10

LEAF_FLAG_BUCKET = 0x01


class CorruptDB(ValueError):
    pass


def _fnv64a(data: bytes) -> int:
    """FNV-64a (bbolt meta.sum64) — validates meta checksums."""
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def _unpack(fmt: str, buf, off: int) -> tuple:
    try:
        return struct.unpack_from(fmt, buf, off)
    except struct.error as e:
        raise CorruptDB(f"truncated page data at {off}: {e}")


class Bucket:
    """Read-only view of one bucket."""

    def __init__(self, db: "BoltDB", root_pgid: int,
                 inline: Optional[tuple] = None):
        self.db = db
        self.root_pgid = root_pgid
        self._inline = inline          # (buf, offset) for root==0

    # -- page access --

    def _page(self, pgid: int) -> tuple:
        return self.db._page(pgid)

    def _root_page(self) -> tuple:
        if self._inline is not None:
            return self._inline
        return self._page(self.root_pgid)

    # -- iteration --

    def _iter_page(self, buf, off) -> Iterator[tuple]:
        """Yields (key, value, leaf_flags), descending branches."""
        _, flags, count, _ = self.db._header(buf, off)
        if flags & FLAG_LEAF:
            base = off + PAGE_HEADER
            for i in range(count):
                eoff = base + i * LEAF_ELEM
                lf, pos, ksize, vsize = _unpack(
                    "<IIII", buf, eoff)
                kstart = eoff + pos
                key = bytes(buf[kstart:kstart + ksize])
                val = bytes(buf[kstart + ksize:
                                kstart + ksize + vsize])
                yield key, val, lf
        elif flags & FLAG_BRANCH:
            base = off + PAGE_HEADER
            for i in range(count):
                eoff = base + i * BRANCH_ELEM
                _pos, _ksize, pgid = _unpack(
                    "<IIQ", buf, eoff)
                cbuf, coff = self._page(pgid)
                yield from self._iter_page(cbuf, coff)
        else:
            raise CorruptDB(f"page is neither branch nor leaf "
                            f"(flags={flags:#x})")

    def items(self) -> Iterator[tuple]:
        """(key, value) pairs; nested buckets are skipped."""
        buf, off = self._root_page()
        for key, val, lf in self._iter_page(buf, off):
            if not (lf & LEAF_FLAG_BUCKET):
                yield key, val

    def buckets(self) -> Iterator[tuple]:
        """(name, Bucket) for nested buckets."""
        buf, off = self._root_page()
        for key, val, lf in self._iter_page(buf, off):
            if lf & LEAF_FLAG_BUCKET:
                yield key, self.db._open_bucket(val)

    def bucket(self, name: bytes) -> Optional["Bucket"]:
        for key, b in self.buckets():
            if key == name:
                return b
        return None

    def get(self, key: bytes) -> Optional[bytes]:
        for k, v in self.items():
            if k == key:
                return v
        return None


class BoltDB:
    """Read-only BoltDB file. Use as a context manager."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        try:
            self._mm = mmap.mmap(self._f.fileno(), 0,
                                 access=mmap.ACCESS_READ)
        except ValueError:
            self._f.close()
            raise CorruptDB(f"empty or unmappable file: {path}")
        try:
            self.page_size, self._root_pgid = self._read_meta()
        except Exception:
            self.close()
            raise

    # -- low level --

    def _meta_at(self, off: int):
        """Decode + validate one meta page; None if invalid.

        Field layout mirrors bbolt's meta struct (magic, version,
        pageSize, flags, root bucket{pgid, seq}, freelist, pgid,
        txid, checksum — txid at +48). A nonzero checksum must equal
        FNV-64a over the first 56 meta bytes (bbolt meta.validate);
        on a torn write the corrupt meta is skipped so the older
        valid meta wins instead of a garbage tree."""
        if off + PAGE_HEADER + 64 > len(self._mm):
            return None
        base = off + PAGE_HEADER
        magic, version, page_size = struct.unpack_from(
            "<III", self._mm, base)
        if magic != MAGIC or version != 2:
            return None
        root_pgid = struct.unpack_from("<Q", self._mm, base + 16)[0]
        txid, checksum = struct.unpack_from(
            "<QQ", self._mm, base + 48)
        if checksum and checksum != _fnv64a(
                self._mm[base:base + 56]):
            return None
        return (page_size, root_pgid, txid)

    def _read_meta(self) -> tuple:
        # try both meta pages (0 and 1), prefer the valid one with
        # the highest txid (bbolt picks the newer valid meta)
        best = None
        # meta1 sits at page_size; probe the common page sizes so a
        # torn meta0 on a 16K-page host is still recoverable
        for off in (0, 4096, 8192, 16384, 32768, 65536):
            m = self._meta_at(off)
            if m is None:
                continue
            if off not in (0, m[0]):
                continue   # not a real meta page for this db
            if best is None or m[2] > best[2]:
                best = m
            # meta1 actually lives at page_size, not 4096 — re-probe
            # when the first meta reports a different page size
            if off == 0 and m[0] != 4096:
                m2 = self._meta_at(m[0])
                if m2 is not None and m2[2] > best[2]:
                    best = m2
        if best is None:
            raise CorruptDB(f"not a boltdb file: {self.path}")
        return best[0], best[1]

    def _header(self, buf, off) -> tuple:
        pid, flags, count = _unpack("<QHH", buf, off)
        overflow = _unpack("<I", buf, off + 12)[0]
        return pid, flags, count, overflow

    def _page(self, pgid: int) -> tuple:
        off = pgid * self.page_size
        if off + PAGE_HEADER > len(self._mm):
            raise CorruptDB(f"page {pgid} out of bounds")
        return self._mm, off

    def _open_bucket(self, value: bytes) -> Bucket:
        if len(value) < BUCKET_HEADER:
            raise CorruptDB("short bucket value")
        root, _seq = _unpack("<QQ", value, 0)
        if root == 0:
            # inline bucket: page embedded after the header
            return Bucket(self, 0, inline=(value, BUCKET_HEADER))
        return Bucket(self, root)

    # -- public --

    def root(self) -> Bucket:
        return Bucket(self, self._root_pgid)

    def buckets(self) -> Iterator[tuple]:
        yield from self.root().buckets()

    def bucket(self, name: bytes) -> Optional[Bucket]:
        return self.root().bucket(name)

    def close(self) -> None:
        try:
            self._mm.close()
        finally:
            self._f.close()

    def __enter__(self) -> "BoltDB":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def load_trivy_db(path: str, store=None):
    """Ingest a trivy-db BoltDB file into an AdvisoryStore.

    Schema (SURVEY §2.3): top-level buckets per source
    (``alpine 3.16``, ``pip::Python``, ...) → nested bucket per
    package → key=vuln id, value=JSON advisory; plus a flat
    ``vulnerability`` bucket keyed by vuln id with the detail record.
    """
    import json

    from ..utils import get_logger
    from .compiled import gc_paused
    from .store import AdvisoryStore

    log = get_logger("db.boltdb")
    if store is None:
        store = AdvisoryStore()
    with gc_paused():      # same object volume as compile
        return _load(path, store, log, json)


def _load(path, store, log, json):
    n_adv = n_detail = n_skipped = 0
    with BoltDB(path) as db:
        for bname, bucket in db.buckets():
            name = bname.decode("utf-8", "replace")
            if name == "vulnerability":
                for key, val in bucket.items():
                    try:
                        # bytes→str first: json.loads(bytes) pays a
                        # detect_encoding pass per value
                        store.put_vulnerability(
                            key.decode("utf-8", "replace"),
                            json.loads(val.decode("utf-8")))
                        n_detail += 1
                    except ValueError:   # UnicodeDecodeError included
                        n_skipped += 1
                        continue
                continue
            if name == "trivy":          # metadata bucket
                continue
            for pkg_name, pkg_bucket in bucket.buckets():
                pname = pkg_name.decode("utf-8", "replace")
                for vuln_id, val in pkg_bucket.items():
                    try:
                        store.put_advisory(
                            name, pname,
                            vuln_id.decode("utf-8", "replace"),
                            json.loads(val.decode("utf-8")))
                        n_adv += 1
                    except ValueError:   # UnicodeDecodeError included
                        n_skipped += 1
                        continue
    if n_skipped:
        log.warning("boltdb ingest skipped %d unparseable rows "
                    "(corrupt values?)", n_skipped)
    return store, n_adv, n_detail
