"""Minimal BoltDB file writer — fixture/bench generator.

Produces structurally valid bbolt files (meta pages, leaf/branch
pages, inline buckets, overflow pages) so the pure-Python reader
(boltdb.py) and the advisory-ingest path can be exercised and
benchmarked without a Go toolchain. This is a fixture generator, not
a database: no freelist management, no transactions, write-once.
"""

from __future__ import annotations

import struct

# the on-disk layout is defined once, by the reader
from .boltdb import (BRANCH_ELEM, BUCKET_HEADER, FLAG_BRANCH,
                     FLAG_FREELIST, FLAG_LEAF, FLAG_META,
                     LEAF_ELEM, LEAF_FLAG_BUCKET, MAGIC,
                     PAGE_HEADER)

PAGE_SIZE = 4096


def _page_header(pgid, flags, count, overflow=0) -> bytes:
    return struct.pack("<QHHI", pgid, flags, count, overflow)


def _leaf_page_body(items, pgid=0) -> bytes:
    """items: list of (flags, key, value). Returns a full page image
    (may exceed PAGE_SIZE for overflow values)."""
    n = len(items)
    elems = b""
    data = b""
    data_start = PAGE_HEADER + n * LEAF_ELEM
    for i, (lf, key, val) in enumerate(items):
        elem_off = PAGE_HEADER + i * LEAF_ELEM
        pos = data_start + len(data) - elem_off
        elems += struct.pack("<IIII", lf, pos, len(key), len(val))
        data += key + val
    total = data_start + len(data)
    n_pages = (total + PAGE_SIZE - 1) // PAGE_SIZE
    body = _page_header(pgid, FLAG_LEAF, n, n_pages - 1) + \
        elems + data
    return body.ljust(n_pages * PAGE_SIZE, b"\x00")


def inline_bucket_value(items) -> bytes:
    """Bucket value with root=0 and an embedded leaf page (same
    element packing as a real leaf page, unpadded)."""
    total = PAGE_HEADER + sum(LEAF_ELEM + len(k) + len(v)
                              for _, k, v in items)
    return struct.pack("<QQ", 0, 0) + _leaf_page_body(items)[:total]


class Writer:
    def __init__(self):
        self.pages = {}            # pgid -> bytes (multiple of PAGE)
        self.next_pgid = 4         # 0,1 meta; 2 freelist; 3 root

    def alloc(self, body: bytes) -> int:
        pgid = self.next_pgid
        n_pages = max(1, (len(body) + PAGE_SIZE - 1) // PAGE_SIZE)
        # rewrite the page id inside the header
        body = struct.pack("<Q", pgid) + body[8:]
        self.pages[pgid] = body.ljust(n_pages * PAGE_SIZE, b"\x00")
        self.next_pgid += n_pages
        return pgid

    def leaf_page(self, items) -> int:
        return self.alloc(_leaf_page_body(items))

    def tree_page(self, items, chunk: int = 4096) -> int:
        """Leaf page, or branch-of-leaves when the element count
        would overflow the page header's u16 count."""
        if len(items) <= chunk:
            return self.leaf_page(items)
        children = []
        for i in range(0, len(items), chunk):
            part = items[i:i + chunk]
            children.append((part[0][1], self.leaf_page(part)))
        return self.branch_page(children)

    def branch_page(self, children) -> int:
        """children: list of (key, child_pgid)."""
        n = len(children)
        elems = b""
        data = b""
        data_start = PAGE_HEADER + n * BRANCH_ELEM
        for i, (key, pgid) in enumerate(children):
            elem_off = PAGE_HEADER + i * BRANCH_ELEM
            pos = data_start + len(data) - elem_off
            elems += struct.pack("<IIQ", pos, len(key), pgid)
            data += key
        body = _page_header(0, FLAG_BRANCH, n) + elems + data
        return self.alloc(body)

    def bucket_value(self, root_pgid: int) -> bytes:
        return struct.pack("<QQ", root_pgid, 0)

    def write(self, path: str, root_pgid: int) -> None:
        high = self.next_pgid
        out = bytearray(high * PAGE_SIZE)

        def meta(pgid, txid) -> bytes:
            m = _page_header(pgid, FLAG_META, 0)
            body = struct.pack("<III", MAGIC, 2, PAGE_SIZE)
            body += struct.pack("<I", 0)               # meta flags
            body += struct.pack("<QQ", root_pgid, 0)   # root bucket
            body += struct.pack("<Q", 2)               # freelist
            body += struct.pack("<Q", high)            # pgid high water
            body += struct.pack("<Q", txid)
            from .boltdb import _fnv64a                # bbolt sum64
            body += struct.pack("<Q", _fnv64a(body))
            return (m + body).ljust(PAGE_SIZE, b"\x00")

        out[0:PAGE_SIZE] = meta(0, 1)
        out[PAGE_SIZE:2 * PAGE_SIZE] = meta(1, 2)
        out[2 * PAGE_SIZE:3 * PAGE_SIZE] = _page_header(
            2, FLAG_FREELIST, 0).ljust(PAGE_SIZE, b"\x00")
        for pgid, body in self.pages.items():
            out[pgid * PAGE_SIZE:pgid * PAGE_SIZE + len(body)] = body
        with open(path, "wb") as f:
            f.write(out)


def write_trivy_db(path: str, sources: dict, details: dict) -> None:
    """sources: {bucket: {pkg: {vuln_id: advisory-dict}}};
    details: {vuln_id: detail-dict}."""
    import json
    w = Writer()
    root_items = []
    for bucket_name in sorted(sources):
        pkg_items = []
        for pkg in sorted(sources[bucket_name]):
            kv = [(0, vid.encode(), json.dumps(adv).encode())
                  for vid, adv in sorted(
                      sources[bucket_name][pkg].items())]
            # inline the package bucket when it's small
            if sum(len(k) + len(v) for _, k, v in kv) < 1024:
                pkg_items.append((LEAF_FLAG_BUCKET, pkg.encode(),
                                  inline_bucket_value(kv)))
            else:
                pgid = w.leaf_page(kv)
                pkg_items.append((LEAF_FLAG_BUCKET, pkg.encode(),
                                  w.bucket_value(pgid)))
        pgid = w.tree_page(pkg_items)
        root_items.append((LEAF_FLAG_BUCKET, bucket_name.encode(),
                           w.bucket_value(pgid)))
    detail_items = [(0, vid.encode(), json.dumps(d).encode())
                    for vid, d in sorted(details.items())]
    pgid = w.tree_page(detail_items)
    root_items.append((LEAF_FLAG_BUCKET, b"vulnerability",
                       w.bucket_value(pgid)))
    root_items.sort(key=lambda it: it[1])
    root_pgid = w.tree_page(root_items)
    w.write(path, root_pgid)
