"""Compiled, persistent, TPU-resident advisory tables.

Round-1 rebuilt the rank universe from scratch on every dispatch
(detect/batch._RankSpace), which is O(advisory universe) host work per
scan — fine for fixtures, fatal at trivy-db scale. This module is the
SURVEY §7 step-5 design: flatten the advisory store ONCE at DB-load
time into

  - per-grammar sorted bound-key universes (every constraint parsed
    exactly once, at compile time);
  - int32 interval tables [N, MAX_INTERVALS] in a doubled rank space
    (bound = 2·rank + grammar band offset, exclusivity = ±1);
  - a host-side name-join index bucket → package → row span;
  - per-row metadata for DetectedVulnerability assembly;
  - host-fallback rows for constraints the interval form can't carry
    (> MAX_INTERVALS alternatives, parse errors, npm prereleases).

At scan time, per-dispatch host work is O(packages): parse each
distinct installed version once, binary-search its rank, gather
candidate rows via the dict join — then ONE resident-table kernel
dispatch (ops.intervals.interval_hits_resident) evaluates every
(package, advisory) pair. The tables are pushed to device once and
reused across scans; ``SwappableStore`` double-buffers them for hot
swaps (reference: pkg/rpc/server/listen.go:71-80).

Persistence: ``save``/``load`` round-trip the arrays plus the
indexes/universes as ONE npz file whose ``meta`` member is tagged
JSON — a data-only format (no pickle: a compiled DB may arrive over
the network in the reference's trivy-db workflow, and the server
hot-swaps whatever appears at the watched path, so deserialization
must not be code execution), written to a temp name and atomically
renamed so the hot-swap watcher can never observe a half-written
pair.
"""

from __future__ import annotations

import contextlib

import json
import os
import threading
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..ops.intervals import MAX_INTERVALS, NEG_INF, POS_INF
from ..utils import get_logger
import datetime as _dt

from ..vercmp import get_comparer
from ..vercmp.maven import _PaddedKey
from ..vercmp.rubygems import _GemKey
from ..vercmp.semver import SemverKey
from .store import Advisory, AdvisoryStore

log = get_logger("db.compiled")


@contextlib.contextmanager
def gc_paused():
    """Pause the cyclic collector across bulk object construction,
    restoring the caller's setting (used by compile and the boltdb
    ingest)."""
    import gc
    was_on = gc.isenabled()
    gc.disable()
    try:
        yield
    finally:
        if was_on:
            gc.enable()

def _eco_grammar() -> dict:
    """ecosystem prefix (before ::) → version grammar, derived from
    the single source of truth in detect.library._TYPES (lazy to
    avoid a circular import through trivy_tpu.db)."""
    from ..detect.library import _TYPES
    return {eco: grammar for eco, grammar in _TYPES.values()}

# OS bucket leading token → distro version grammar (detect/ospkg)
_OS_GRAMMAR = {
    "alpine": "apk",
    "debian": "deb",
    "ubuntu": "deb",
    "amazon": "rpm",
    "oracle": "rpm",
    "alma": "rpm",
    "rocky": "rpm",
    "red": "rpm",           # "Red Hat"
    "centos": "rpm",
    "fedora": "rpm",
    "cbl-mariner": "rpm",
    "photon": "rpm",
    "opensuse": "rpm",
    "suse": "rpm",
}

# row flag bits (0-2 shared with ops.intervals)
F_HAS_VULN = 1
F_FORCE = 2
F_HAS_SEC = 4
F_HOST = 8            # evaluate on host (exact fallback)
F_UNFIXED = 16        # os advisory without FixedVersion


def bucket_grammar(bucket: str) -> Optional[str]:
    if "::" in bucket:
        return _eco_grammar().get(bucket.split("::", 1)[0])
    return _OS_GRAMMAR.get(bucket.split()[0].lower()) if bucket \
        else None


@dataclass
class _Row:
    bucket: str
    pkg: str
    advisory: Advisory
    grammar: str
    vuln_ivs: list = field(default_factory=list)
    sec_ivs: list = field(default_factory=list)
    flags: int = 0


_GENERATION_LOCK = threading.Lock()
_GENERATION_SEQ = [0]


def _next_generation() -> int:
    """Process-monotonic table generation key: every compile/load
    gets a fresh one, so device buffers, caches and metrics can tell
    "the same tables again" from "a hot-swapped update" without
    hashing gigabytes (docs/performance.md). Shared by the advisory
    DB and the secret DFA table (ops/dfa.py) — one namespace means
    one invalidation story."""
    with _GENERATION_LOCK:
        _GENERATION_SEQ[0] += 1
        return _GENERATION_SEQ[0]


import weakref

# every live ResidentTables instance, for the /metrics residency
# gauges (trivy_tpu_resident_bytes{table,placement}) — weak refs so
# a dropped table (hot-swap, test teardown) leaves no ghost row
_RESIDENT_REGISTRY: "weakref.WeakSet" = weakref.WeakSet()
_RESIDENT_REG_LOCK = threading.Lock()


def _placement_label(key) -> str:
    """A bounded, human-stable label for a placement key: "default",
    "mesh", or "device" — never the repr of a device object (labels
    are /metrics cardinality)."""
    if key == "default":
        return "default"
    if hasattr(key, "devices"):
        return "mesh"
    return "device"


def resident_snapshot() -> list:
    """[{table, placement, bytes, generation}] across every live
    resident table — what ``trivy_tpu_resident_bytes`` serves. Only
    placements currently STAGED count; ``invalidate_device`` drops
    the rows (the superseded HBM is freed when in-flight dispatches
    release it)."""
    with _RESIDENT_REG_LOCK:
        tables = list(_RESIDENT_REGISTRY)
    out = []
    for t in tables:
        with t._device_lock:
            rows = [(key, nbytes)
                    for key, nbytes in t._device_bytes.items()]
            gen = t.generation
        for key, nbytes in rows:
            out.append({"table": t._TABLE,
                        "placement": _placement_label(key),
                        "bytes": int(nbytes),
                        "generation": gen})
    out.sort(key=lambda r: (r["table"], r["placement"],
                            r["generation"]))
    return out


def prewarm_resident() -> list:
    """Stage every live resident table's default placement NOW —
    the HBM-upload half of a joining replica's prewarm
    (docs/serving.md "Elastic lifecycle"). ``device_tables`` is
    idempotent per (generation, placement), so an already-staged
    table is a no-op; a table whose upload fails (device pressure
    mid-join) is skipped — prewarm is an optimization, the first
    dispatch will stage it like before. Returns
    ``[{table, generation, staged}]`` for the boot log."""
    with _RESIDENT_REG_LOCK:
        tables = list(_RESIDENT_REGISTRY)
    out = []
    for t in sorted(tables, key=lambda x: x._TABLE):
        row = {"table": t._TABLE, "generation": t.generation,
               "staged": True}
        try:
            t.device_tables()
        except (RuntimeError, OSError, ValueError) as e:
            log.warning("prewarm staging skipped %s: %r",
                        t._TABLE, e)
            row["staged"] = False
        out.append(row)
    return out


class ResidentTables:
    """Device-residency plumbing shared by every table that lives in
    HBM across dispatches: the compiled advisory DB below and the
    secret scanner's DFA table (trivy_tpu.ops.dfa).

    Contract: ``device_tables(placement)`` stages the arrays from
    ``_resident_arrays()`` ONCE per (generation, placement) and
    hands back the same device buffers on every later call;
    ``invalidate_device()`` drops them on hot swap (in-flight
    dispatches keep their references until they finish — jax frees
    the HBM when the last one drops). ``placement`` is None (default
    device), a ``jax.sharding.Mesh`` (replicated to every chip), or
    a single ``jax.Device`` (the async sharded sieve places the DFA
    table per data shard). Upload/dispatch amortization is counted
    in ``device_stats()`` and mirrored to the subclass's metrics via
    the ``_note_*`` hooks."""

    _UPLOAD_SPAN = "db_upload"
    _TABLE = "advisory_db"      # /metrics residency label

    def _init_resident(self) -> None:
        self.generation = _next_generation()
        self._device: dict = {}
        self._device_bytes: dict = {}   # placement -> staged bytes
        self._device_lock = threading.Lock()
        self._device_stats = {"uploads": 0, "upload_bytes": 0,
                              "dispatches": 0, "invalidations": 0}
        with _RESIDENT_REG_LOCK:
            _RESIDENT_REGISTRY.add(self)

    # --- subclass hooks ---

    def _resident_arrays(self) -> tuple:
        raise NotImplementedError

    def _span_attrs(self) -> dict:
        return {}

    def _note_upload(self, nbytes: int) -> None:
        pass

    def _note_dispatch(self) -> None:
        pass

    def _note_invalidation(self) -> None:
        pass

    # --- the shared machinery ---

    def device_tables(self, placement=None) -> tuple:
        import jax

        from ..obs.trace import phase_span
        key = "default" if placement is None else placement
        with self._device_lock:
            placed = self._device.get(key)
            if placed is None:
                arrs = self._resident_arrays()
                nbytes = int(sum(a.nbytes for a in arrs))
                with phase_span(self._UPLOAD_SPAN, bytes=nbytes,
                                generation=self.generation,
                                **self._span_attrs()):
                    if placement is None:
                        placed = tuple(jax.device_put(a)
                                       for a in arrs)
                    elif hasattr(placement, "devices"):   # a Mesh
                        from ..parallel.interval_shard import \
                            replicate_tables
                        placed = replicate_tables(placement, arrs)
                    else:                          # a single Device
                        placed = tuple(
                            jax.device_put(a, placement)
                            for a in arrs)
                self._device[key] = placed
                self._device_bytes[key] = nbytes
                self._device_stats["uploads"] += 1
                self._device_stats["upload_bytes"] += nbytes
                self._note_upload(nbytes)
            self._device_stats["dispatches"] += 1
        self._note_dispatch()
        return placed

    def invalidate_device(self) -> None:
        """Drop this generation's device buffers (hot-swap path)."""
        with self._device_lock:
            if not self._device:
                return
            self._device.clear()
            self._device_bytes.clear()
            self._device_stats["invalidations"] += 1
        self._note_invalidation()

    def device_stats(self) -> dict:
        """Upload-amortization numbers for bench/metrics: how many
        dispatches each HBM upload served."""
        with self._device_lock:
            out = dict(self._device_stats)
        out["generation"] = self.generation
        out["amortization"] = round(
            out["dispatches"] / out["uploads"], 2) \
            if out["uploads"] else 0.0
        return out


class CompiledDB(ResidentTables):
    """Flattened advisory tables + join index. Read-only after
    ``compile`` / ``load``."""

    def __init__(self):
        self.rows_meta: list = []       # per row: (bucket, pkg, Advisory)
        self.row_grammar: list = []
        self.v_lo = self.v_hi = self.s_lo = self.s_hi = None
        self.flags = None               # np.int32 [N]
        self.index: dict = {}           # bucket → {pkg → [row ids]}
        self.universe: dict = {}        # grammar → (keys list, base)
        self.vulnerabilities: dict = {}
        self.data_sources: dict = {}
        self.stats: dict = {}
        self._init_resident()
        self._parse_cache: dict = {}

    # ---- compile ----

    @classmethod
    def compile(cls, store: AdvisoryStore) -> "CompiledDB":
        # millions of long-lived row/interval objects make the cyclic
        # collector quadratic-ish (2.3x at 1M advisories); nothing
        # cyclic is created here
        with gc_paused():
            return cls._compile(store)

    @classmethod
    def _compile(cls, store: AdvisoryStore) -> "CompiledDB":
        self = cls()
        self.vulnerabilities = dict(store.vulnerabilities)
        self.data_sources = dict(store.data_sources)

        rows: list = []
        n_host = 0
        for bucket in sorted(store.buckets):
            grammar = bucket_grammar(bucket)
            for pkg in sorted(store.buckets[bucket]):
                for adv in store.get(bucket, pkg):
                    row = self._compile_row(bucket, pkg, adv, grammar)
                    n_host += bool(row.flags & F_HOST)
                    rows.append(row)

        # per-grammar bound universes with disjoint band offsets
        bounds: dict = {}
        for row in rows:
            for iv in row.vuln_ivs + row.sec_ivs:
                b = bounds.setdefault(row.grammar, set())
                if iv.lo is not None:
                    b.add(iv.lo)
                if iv.hi is not None:
                    b.add(iv.hi)
        base = 1
        for grammar in sorted(bounds):
            keys = sorted(bounds[grammar])
            self.universe[grammar] = (keys, base)
            base += 2 * len(keys) + 4

        N = len(rows)
        self.v_lo = np.full((N, MAX_INTERVALS), POS_INF, np.int32)
        self.v_hi = np.full((N, MAX_INTERVALS), NEG_INF, np.int32)
        self.s_lo = np.full((N, MAX_INTERVALS), POS_INF, np.int32)
        self.s_hi = np.full((N, MAX_INTERVALS), NEG_INF, np.int32)
        self.flags = np.zeros(N, np.int32)
        for i, row in enumerate(rows):
            self.flags[i] = row.flags
            if row.flags & F_HOST:
                continue
            for j, iv in enumerate(row.vuln_ivs):
                self.v_lo[i, j], self.v_hi[i, j] = \
                    self._encode(row.grammar, iv)
            for j, iv in enumerate(row.sec_ivs):
                self.s_lo[i, j], self.s_hi[i, j] = \
                    self._encode(row.grammar, iv)
        self.rows_meta = [(r.bucket, r.pkg, r.advisory) for r in rows]
        self.row_grammar = [r.grammar for r in rows]
        for i, row in enumerate(rows):
            self.index.setdefault(row.bucket, {}) \
                .setdefault(row.pkg, []).append(i)

        self.stats = {
            "rows": N,
            "host_fallback_rows": n_host,
            "host_fallback_rate": (n_host / N) if N else 0.0,
            "grammars": {g: len(k)
                         for g, (k, _) in self.universe.items()},
        }
        log.info("compiled advisory db: %d rows, %d host-fallback "
                 "(%.3f%%)", N, n_host,
                 100.0 * self.stats["host_fallback_rate"])
        return self

    def _compile_row(self, bucket: str, pkg: str, adv: Advisory,
                     grammar: Optional[str]) -> _Row:
        row = _Row(bucket=bucket, pkg=pkg, advisory=adv,
                   grammar=grammar or "generic")
        is_ospkg = not (adv.vulnerable_versions or
                        adv.patched_versions or
                        adv.unaffected_versions)
        # the unfixed marker survives host fallback so the driver's
        # report_unfixed filter still applies (detect_pairs_resident)
        unfixed = F_UNFIXED if is_ospkg and \
            adv.fixed_version == "" else 0
        if grammar is None:
            row.flags = F_HOST | unfixed
            return row
        comparer = get_comparer(grammar)
        try:
            if is_ospkg:
                self._compile_ospkg(row, comparer)
            else:
                self._compile_library(row, comparer)
        except ValueError:
            row.vuln_ivs, row.sec_ivs = [], []
            row.flags = F_HOST | unfixed
        return row

    def _compile_library(self, row: _Row, comparer) -> None:
        adv = row.advisory
        if any(v == "" for v in
               list(adv.vulnerable_versions) +
               list(adv.patched_versions)):
            row.flags = F_FORCE
            return
        from ..detect.ccache import INTERVAL_CACHE
        if adv.vulnerable_versions:
            row.flags |= F_HAS_VULN
            for c in " || ".join(adv.vulnerable_versions).split("||"):
                if not c.strip():
                    raise ValueError("empty constraint alternative")
                row.vuln_ivs.extend(INTERVAL_CACHE.intervals(
                    row.grammar, comparer, c))
        secure = list(adv.patched_versions) + \
            list(adv.unaffected_versions)
        if secure:
            row.flags |= F_HAS_SEC
            for c in " || ".join(secure).split("||"):
                if not c.strip():
                    raise ValueError("empty constraint alternative")
                row.sec_ivs.extend(INTERVAL_CACHE.intervals(
                    row.grammar, comparer, c))
        if len(row.vuln_ivs) > MAX_INTERVALS or \
                len(row.sec_ivs) > MAX_INTERVALS:
            row.vuln_ivs, row.sec_ivs = [], []
            row.flags = F_HOST

    def _compile_ospkg(self, row: _Row, comparer) -> None:
        from ..vercmp.base import Interval
        adv = row.advisory
        lo = comparer.parse(adv.affected_version) \
            if adv.affected_version else None
        if adv.fixed_version == "":
            row.vuln_ivs = [Interval(lo=lo)]
            row.flags = F_HAS_VULN | F_UNFIXED
        else:
            row.vuln_ivs = [Interval(
                lo=lo, hi=comparer.parse(adv.fixed_version),
                hi_incl=False)]
            row.flags = F_HAS_VULN

    def _encode(self, grammar: str, iv) -> tuple:
        keys, base = self.universe[grammar]
        if iv.lo is None:
            lo = NEG_INF
        else:
            lo = base + 2 * bisect_left(keys, iv.lo) + \
                (0 if iv.lo_incl else 1)
        if iv.hi is None:
            hi = POS_INF
        else:
            hi = base + 2 * bisect_left(keys, iv.hi) - \
                (0 if iv.hi_incl else 1)
        return lo, hi

    # ---- scan-time API ----

    def pkg_rank(self, grammar: str, version: str) -> Optional[int]:
        """Rank an installed version in its grammar band. Bound keys
        sit at even offsets; a version strictly between bounds gets
        the odd offset below the next bound — containment is then
        EXACT for bounds-only universes. None on parse failure."""
        cached = self._parse_cache.get((grammar, version))
        if cached is not None:
            return cached if cached != -1 else None
        keys, base = self.universe.get(grammar, ([], 1))
        try:
            key = get_comparer(grammar).parse(version)
        except ValueError:
            self._parse_cache[(grammar, version)] = -1
            return None
        i = bisect_left(keys, key)
        if i < len(keys) and keys[i] == key:
            r = base + 2 * i
        else:
            r = base + 2 * i - 1
        self._parse_cache[(grammar, version)] = r
        return r

    def candidate_rows(self, bucket: str, pkg: str) -> list:
        return self.index.get(bucket, {}).get(pkg, [])

    def _prefix_index(self) -> dict:
        """ecosystem prefix ("pip::") → bucket list, built lazily so
        prefix joins are O(1) per package, not O(buckets)."""
        if not hasattr(self, "_prefixes"):
            prefixes: dict = {}
            for bucket in self.index:
                if "::" in bucket:
                    pre = bucket.split("::", 1)[0] + "::"
                    prefixes.setdefault(pre, []).append(bucket)
            self._prefixes = prefixes
        return self._prefixes

    def candidate_rows_prefix(self, prefix: str, pkg: str) -> list:
        buckets = self._prefix_index().get(prefix)
        if buckets is None:               # non-ecosystem prefix query
            buckets = [b for b in self.index if b.startswith(prefix)]
        out = []
        for bucket in buckets:
            out.extend(self.index[bucket].get(pkg, []))
        return out

    def host_eval(self, row: int, version: str) -> bool:
        """Exact host evaluation for F_HOST rows — must mirror the
        classic paths (base.is_vulnerable / Driver._is_vulnerable)."""
        from ..vercmp.base import is_vulnerable
        bucket, _pkg, adv = self.rows_meta[row]
        grammar = self.row_grammar[row]
        if grammar == "generic":
            grammar = bucket_grammar(bucket) or "semver"
        comparer = get_comparer(grammar)
        if adv.vulnerable_versions or adv.patched_versions or \
                adv.unaffected_versions:
            return is_vulnerable(comparer, version,
                                 adv.vulnerable_versions,
                                 adv.patched_versions,
                                 adv.unaffected_versions)
        # ospkg: affected-version gate first (alpine "introduced in");
        # a parse error rejects, as in Driver._is_vulnerable
        if adv.affected_version:
            try:
                if comparer.parse(adv.affected_version) > \
                        comparer.parse(version):
                    return False
            except ValueError:
                return False
        if adv.fixed_version == "":
            return True
        try:
            return comparer.compare(version, adv.fixed_version) < 0
        except ValueError:
            return False

    # ---- device residency (ResidentTables hooks) ----
    #
    # device_tables(mesh) pushes (v_lo, v_hi, s_lo, s_hi, flags) to
    # the default device (or replicated across the mesh) ONCE per
    # (generation, placement); invalidate_device (hot-swap / ``trivy
    # db update``) drops the buffers so the superseded generation's
    # HBM is reclaimed as soon as its last reader finishes.

    def device_tables(self, mesh=None) -> tuple:
        return super().device_tables(mesh)

    def _resident_arrays(self) -> tuple:
        return (self.v_lo, self.v_hi, self.s_lo, self.s_hi,
                self.flags)

    def _span_attrs(self) -> dict:
        return {"rows": int(len(self.flags))}

    def _note_upload(self, nbytes: int) -> None:
        from ..detect.metrics import DETECT_METRICS
        DETECT_METRICS.note_db_upload(nbytes)

    def _note_dispatch(self) -> None:
        from ..detect.metrics import DETECT_METRICS
        DETECT_METRICS.inc("resident_dispatches")

    def _note_invalidation(self) -> None:
        from ..detect.metrics import DETECT_METRICS
        DETECT_METRICS.inc("db_invalidations")

    # ---- content identity (trivy_tpu.memo) ----

    def content_fingerprint(self) -> str:
        """Content hash of the compiled tables + advisory records —
        the cross-process "DB generation" the findings memo keys on
        (``generation`` is process-monotonic and says nothing about
        content). Cached: a CompiledDB is read-only after
        compile/load."""
        fp = getattr(self, "_content_fp", None)
        if fp is None:
            import hashlib
            h = hashlib.sha256()
            for a in (self.v_lo, self.v_hi, self.s_lo, self.s_hi,
                      self.flags):
                if a is not None:
                    h.update(np.ascontiguousarray(a).tobytes())
            h.update(json.dumps(
                [[b, p, _adv_enc(a)] for b, p, a in self.rows_meta],
                sort_keys=True, default=_json_default).encode())
            fp = self._content_fp = h.hexdigest()[:32]
        return fp

    # ---- enrichment reads (db.Config parity) ----

    def get_vulnerability(self, vuln_id: str):
        from .store import VulnerabilityDetail
        v = self.vulnerabilities.get(vuln_id)
        if v is None:
            return None
        return VulnerabilityDetail.from_dict(vuln_id, v)

    # ---- persistence ----
    # (tagged-JSON helpers for save/load live at module scope below)

    def save(self, path: str) -> None:
        """Write ``path + ".npz"`` atomically (temp file + rename).

        Everything non-array rides in the ``meta`` member as tagged
        JSON (see ``_enc_key``); a single file means the DBWorker's
        mtime check can never pair new arrays with stale metadata."""
        meta = {
            "rows_meta": [(b, p, _adv_enc(a))
                          for b, p, a in self.rows_meta],
            "row_grammar": self.row_grammar,
            "index": self.index,
            "universe": {g: [[_enc_key(k) for k in keys], base]
                         for g, (keys, base) in self.universe.items()},
            "vulnerabilities": self.vulnerabilities,
            "data_sources": self.data_sources,
            "stats": self.stats,
        }
        blob = np.frombuffer(
            json.dumps(meta, default=_json_default).encode(),
            np.uint8)
        tmp = path + ".npz.tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f, v_lo=self.v_lo, v_hi=self.v_hi,
                s_lo=self.s_lo, s_hi=self.s_hi, flags=self.flags,
                meta=blob)
        os.replace(tmp, path + ".npz")

    @classmethod
    def load(cls, path: str) -> "CompiledDB":
        self = cls()
        arrs = np.load(path + ".npz")
        self.v_lo, self.v_hi = arrs["v_lo"], arrs["v_hi"]
        self.s_lo, self.s_hi = arrs["s_lo"], arrs["s_hi"]
        self.flags = arrs["flags"]
        if "meta" not in arrs:
            raise ValueError(
                f"{path}.npz has no meta member — rebuild with "
                f"'db build' (pre-data-only-format file?)")
        d = json.loads(arrs["meta"].tobytes().decode(),
                       object_hook=_json_hook)
        self.rows_meta = [(b, p, _adv_dec(a))
                          for b, p, a in d["rows_meta"]]
        self.row_grammar = d["row_grammar"]
        self.index = d["index"]
        self.universe = {g: ([_dec_key(k) for k in keys], base)
                         for g, (keys, base) in d["universe"].items()}
        self.vulnerabilities = d["vulnerabilities"]
        self.data_sources = d["data_sources"]
        self.stats = d["stats"]
        return self


# ---- data-only persistence helpers ---------------------------------
#
# Version-grammar parse keys are nested tuples, sometimes wrapped in a
# grammar's own comparable class (SemverKey, maven _PaddedKey,
# rubygems _GemKey). bisect at scan time compares freshly parsed keys
# against persisted ones, so the round-trip must restore EXACT types —
# hence a tagged encoding over a closed class set that fails loudly on
# anything new instead of silently pickling it.

def _enc_key(v):
    if isinstance(v, SemverKey):
        return ["sv"] + [_enc_key(x) for x in v]
    if isinstance(v, _PaddedKey):
        return ["mv", _enc_key(v.toks)]
    if isinstance(v, _GemKey):
        return ["gem", _enc_key(v.segs)]
    if isinstance(v, tuple):
        return ["t"] + [_enc_key(x) for x in v]
    if isinstance(v, list):
        return ["l"] + [_enc_key(x) for x in v]
    if v is None or isinstance(v, (int, float, str, bool)):
        return v
    raise TypeError(f"unencodable universe key part: {type(v)}")


def _dec_key(v):
    if not isinstance(v, list):
        return v
    tag, rest = v[0], v[1:]
    if tag == "sv":
        return SemverKey(tuple(_dec_key(x) for x in rest))
    if tag == "mv":
        return _PaddedKey(_dec_key(rest[0]))
    if tag == "gem":
        return _GemKey(_dec_key(rest[0]))
    if tag == "t":
        return tuple(_dec_key(x) for x in rest)
    if tag == "l":
        return [_dec_key(x) for x in rest]
    raise ValueError(f"bad universe key tag: {tag!r}")


def _json_default(o):
    """Vulnerability detail dicts come from YAML fixtures, which parse
    ISO timestamps into datetime (and unquoted day-only values into
    date) — tag both for the round-trip."""
    if isinstance(o, _dt.datetime):
        return {"$dt": o.isoformat()}
    if isinstance(o, _dt.date):
        return {"$d": o.isoformat()}
    raise TypeError(f"unencodable compiled-db value: {type(o)}")


def _json_hook(d: dict):
    if len(d) == 1:
        if "$dt" in d:
            return _dt.datetime.fromisoformat(d["$dt"])
        if "$d" in d:
            return _dt.date.fromisoformat(d["$d"])
    return d


def _adv_enc(a: Advisory) -> list:
    ds = a.data_source
    return [a.vulnerability_id, a.fixed_version, a.affected_version,
            a.vulnerable_versions, a.patched_versions,
            a.unaffected_versions, a.arches, a.severity, a.vendor_ids,
            [ds.id, ds.name, ds.url] if ds is not None else None,
            a.content_sets]


def _adv_dec(v: list) -> Advisory:
    from ..types import DataSource
    ds = DataSource(id=v[9][0], name=v[9][1], url=v[9][2]) \
        if v[9] is not None else None
    return Advisory(
        vulnerability_id=v[0], fixed_version=v[1],
        affected_version=v[2], vulnerable_versions=v[3],
        patched_versions=v[4], unaffected_versions=v[5],
        arches=v[6], severity=v[7], vendor_ids=v[8], data_source=ds,
        content_sets=v[10] if len(v) > 10 else [])


class SwappableStore:
    """Double-buffered advisory DB holder (reference: the RW-waitgroup
    pair gating the server's hourly DB update, listen.go:54-83).

    Readers take ``current()`` under a shared lock; ``swap`` installs
    a freshly compiled DB after in-flight scans drain. On TPU the old
    device tables stay alive until their last reader finishes, then
    get garbage-collected — the new tables are staged with
    ``device_tables()`` BEFORE the swap so scans never wait on the
    transfer."""

    def __init__(self, db: Optional[CompiledDB] = None):
        self._db = db
        self._lock = threading.Lock()
        self._readers = 0
        self._no_readers = threading.Condition(self._lock)
        # swap hooks (db/lifecycle.attach_memo): called AFTER a new
        # generation installs, with (old, new) — the findings memo
        # registers its delta re-match here
        self._swap_hooks: list = []

    def add_swap_hook(self, fn) -> "SwappableStore":
        """Register ``fn(old_db, new_db)`` to run after every swap.
        Hook failures are logged, never raised — a broken observer
        must not wedge the DB update."""
        self._swap_hooks.append(fn)
        return self

    def remove_swap_hook(self, fn) -> None:
        try:
            self._swap_hooks.remove(fn)
        except ValueError:
            pass

    def acquire(self) -> CompiledDB:
        with self._lock:
            self._readers += 1
            return self._db

    def release(self) -> None:
        with self._lock:
            self._readers -= 1
            if self._readers == 0:
                self._no_readers.notify_all()

    def current(self) -> CompiledDB:
        with self._lock:
            return self._db

    def swap(self, new_db: CompiledDB, stage: bool = True) -> None:
        if stage and new_db.v_lo is not None and len(new_db.v_lo):
            try:
                new_db.device_tables()      # stage HBM copy up front
            except Exception:               # no device available
                pass
        with self._lock:
            while self._readers:
                self._no_readers.wait()
            old, self._db = self._db, new_db
        # the superseded generation's resident buffers are explicitly
        # invalidated (``trivy db update`` lifecycle): dispatches
        # already holding the tuple finish on it, new dispatches key
        # against the new generation, and the old HBM frees as soon
        # as the last in-flight reference drops. getattr: the holder
        # also fronts plain AdvisoryStores (no device residency)
        drop = getattr(old, "invalidate_device", None)
        if drop is not None and old is not new_db:
            drop()
        if old is not new_db:
            for fn in list(self._swap_hooks):
                try:
                    fn(old, new_db)
                except Exception as e:      # noqa: BLE001
                    log.warning("swap hook %r failed: %r", fn, e)
