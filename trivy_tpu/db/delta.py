"""Advisory delta between two compiled DB generations.

``trivy db update`` swaps a freshly compiled table set in
(SwappableStore.swap); the delta names exactly the ``(bucket,
package)`` join keys whose advisory content changed — added, removed,
or edited rows — so the findings memo (trivy_tpu.memo) can re-match
ONLY the packages those keys touch against the new device-resident
tables and migrate everything else untouched, instead of flushing the
store and re-scanning the world (docs/performance.md "Findings
memoization & incremental re-scan").

Signatures are content-based (``memo.keys.adv_sig`` over the
advisory's encoded record) — row ids are compile-order artifacts and
shift wholesale whenever any bucket grows, so they can never anchor a
cross-generation comparison.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class AdvisoryDelta:
    """Touched join keys between two generations."""

    touched: set = field(default_factory=set)   # {(bucket, pkg)}
    added: int = 0
    removed: int = 0
    changed: int = 0
    pairs_old: int = 0
    pairs_new: int = 0
    # pkg name -> set of touched buckets, for ecosystem-prefix joins
    # (library packages query "pip::" across every pip bucket)
    _by_name: dict = field(default_factory=dict)

    def note(self, bucket: str, pkg: str) -> None:
        self.touched.add((bucket, pkg))
        self._by_name.setdefault(pkg, set()).add(bucket)

    def touches(self, kind: str, bucket_or_prefix: str,
                name: str) -> bool:
        """Does this delta touch one memoized query? ``kind`` "os"
        queries name a concrete bucket; "lib" queries name an
        ecosystem prefix that spans every bucket under it."""
        if kind == "os":
            return (bucket_or_prefix, name) in self.touched
        buckets = self._by_name.get(name)
        if not buckets:
            return False
        return any(b.startswith(bucket_or_prefix) for b in buckets)

    def stats(self) -> dict:
        return {"touched_keys": len(self.touched),
                "added": self.added, "removed": self.removed,
                "changed": self.changed,
                "pairs_old": self.pairs_old,
                "pairs_new": self.pairs_new}


def _pair_sigs(cdb) -> dict:
    """{(bucket, pkg): [ordered advisory content sigs]} for one
    compiled DB — candidate_rows order, which is compile order."""
    from ..memo.keys import adv_sig
    out: dict = {}
    for bucket, pkgs in cdb.index.items():
        for pkg, rows in pkgs.items():
            out[(bucket, pkg)] = [adv_sig(cdb, r) for r in rows]
    return out


def advisory_delta(old_cdb, new_cdb) -> AdvisoryDelta:
    """Compare two compiled generations by advisory content."""
    old = _pair_sigs(old_cdb)
    new = _pair_sigs(new_cdb)
    delta = AdvisoryDelta(pairs_old=len(old), pairs_new=len(new))
    for key, sigs in old.items():
        nsigs = new.get(key)
        if nsigs is None:
            delta.removed += 1
            delta.note(*key)
        elif nsigs != sigs:
            delta.changed += 1
            delta.note(*key)
    for key in new:
        if key not in old:
            delta.added += 1
            delta.note(*key)
    return delta
