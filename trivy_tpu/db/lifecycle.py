"""Advisory-DB lifecycle: OCI-layout distribution + metadata freshness.

Mirrors the reference's DB client (pkg/db/db.go:90-178) and OCI
artifact reader (pkg/oci/artifact.go:46-130):

  - trivy-db ships as a single-layer OCI artifact whose layer media
    type is ``application/vnd.aquasec.trivy.db.layer.v1.tar+gzip``
    and whose ``org.opencontainers.image.title`` annotation names the
    archive (db.go:19, artifact.go:93-103);
  - the archive unpacks to ``trivy.db`` + ``metadata.json`` under
    ``<cache>/db/``;
  - ``metadata.json`` freshness (db.go NeedsUpdate:90-120): schema
    mismatch → update (or error if the local schema is NEWER than
    supported); else fresh while ``now < NextUpdate`` or
    ``now < DownloadedAt + 1h``; ``--skip-db-update`` is rejected on
    first run and on old schemas.

This environment has no registry egress, so the network pull is a
seam: ``update_from_oci_layout`` consumes a local OCI *layout*
directory (``index.json`` + ``blobs/``), which is the format a
registry pull produces — the transport is the only missing piece
(artifact/resolve.py documents the same seam for images).
"""

from __future__ import annotations

import datetime
import gzip
import io
import json
import os
import tarfile
from dataclasses import dataclass, field
from typing import Optional

from ..utils import get_logger

log = get_logger("db.lifecycle")

SCHEMA_VERSION = 2            # reference: trivy-db db.SchemaVersion
DB_MEDIA_TYPE = "application/vnd.aquasec.trivy.db.layer.v1.tar+gzip"
TITLE_ANNOTATION = "org.opencontainers.image.title"
_RFC3339 = "%Y-%m-%dT%H:%M:%S"


def _parse_time(s: str) -> datetime.datetime:
    if not s:
        return datetime.datetime.fromtimestamp(
            0, tz=datetime.timezone.utc)
    # Go emits RFC3339Nano; fromisoformat handles offsets but not 'Z'
    # before 3.11-style normalization
    s = s.replace("Z", "+00:00")
    try:
        t = datetime.datetime.fromisoformat(s)
    except ValueError:
        return datetime.datetime.fromtimestamp(
            0, tz=datetime.timezone.utc)
    if t.tzinfo is None:
        # offset-less timestamps would make needs_update comparisons
        # raise (naive vs aware); treat them as UTC like Go's zero-loc
        t = t.replace(tzinfo=datetime.timezone.utc)
    return t


def _fmt_time(t: datetime.datetime) -> str:
    return t.astimezone(datetime.timezone.utc).strftime(
        _RFC3339) + "Z"


@dataclass
class Metadata:
    """trivy-db metadata.json (trivy-db metadata.Metadata)."""

    version: int = 0
    next_update: datetime.datetime = field(
        default_factory=lambda: datetime.datetime.fromtimestamp(
            0, tz=datetime.timezone.utc))
    updated_at: datetime.datetime = field(
        default_factory=lambda: datetime.datetime.fromtimestamp(
            0, tz=datetime.timezone.utc))
    downloaded_at: datetime.datetime = field(
        default_factory=lambda: datetime.datetime.fromtimestamp(
            0, tz=datetime.timezone.utc))

    @classmethod
    def from_dict(cls, d: dict) -> "Metadata":
        return cls(
            version=int(d.get("Version", 0)),
            next_update=_parse_time(d.get("NextUpdate", "")),
            updated_at=_parse_time(d.get("UpdatedAt", "")),
            downloaded_at=_parse_time(d.get("DownloadedAt", "")))

    def to_dict(self) -> dict:
        return {
            "Version": self.version,
            "NextUpdate": _fmt_time(self.next_update),
            "UpdatedAt": _fmt_time(self.updated_at),
            "DownloadedAt": _fmt_time(self.downloaded_at),
        }


def db_dir(cache_dir: str) -> str:
    return os.path.join(cache_dir, "db")


def metadata_path(cache_dir: str) -> str:
    return os.path.join(db_dir(cache_dir), "metadata.json")


def load_metadata(cache_dir: str) -> Optional[Metadata]:
    try:
        with open(metadata_path(cache_dir), encoding="utf-8") as f:
            return Metadata.from_dict(json.load(f))
    except (OSError, ValueError):
        return None


def save_metadata(cache_dir: str, meta: Metadata) -> None:
    os.makedirs(db_dir(cache_dir), exist_ok=True)
    with open(metadata_path(cache_dir), "w", encoding="utf-8") as f:
        json.dump(meta.to_dict(), f)


def needs_update(cache_dir: str, skip: bool = False,
                 now: Optional[datetime.datetime] = None) -> bool:
    """db.go NeedsUpdate:90-120 semantics. Raises ValueError where
    the reference errors (newer-schema DB; --skip on first run or on
    an old schema)."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    meta = load_metadata(cache_dir)
    if meta is None:
        if skip:
            raise ValueError(
                "--skip-db-update cannot be specified on the first "
                "run")
        meta = Metadata(version=SCHEMA_VERSION)

    if SCHEMA_VERSION < meta.version:
        raise ValueError(
            f"the version of DB schema doesn't match. Local DB: "
            f"{meta.version}, Expected: {SCHEMA_VERSION}")

    if skip:
        if SCHEMA_VERSION != meta.version:
            raise ValueError(
                f"--skip-db-update cannot be specified with the old "
                f"DB schema. Local DB: {meta.version}, Expected: "
                f"{SCHEMA_VERSION}")
        return False

    if SCHEMA_VERSION != meta.version:
        return True
    # isNewDB (db.go:133-143): fresh while inside NextUpdate, or
    # downloaded within the last hour
    if now < meta.next_update:
        return False
    if now < meta.downloaded_at + datetime.timedelta(hours=1):
        return False
    return True


# --------------------------------------------------- hot-swap observers

def attach_memo(store, memo):
    """Register a findings memo (trivy_tpu.memo.FindingsMemo) on a
    SwappableStore's swap lifecycle: every ``db update`` hot swap
    computes the advisory delta between the outgoing and incoming
    generations and re-matches only the delta-touched packages
    against the new device-resident tables
    (docs/performance.md "Findings memoization & incremental
    re-scan"). Returns a detach callable."""
    def hook(old_db, new_db):
        memo.hot_swap(old_db, new_db)

    store.add_swap_hook(hook)

    def detach():
        store.remove_swap_hook(hook)

    return detach


# ------------------------------------------------------------ OCI layout

def _read_json(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _blob_path(layout_dir: str, digest: str) -> str:
    # validate BEFORE the digest becomes a filesystem path — a
    # crafted index/manifest must not read outside the layout
    from ..guard.safetar import validate_digest
    algo, _, hexd = validate_digest(digest).partition(":")
    return os.path.join(layout_dir, "blobs", algo, hexd)


def read_oci_layout(layout_dir: str) -> tuple:
    """OCI image layout → (layer bytes, title annotation).

    Mirrors pkg/oci/artifact.go:46-103: exactly one layer, media type
    must be the trivy-db tgz, title annotation must be present — and
    the layer bytes must hash to the digest the manifest pins (a
    tampered or torn download fails HERE, before any unpack)."""
    import hashlib
    index = _read_json(os.path.join(layout_dir, "index.json"))
    manifests = index.get("manifests") or []
    if not manifests:
        raise ValueError(f"{layout_dir}: empty OCI index")
    manifest = _read_json(
        _blob_path(layout_dir, manifests[0]["digest"]))
    layers = manifest.get("layers") or []
    if len(layers) != 1:
        raise ValueError("OCI artifact must be a single layer")
    layer = layers[0]
    if layer.get("mediaType") != DB_MEDIA_TYPE:
        raise ValueError(
            f"unacceptable media type: {layer.get('mediaType')!r}")
    title = (layer.get("annotations") or {}).get(TITLE_ANNOTATION)
    if not title:
        raise ValueError(f"annotation {TITLE_ANNOTATION} is missing")
    digest = layer.get("digest") or ""
    with open(_blob_path(layout_dir, digest), "rb") as f:
        blob = f.read()
    algo, _, want = digest.partition(":")
    if algo != "sha256":
        raise ValueError(f"unsupported layer digest {digest!r}")
    got = hashlib.sha256(blob).hexdigest()
    if got != want:
        raise ValueError(
            f"layer digest mismatch: manifest pins {digest}, "
            f"blob is sha256:{got}")
    return blob, title


def update_from_oci_layout(
        layout_dir: str, cache_dir: str,
        now: Optional[datetime.datetime] = None) -> Metadata:
    """``trivy-tpu db update --from-oci-layout``: unpack the layer
    tgz and install it ATOMICALLY into <cache>/db/ (db.go Download:
    146-184 + hostile-input hardening, docs/robustness.md):

    1. unpack into a temp dir NEXT TO the destination (same fs, so
       the final ``os.replace`` is atomic), through the bounded
       safe-tar reader (a bomb or 100k-entry flood trips the budget
       instead of filling the disk);
    2. verify the unpacked ``trivy.db`` opens as a valid BoltDB
       (meta-page magic + checksum);
    3. only then drop the stale metadata/compiled tables and
       ``os.replace`` the new files in.

    A corrupt, truncated, or tampered download therefore raises and
    leaves the PREVIOUS DB serving — never a half-written install.
    Returns the resulting metadata."""
    import shutil
    import tempfile

    from ..guard.budget import ResourceBudget, ResourceLimits
    from ..guard.safetar import safe_extract_db_archive

    now = now or datetime.datetime.now(datetime.timezone.utc)
    blob, _title = read_oci_layout(layout_dir)
    dest = db_dir(cache_dir)
    os.makedirs(dest, exist_ok=True)

    budget = ResourceBudget(
        ResourceLimits(max_decompressed_bytes=4 << 30,
                       max_file_bytes=4 << 30, max_files=64,
                       ingest_deadline_s=600.0),
        name="db-update")
    tmpdir = tempfile.mkdtemp(prefix=".db-install-", dir=cache_dir)
    try:
        safe_extract_db_archive(blob, tmpdir, budget)
        bolt_tmp = os.path.join(tmpdir, "trivy.db")
        if not os.path.exists(bolt_tmp):
            raise ValueError("OCI layer does not contain trivy.db")
        from .boltdb import BoltDB
        BoltDB(bolt_tmp).close()     # CorruptDB (a ValueError) if not
        # validation passed — point of no return: drop the stale
        # metadata (db.go:148-151) and any compiled tables derived
        # from the OLD trivy.db (they would silently shadow the
        # fresh install in _store), then swap the new files in
        for stale in (metadata_path(cache_dir),
                      os.path.join(dest, "compiled.npz")):
            try:
                os.remove(stale)
            except OSError:
                pass
        os.replace(bolt_tmp, os.path.join(dest, "trivy.db"))
        meta_tmp = os.path.join(tmpdir, "metadata.json")
        if os.path.exists(meta_tmp):
            os.replace(meta_tmp, metadata_path(cache_dir))
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    meta = load_metadata(cache_dir) or Metadata(
        version=SCHEMA_VERSION)
    meta.downloaded_at = now
    save_metadata(cache_dir, meta)
    log.info("advisory DB updated from %s -> %s", layout_dir, dest)
    return meta


def write_oci_layout(layout_dir: str, archive: bytes) -> None:
    """Produce an OCI layout holding one trivy-db layer — the shape a
    registry pull yields; used by fixtures/tests and `db export`."""
    import hashlib
    os.makedirs(os.path.join(layout_dir, "blobs", "sha256"),
                exist_ok=True)

    def put(data: bytes) -> str:
        digest = hashlib.sha256(data).hexdigest()
        with open(os.path.join(layout_dir, "blobs", "sha256",
                               digest), "wb") as f:
            f.write(data)
        return f"sha256:{digest}"

    layer_digest = put(archive)
    config = json.dumps({}).encode()
    config_digest = put(config)
    manifest = json.dumps({
        "schemaVersion": 2,
        "mediaType": "application/vnd.oci.image.manifest.v1+json",
        "config": {
            "mediaType": "application/vnd.unknown.config.v1+json",
            "digest": config_digest, "size": len(config)},
        "layers": [{
            "mediaType": DB_MEDIA_TYPE,
            "digest": layer_digest, "size": len(archive),
            "annotations": {TITLE_ANNOTATION: "db.tar.gz"}}],
    }).encode()
    manifest_digest = put(manifest)
    with open(os.path.join(layout_dir, "index.json"), "w",
              encoding="utf-8") as f:
        json.dump({
            "schemaVersion": 2,
            "manifests": [{
                "mediaType":
                    "application/vnd.oci.image.manifest.v1+json",
                "digest": manifest_digest,
                "size": len(manifest)}],
        }, f)
    with open(os.path.join(layout_dir, "oci-layout"), "w",
              encoding="utf-8") as f:
        json.dump({"imageLayoutVersion": "1.0.0"}, f)


def pack_db_archive(bolt_bytes: bytes,
                    meta: Optional[Metadata] = None) -> bytes:
    """tgz holding trivy.db (+ metadata.json) — the layer payload."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        ti = tarfile.TarInfo("trivy.db")
        ti.size = len(bolt_bytes)
        tf.addfile(ti, io.BytesIO(bolt_bytes))
        if meta is not None:
            mb = json.dumps(meta.to_dict()).encode()
            ti = tarfile.TarInfo("metadata.json")
            ti.size = len(mb)
            tf.addfile(ti, io.BytesIO(mb))
    return gzip.compress(buf.getvalue())
