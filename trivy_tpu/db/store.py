"""In-memory advisory store with trivy-db access semantics.

API mirrors the reference's ``db.Config``: ``get_advisories(prefix,
pkg_name)`` scans every bucket whose name starts with the prefix
(driver.go:83-91), ``get(bucket, pkg_name)`` reads one bucket
(ospkg drivers), ``get_vulnerability(id)`` reads the detail record
(pkg/vulnerability/vulnerability.go:44)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..types import DataSource


@dataclass
class Advisory:
    """trivy-db types.Advisory — only fields the detectors consume."""

    vulnerability_id: str = ""
    fixed_version: str = ""
    affected_version: str = ""      # Alpine "introduced in"
    vulnerable_versions: list = field(default_factory=list)
    patched_versions: list = field(default_factory=list)
    unaffected_versions: list = field(default_factory=list)
    arches: list = field(default_factory=list)
    severity: int = 0               # per-source severity enum value
    vendor_ids: list = field(default_factory=list)
    data_source: Optional[DataSource] = None
    # Red Hat: repositories/NVRs this advisory applies to. Empty =
    # applies everywhere. Flattened from trivy-db redhat-oval's
    # repository→CPE-index indirection (redhat.go:129-138) onto the
    # advisory record itself; observable narrowing is the same.
    content_sets: list = field(default_factory=list)

    @classmethod
    def from_dict(cls, vuln_id: str, d: dict) -> "Advisory":
        ds = d.get("DataSource")
        return cls(
            vulnerability_id=vuln_id,
            fixed_version=d.get("FixedVersion", ""),
            affected_version=d.get("AffectedVersion", ""),
            vulnerable_versions=list(d.get("VulnerableVersions") or []),
            patched_versions=list(d.get("PatchedVersions") or []),
            unaffected_versions=list(d.get("UnaffectedVersions") or []),
            arches=list(d.get("Arches") or []),
            severity=int(d.get("Severity", 0) or 0),
            vendor_ids=list(d.get("VendorIDs") or []),
            data_source=DataSource(
                id=ds.get("ID", ""), name=ds.get("Name", ""),
                url=ds.get("URL", "")) if ds else None,
            content_sets=list(d.get("ContentSets") or []),
        )


@dataclass
class VulnerabilityDetail:
    """trivy-db ``vulnerability`` bucket record."""

    id: str = ""
    title: str = ""
    description: str = ""
    severity: str = ""
    vendor_severity: dict = field(default_factory=dict)
    cvss: dict = field(default_factory=dict)
    cwe_ids: list = field(default_factory=list)
    references: list = field(default_factory=list)
    published_date: str = ""
    last_modified_date: str = ""

    @classmethod
    def from_dict(cls, vuln_id: str, d: dict) -> "VulnerabilityDetail":
        sev = d.get("Severity", "")
        if isinstance(sev, int):
            from ..types import SEVERITIES
            sev = str(SEVERITIES[sev]) if 0 <= sev < 5 else ""
        return cls(
            id=vuln_id,
            title=d.get("Title", ""),
            description=d.get("Description", ""),
            severity=sev,
            vendor_severity=dict(d.get("VendorSeverity") or {}),
            cvss=dict(d.get("CVSS") or {}),
            cwe_ids=list(d.get("CweIDs") or []),
            references=list(d.get("References") or []),
            published_date=d.get("PublishedDate", ""),
            last_modified_date=d.get("LastModifiedDate", ""),
        )


class AdvisoryStore:
    """bucket name → package name → {cve id → advisory dict}."""

    def __init__(self):
        self.buckets: dict = {}
        self.vulnerabilities: dict = {}
        self.data_sources: dict = {}
        self._adv_cache: dict = {}      # (bucket, pkg) → [Advisory]
        self._detail_cache: dict = {}   # vuln id → detail
        self._cpe_names = None          # index → [repo/nvr names]
        # mutation epoch: the findings memo (trivy_tpu.memo) caches
        # this store's content fingerprint against it, so fixture
        # stores mutated after a scan re-fingerprint correctly
        self.mutations = 0

    # --- writes ---

    def put_advisory(self, bucket: str, pkg: str, vuln_id: str,
                     value: dict) -> None:
        self.buckets.setdefault(bucket, {}) \
            .setdefault(pkg, {})[vuln_id] = value
        self._adv_cache.pop((bucket, pkg), None)
        self.mutations += 1
        if bucket == "Red Hat CPE":
            # the CPE mapping feeds every expanded Red Hat advisory
            self._cpe_names = None
            self._adv_cache = {}

    def put_vulnerability(self, vuln_id: str, value: dict) -> None:
        self.vulnerabilities[vuln_id] = value
        self._detail_cache.pop(vuln_id, None)
        self.mutations += 1

    def put_data_source(self, bucket: str, value: dict) -> None:
        self.data_sources[bucket] = value
        self.mutations += 1
        self._adv_cache = {k: v for k, v in self._adv_cache.items()
                           if k[0] != bucket}

    # --- reads (db.Config semantics) ---

    def get(self, bucket: str, pkg_name: str) -> list:
        """Advisories for one package in one bucket. Non-dict values
        (metadata buckets like "Red Hat CPE" repo→CPE maps) are not
        advisories and are skipped. Decoded Advisory lists are
        memoized per (bucket, pkg): a 512-image fleet asks for the
        same handful of packages tens of thousands of times, and
        re-building dataclasses dominated the job-prep phase."""
        key = (bucket, pkg_name)
        cached = self._adv_cache.get(key)
        if cached is not None:
            return cached
        out = []
        for vid, v in (self.buckets.get(bucket, {})
                       .get(pkg_name, {})).items():
            if not isinstance(v, dict):
                continue
            if "Entries" in v:
                # trivy-db redhat-oval v2 record (vulnsrc
                # redhat-oval: per-entry CPE indices + CVE list)
                out.extend(self._expand_redhat(vid, v, bucket))
                continue
            adv = Advisory.from_dict(vid, v)
            if adv.data_source is None:
                adv.data_source = self._bucket_source(bucket)
            out.append(adv)
        self._adv_cache[key] = out
        return out

    def _expand_redhat(self, key: str, value: dict,
                       bucket: str) -> list:
        """redhat-oval schema → flat advisories: one per
        (entry, CVE), with the entry's Affected CPE indices
        translated back to repository/NVR names so the Red Hat
        driver's content-set narrowing applies
        (redhat.go:129-138 + trivy-db redhat-oval Get). The
        advisory key is a CVE id or an RHSA/RHBA vendor id; vendor
        keys surface as VendorIDs on each carried CVE."""
        idx_names = self._cpe_index_names()
        out = []
        for entry in value.get("Entries") or []:
            affected = []
            for i in entry.get("Affected") or []:
                try:
                    affected.append(int(i))
                except (TypeError, ValueError):
                    continue        # malformed row: skip, not crash
            sets = sorted({name for i in affected
                           for name in idx_names.get(i, [])})
            if affected and not sets:
                # indices with no known repository/NVR: keep the
                # entry narrowed (it can never match), not open
                sets = [f"cpe-index:{i}" for i in affected]
            cves = entry.get("Cves") or [{}]
            for cve in cves:
                vuln_id = cve.get("ID") or key
                out.append(Advisory(
                    vulnerability_id=vuln_id,
                    fixed_version=entry.get("FixedVersion", ""),
                    arches=list(entry.get("Arches") or []),
                    severity=int(cve.get("Severity", 0) or 0),
                    vendor_ids=[key] if key != vuln_id else [],
                    content_sets=sets,
                    data_source=self._bucket_source(bucket)))
        return out

    def _cpe_index_names(self) -> dict:
        """index → [repository/NVR names] inverted from the
        "Red Hat CPE" bucket's repository and nvr sub-buckets."""
        if self._cpe_names is None:
            inv: dict = {}
            cpe = self.buckets.get("Red Hat CPE", {})
            for sub in ("repository", "nvr"):
                for name, indices in (cpe.get(sub) or {}).items():
                    if not isinstance(indices, list):
                        continue
                    for i in indices:
                        try:
                            inv.setdefault(int(i), []).append(name)
                        except (TypeError, ValueError):
                            continue
            self._cpe_names = inv
        return self._cpe_names

    def get_advisories(self, prefix: str, pkg_name: str) -> list:
        """Prefix scan over buckets (e.g. ``pip::``) — driver.go:83."""
        out = []
        for bucket in sorted(self.buckets):
            if bucket.startswith(prefix):
                out.extend(self.get(bucket, pkg_name))
        return out

    def get_vulnerability(self, vuln_id: str)\
            -> Optional[VulnerabilityDetail]:
        """Memoized like get(): enrichment asks for the same CVE
        once per affected image across a fleet."""
        detail = self._detail_cache.get(vuln_id)
        if detail is not None:
            return detail
        v = self.vulnerabilities.get(vuln_id)
        if v is None:
            return None
        detail = VulnerabilityDetail.from_dict(vuln_id, v)
        self._detail_cache[vuln_id] = detail
        return detail

    def _bucket_source(self, bucket: str) -> Optional[DataSource]:
        d = self.data_sources.get(bucket)
        if not d:
            return None
        return DataSource(id=d.get("ID", ""), name=d.get("Name", ""),
                          url=d.get("URL", ""))
