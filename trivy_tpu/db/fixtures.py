"""YAML bucket fixture loader — the dbtest pattern.

Reference: pkg/dbtest/db.go loads YAML fixtures (bucket → package →
CVE → advisory, integration/testdata/fixtures/db/*.yaml) into a temp
BoltDB via bolt-fixtures. Here they load straight into AdvisoryStore;
the fixture FORMAT is kept identical so the reference's fixture files
remain usable."""

from __future__ import annotations

import re as _re

from .store import AdvisoryStore

try:
    import yaml
except ImportError:  # pragma: no cover
    yaml = None


def load_fixtures(paths: list, store: AdvisoryStore = None)\
        -> AdvisoryStore:
    if yaml is None:  # pragma: no cover
        raise RuntimeError("PyYAML required for fixture loading")
    if store is None:
        store = AdvisoryStore()
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        try:
            docs = yaml.safe_load(text) or []
        except yaml.YAMLError:
            # the reference's own fixtures carry go-yaml-tolerated
            # quirks (trailing comma after a quoted list item);
            # strip them and retry
            cleaned = _re.sub(r'^(\s*- ".*"),\s*$', r"\1", text,
                              flags=_re.MULTILINE)
            docs = yaml.safe_load(cleaned) or []
        for top in docs:
            _load_bucket(store, top)
    return store


def _load_bucket(store: AdvisoryStore, top: dict) -> None:
    bucket = top.get("bucket", "")
    pairs = top.get("pairs") or []
    if bucket == "vulnerability":
        for p in pairs:
            store.put_vulnerability(p["key"], p.get("value") or {})
        return
    if bucket == "data-source":
        for p in pairs:
            store.put_data_source(p["key"], p.get("value") or {})
        return
    for p in pairs:
        if "bucket" in p:        # nested: package bucket
            pkg = p["bucket"]
            for kv in p.get("pairs") or []:
                store.put_advisory(bucket, pkg, kv["key"],
                                   kv.get("value") or {})
        else:                    # flat key under source bucket
            store.put_advisory(bucket, p["key"], "", p.get("value")
                               or {})
