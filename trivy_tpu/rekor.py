"""Rekor transparency-log client (reference: pkg/rekor/client.go).

Searches the log by artifact sha256 and fetches entries whose
attestations carry SBOMs (the reference uses this to discover SBOM
attestations for bare executables). HTTP against the Rekor REST API
(``/api/v1/index/retrieve`` + ``/api/v1/log/entries/retrieve``);
in this zero-egress build the default endpoint fails with a clean
error and tests drive the same code against a local fake server.
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.request
from dataclasses import dataclass

from .utils import get_logger

log = get_logger("rekor")

DEFAULT_URL = "https://rekor.sigstore.dev"
MAX_GET_ENTRIES = 10       # client.go MaxGetEntriesLimit

_TREE_ID_LEN = 16
_UUID_LEN = 64


class RekorError(RuntimeError):
    pass


@dataclass
class EntryID:
    tree_id: str = ""
    uuid: str = ""

    @classmethod
    def parse(cls, raw: str) -> "EntryID":
        """client.go:33-46: 80 hex chars = treeID+uuid, 64 = uuid."""
        if len(raw) == _TREE_ID_LEN + _UUID_LEN:
            return cls(tree_id=raw[:_TREE_ID_LEN],
                       uuid=raw[_TREE_ID_LEN:])
        if len(raw) == _UUID_LEN:
            return cls(uuid=raw)
        raise RekorError(f"invalid Entry ID length: {raw!r}")

    def __str__(self) -> str:
        return self.tree_id + self.uuid


@dataclass
class Entry:
    statement: bytes = b""


class Client:
    def __init__(self, url: str = DEFAULT_URL,
                 timeout_s: float = 30.0):
        self.base_url = url.rstrip("/")
        self.timeout_s = timeout_s

    def _call(self, method: str, path: str, body=None) -> object:
        req = urllib.request.Request(
            self.base_url + path,
            data=json.dumps(body).encode() if body is not None
            else None,
            method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                return json.loads(resp.read() or b"null")
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise RekorError(
                f"rekor request failed (network egress needed for "
                f"{self.base_url}): {e}")

    def search(self, hash_: str) -> list:
        """sha256 → entry ids (client.go:73-90 Search)."""
        payload = self._call("POST", "/api/v1/index/retrieve",
                             {"hash": hash_})
        return [EntryID.parse(raw) for raw in payload or []]

    def get_entries(self, entry_ids: list) -> list:
        """entry ids → attestation statements (client.go:92-)."""
        if len(entry_ids) > MAX_GET_ENTRIES:
            raise RekorError(
                f"over get entries limit ({MAX_GET_ENTRIES})")
        if not entry_ids:
            return []
        requested = {str(e) for e in entry_ids} | \
            {e.uuid for e in entry_ids}
        payload = self._call(
            "POST", "/api/v1/log/entries/retrieve",
            {"entryUUIDs": [str(e) for e in entry_ids]})
        out = []
        for record in payload or []:
            for key, entry in record.items():
                if key not in requested:
                    # never attribute someone else's attestation to
                    # this artifact (client.go filters the same way)
                    log.debug("unrequested entry %s skipped", key)
                    continue
                att = (entry.get("attestation") or {}).get("data")
                if att:
                    try:
                        out.append(Entry(
                            statement=base64.b64decode(att)))
                    except ValueError:
                        log.debug("undecodable attestation skipped")
        return out


def discover_sbom(client: Client, artifact_digest: str):
    """The integration point the reference uses this client for
    (executable → SBOM attestation discovery): search the log by the
    artifact's sha256, fetch attestation statements, and decode the
    first CycloneDX predicate into a scannable SBOM. Returns a
    DecodedSBOM or None."""
    import json as json_mod

    from .sbom import cyclonedx as cdx

    ids = client.search(artifact_digest)
    for entry in client.get_entries(ids[:MAX_GET_ENTRIES]):
        try:
            stmt = json_mod.loads(entry.statement)
        except ValueError:
            continue
        if stmt.get("predicateType") != "https://cyclonedx.org/bom":
            continue
        predicate = stmt.get("predicate") or {}
        bom = predicate.get("Data", predicate)
        if isinstance(bom, str):
            try:
                bom = json_mod.loads(bom)
            except ValueError:
                continue
        if isinstance(bom, dict):
            return cdx.unmarshal(bom)
    return None
