"""Phrase-based license classifier.

The reference wraps google/licenseclassifier v2 (classifier.go:42),
which ships a corpus of full license texts. This re-design detects
licenses from three signals, strongest first:

1. an explicit ``SPDX-License-Identifier:`` tag (confidence 1.0),
2. n-gram containment against the embedded corpus of license
   cores (corpus.py) — catches reflowed/re-indented bodies,
3. a distinctive full-text phrase unique to one license,
4. the license's canonical title line.

That covers the common case — LICENSE/COPYING files and source
headers for the licenses that dominate real software — without the
megabyte corpus. Confidence reflects the signal: 1.0 for SPDX tags,
the containment fraction (>= 0.9) for corpus matches, 0.9 for
distinctive phrases, 0.8 for title matches.
"""

from __future__ import annotations

import re

from ..types import LicenseFile, LicenseFinding
from .normalize import normalize

# max bytes inspected for header classification (code files)
HEAD_SIZE = 4096

_SPDX_RE = re.compile(
    r"SPDX-License-Identifier:\s*\(?([A-Za-z0-9.+-]+"
    r"(?:\s+(?:OR|AND|WITH)\s+[A-Za-z0-9.+-]+)*)\)?",
    re.IGNORECASE)

# (license, distinctive phrase) — lowercase substring unique enough to
# identify the license body
_PHRASES = [
    ("MIT", "permission is hereby granted, free of charge, to any "
     "person obtaining a copy"),
    ("Apache-2.0", "licensed under the apache license, version 2.0"),
    ("Apache-2.0", "apache license\n"
     "                           version 2.0, january 2004"),
    ("GPL-3.0", "gnu general public license\n"
     "                       version 3, 29 june 2007"),
    ("GPL-3.0", "under the terms of the gnu general public license "
     "as published by\nthe free software foundation, either "
     "version 3"),
    ("GPL-2.0", "gnu general public license, version 2"),
    ("GPL-2.0", "gnu general public license\n"
     "                       version 2, june 1991"),
    ("GPL-2.0", "under the terms of the gnu general public license "
     "as published by\nthe free software foundation; either "
     "version 2"),
    ("LGPL-3.0", "gnu lesser general public license\n"
     "                       version 3, 29 june 2007"),
    ("LGPL-2.1", "gnu lesser general public license\n"
     "                       version 2.1, february 1999"),
    ("AGPL-3.0", "gnu affero general public license\n"
     "                       version 3, 19 november 2007"),
    ("AGPL-3.0", "gnu affero general public license as published"),
    ("BSD-3-Clause", "neither the name of"),
    ("BSD-2-Clause", "redistributions in binary form must reproduce "
     "the above copyright"),
    ("MPL-2.0", "this source code form is subject to the terms of "
     "the mozilla public\nlicense, v. 2.0"),
    ("MPL-2.0", "mozilla public license version 2.0"),
    ("ISC", "permission to use, copy, modify, and/or distribute "
     "this software for any\npurpose with or without fee"),
    ("Unlicense", "this is free and unencumbered software released "
     "into the public domain"),
    ("WTFPL", "do what the fuck you want to public license"),
    ("CC0-1.0", "creative commons legal code\n\ncc0 1.0 universal"),
    ("CC0-1.0", "cc0 1.0 universal"),
    ("EPL-2.0", "eclipse public license - v 2.0"),
    ("EPL-1.0", "eclipse public license - v 1.0"),
    ("Zlib", "this software is provided 'as-is', without any "
     "express or implied\nwarranty"),
    ("OpenSSL", "openssl license"),
    ("Artistic-2.0", "the artistic license 2.0"),
    ("0BSD", "zero-clause bsd"),
]

# BSD-2 phrase is a subset of BSD-3 text; check specificity order and
# keep the first (most specific) hit per license family
_FAMILY = {
    "BSD-2-Clause": "bsd", "BSD-3-Clause": "bsd",
    "GPL-2.0": "gpl", "GPL-3.0": "gpl",
    "LGPL-2.1": "lgpl", "LGPL-3.0": "lgpl",
    "EPL-1.0": "epl", "EPL-2.0": "epl",
    "AGPL-3.0": "agpl",
}

_AVD_LINK = "https://spdx.org/licenses/{}.html"


def classify_findings(content: bytes) -> list:
    """→ [LicenseFinding], best signal per license family."""
    text = content.decode("utf-8", "replace")
    findings = []
    seen = set()
    families = set()

    for m in _SPDX_RE.finditer(text):
        for name in re.split(r"\s+(?:OR|AND)\s+", m.group(1),
                             flags=re.IGNORECASE):
            # "X WITH exception" qualifies X; the exception is not a
            # license of its own
            name = re.split(r"\s+WITH\s+", name,
                            flags=re.IGNORECASE)[0].strip("()")
            if name and name not in seen:
                seen.add(name)
                canonical = normalize(name)
                families.add(_FAMILY.get(canonical, canonical))
                findings.append(LicenseFinding(
                    name=name, confidence=1.0,
                    link=_AVD_LINK.format(name)))

    from .corpus import corpus_matches
    for name, confidence in corpus_matches(text):
        family = _FAMILY.get(name, name)
        if name in seen or family in families:
            continue
        seen.add(name)
        families.add(family)
        findings.append(LicenseFinding(
            name=name, confidence=confidence,
            link=_AVD_LINK.format(name)))

    lowered = text.lower()
    for name, phrase in _PHRASES:
        if name in seen:
            continue
        family = _FAMILY.get(name, name)
        if family in families:
            continue
        if phrase in lowered:
            seen.add(name)
            families.add(family)
            findings.append(LicenseFinding(
                name=name, confidence=0.9,
                link=_AVD_LINK.format(name)))
    return findings


def is_human_readable(content: bytes) -> bool:
    """Binary sniff (ref license.go isHumanReadable — file(1)'s text
    heuristic)."""
    head = content[:300]
    for b in head:
        if b < 7 or b == 11 or 13 < b < 27 or 27 < b < 0x20 or \
                b == 0x7F:
            return False
    return True


def classify(file_path: str, content: bytes,
             full: bool = False) -> LicenseFile:
    """File → LicenseFile (ref classifier.go Classify/FullClassify):
    license-named files classify on the whole text, code files on the
    head only."""
    data = content if full else content[:HEAD_SIZE]
    return LicenseFile(
        type="license-file" if full else "header",
        file_path=file_path,
        findings=classify_findings(data),
    )
