"""License corpus similarity matching.

The reference wraps google/licenseclassifier v2
(pkg/licensing/classifier.go:42), which normalizes text and scores
q-gram overlap against a corpus of license texts, keeping matches
with confidence > 0.9 (classifier.go Classify). This module is the
same idea sized for an embedded corpus: each entry stores the
distinctive operative core of a license (not the megabyte full
text); a document matches when >= 90% of the entry's word 5-grams
appear in the document after normalization (lowercase, punctuation
folded, whitespace collapsed). That makes detection robust to
reflowed, re-indented, or re-wrapped license bodies that the
phrase fast-path in classifier.py misses.

Entries are LISTS of excerpts: n-grams are built per excerpt and
unioned, so no spurious grams span excerpt boundaries.

Subset suppression: several licenses textually contain others
(BSD-3-Clause adds one clause to BSD-2-Clause; ISC is 0BSD plus a
notice-retention condition). After thresholding, candidates are
accepted best-first and a candidate is dropped when >= 90% of its
grams are already covered by an accepted match's grams.
"""

from __future__ import annotations

import re

_N = 5                  # words per gram
_THRESHOLD = 0.9        # ref classifier.go: match.Confidence > 0.9

_TOKEN_RE = re.compile(r"[a-z0-9']+")


def _tokens(text: str) -> list:
    return _TOKEN_RE.findall(text.lower())


def _grams(tokens: list) -> set:
    return {tuple(tokens[i:i + _N])
            for i in range(len(tokens) - _N + 1)}


# license -> list of distinctive excerpts of its operative core.
# Copyright/ownership lines are deliberately absent (they vary per
# project; the containment direction corpus-in-document makes extra
# document text harmless).
_CORPUS_TEXTS = {
    "MIT": [
        "Permission is hereby granted, free of charge, to any "
        "person obtaining a copy of this software and associated "
        "documentation files (the \"Software\"), to deal in the "
        "Software without restriction, including without "
        "limitation the rights to use, copy, modify, merge, "
        "publish, distribute, sublicense, and/or sell copies of "
        "the Software, and to permit persons to whom the Software "
        "is furnished to do so, subject to the following "
        "conditions: The above copyright notice and this "
        "permission notice shall be included in all copies or "
        "substantial portions of the Software.",
        "THE SOFTWARE IS PROVIDED \"AS IS\", WITHOUT WARRANTY OF "
        "ANY KIND, EXPRESS OR IMPLIED, INCLUDING BUT NOT LIMITED "
        "TO THE WARRANTIES OF MERCHANTABILITY, FITNESS FOR A "
        "PARTICULAR PURPOSE AND NONINFRINGEMENT. IN NO EVENT "
        "SHALL THE AUTHORS OR COPYRIGHT HOLDERS BE LIABLE FOR ANY "
        "CLAIM, DAMAGES OR OTHER LIABILITY, WHETHER IN AN ACTION "
        "OF CONTRACT, TORT OR OTHERWISE, ARISING FROM, OUT OF OR "
        "IN CONNECTION WITH THE SOFTWARE OR THE USE OR OTHER "
        "DEALINGS IN THE SOFTWARE.",
    ],
    "ISC": [
        "Permission to use, copy, modify, and/or distribute this "
        "software for any purpose with or without fee is hereby "
        "granted, provided that the above copyright notice and "
        "this permission notice appear in all copies.",
        "THE SOFTWARE IS PROVIDED \"AS IS\" AND THE AUTHOR "
        "DISCLAIMS ALL WARRANTIES WITH REGARD TO THIS SOFTWARE "
        "INCLUDING ALL IMPLIED WARRANTIES OF MERCHANTABILITY AND "
        "FITNESS. IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY "
        "SPECIAL, DIRECT, INDIRECT, OR CONSEQUENTIAL DAMAGES OR "
        "ANY DAMAGES WHATSOEVER RESULTING FROM LOSS OF USE, DATA "
        "OR PROFITS, WHETHER IN AN ACTION OF CONTRACT, NEGLIGENCE "
        "OR OTHER TORTIOUS ACTION, ARISING OUT OF OR IN "
        "CONNECTION WITH THE USE OR PERFORMANCE OF THIS SOFTWARE.",
    ],
    "0BSD": [
        "Permission to use, copy, modify, and/or distribute this "
        "software for any purpose with or without fee is hereby "
        "granted.",
        "THE SOFTWARE IS PROVIDED \"AS IS\" AND THE AUTHOR "
        "DISCLAIMS ALL WARRANTIES WITH REGARD TO THIS SOFTWARE "
        "INCLUDING ALL IMPLIED WARRANTIES OF MERCHANTABILITY AND "
        "FITNESS. IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY "
        "SPECIAL, DIRECT, INDIRECT, OR CONSEQUENTIAL DAMAGES OR "
        "ANY DAMAGES WHATSOEVER RESULTING FROM LOSS OF USE, DATA "
        "OR PROFITS, WHETHER IN AN ACTION OF CONTRACT, NEGLIGENCE "
        "OR OTHER TORTIOUS ACTION, ARISING OUT OF OR IN "
        "CONNECTION WITH THE USE OR PERFORMANCE OF THIS SOFTWARE.",
    ],
    "BSD-2-Clause": [
        "Redistribution and use in source and binary forms, with "
        "or without modification, are permitted provided that the "
        "following conditions are met: 1. Redistributions of "
        "source code must retain the above copyright notice, this "
        "list of conditions and the following disclaimer. 2. "
        "Redistributions in binary form must reproduce the above "
        "copyright notice, this list of conditions and the "
        "following disclaimer in the documentation and/or other "
        "materials provided with the distribution.",
        "THIS SOFTWARE IS PROVIDED BY THE COPYRIGHT HOLDERS AND "
        "CONTRIBUTORS \"AS IS\" AND ANY EXPRESS OR IMPLIED "
        "WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED "
        "WARRANTIES OF MERCHANTABILITY AND FITNESS FOR A "
        "PARTICULAR PURPOSE ARE DISCLAIMED. IN NO EVENT SHALL THE "
        "COPYRIGHT HOLDER OR CONTRIBUTORS BE LIABLE FOR ANY "
        "DIRECT, INDIRECT, INCIDENTAL, SPECIAL, EXEMPLARY, OR "
        "CONSEQUENTIAL DAMAGES (INCLUDING, BUT NOT LIMITED TO, "
        "PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF "
        "USE, DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER "
        "CAUSED AND ON ANY THEORY OF LIABILITY, WHETHER IN "
        "CONTRACT, STRICT LIABILITY, OR TORT (INCLUDING "
        "NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE "
        "USE OF THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY "
        "OF SUCH DAMAGE.",
    ],
    "BSD-3-Clause": [
        "Redistribution and use in source and binary forms, with "
        "or without modification, are permitted provided that the "
        "following conditions are met: 1. Redistributions of "
        "source code must retain the above copyright notice, this "
        "list of conditions and the following disclaimer. 2. "
        "Redistributions in binary form must reproduce the above "
        "copyright notice, this list of conditions and the "
        "following disclaimer in the documentation and/or other "
        "materials provided with the distribution. 3. Neither the "
        "name of the copyright holder nor the names of its "
        "contributors may be used to endorse or promote products "
        "derived from this software without specific prior "
        "written permission.",
        "THIS SOFTWARE IS PROVIDED BY THE COPYRIGHT HOLDERS AND "
        "CONTRIBUTORS \"AS IS\" AND ANY EXPRESS OR IMPLIED "
        "WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED "
        "WARRANTIES OF MERCHANTABILITY AND FITNESS FOR A "
        "PARTICULAR PURPOSE ARE DISCLAIMED. IN NO EVENT SHALL THE "
        "COPYRIGHT HOLDER OR CONTRIBUTORS BE LIABLE FOR ANY "
        "DIRECT, INDIRECT, INCIDENTAL, SPECIAL, EXEMPLARY, OR "
        "CONSEQUENTIAL DAMAGES (INCLUDING, BUT NOT LIMITED TO, "
        "PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF "
        "USE, DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER "
        "CAUSED AND ON ANY THEORY OF LIABILITY, WHETHER IN "
        "CONTRACT, STRICT LIABILITY, OR TORT (INCLUDING "
        "NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE "
        "USE OF THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY "
        "OF SUCH DAMAGE.",
    ],
    "BSD-4-Clause": [
        "All advertising materials mentioning features or use of "
        "this software must display the following "
        "acknowledgement: This product includes software "
        "developed by",
        "Redistribution and use in source and binary forms, with "
        "or without modification, are permitted provided that the "
        "following conditions are met: 1. Redistributions of "
        "source code must retain the above copyright notice, this "
        "list of conditions and the following disclaimer.",
    ],
    "Apache-2.0": [
        "\"License\" shall mean the terms and conditions for use, "
        "reproduction, and distribution as defined by Sections 1 "
        "through 9 of this document.",
        "Grant of Copyright License. Subject to the terms and "
        "conditions of this License, each Contributor hereby "
        "grants to You a perpetual, worldwide, non-exclusive, "
        "no-charge, royalty-free, irrevocable copyright license "
        "to reproduce, prepare Derivative Works of, publicly "
        "display, publicly perform, sublicense, and distribute "
        "the Work and such Derivative Works in Source or Object "
        "form.",
        "Redistribution. You may reproduce and distribute copies "
        "of the Work or Derivative Works thereof in any medium, "
        "with or without modifications, and in Source or Object "
        "form, provided that You meet the following conditions:",
    ],
    "GPL-2.0": [
        "The licenses for most software are designed to take away "
        "your freedom to share and change it. By contrast, the "
        "GNU General Public License is intended to guarantee your "
        "freedom to share and change free software--to make sure "
        "the software is free for all its users.",
        "You may copy and distribute verbatim copies of the "
        "Program's source code as you receive it, in any medium, "
        "provided that you conspicuously and appropriately "
        "publish on each copy an appropriate copyright notice and "
        "disclaimer of warranty",
    ],
    "GPL-3.0": [
        "The GNU General Public License is a free, copyleft "
        "license for software and other kinds of works.",
        "When we speak of free software, we are referring to "
        "freedom, not price. Our General Public Licenses are "
        "designed to make sure that you have the freedom to "
        "distribute copies of free software (and charge for them "
        "if you wish), that you receive source code or can get it "
        "if you want it, that you can change the software or use "
        "pieces of it in new free programs, and that you know you "
        "can do these things.",
    ],
    "LGPL-2.1": [
        "This license, the Lesser General Public License, applies "
        "to some specially designated software packages--"
        "typically libraries--of the Free Software Foundation and "
        "other authors who decide to use it.",
        "When we speak of free software, we are referring to "
        "freedom of use, not price.",
    ],
    "LGPL-3.0": [
        "This version of the GNU Lesser General Public License "
        "incorporates the terms and conditions of version 3 of "
        "the GNU General Public License, supplemented by the "
        "additional permissions listed below.",
        "You may convey a covered work under sections 3 and 4 of "
        "this License without being bound by section 3 of the GNU "
        "GPL.",
    ],
    "AGPL-3.0": [
        "The GNU Affero General Public License is a free, "
        "copyleft license for software and other kinds of works, "
        "specifically designed to ensure cooperation with the "
        "community in the case of network server software.",
    ],
    "MPL-2.0": [
        "\"Source Code Form\" means the form of the work "
        "preferred for making modifications.",
        "Each Contributor hereby grants You a world-wide, "
        "royalty-free, non-exclusive license: under intellectual "
        "property rights (other than patent or trademark) "
        "Licensable by such Contributor to use, reproduce, make "
        "available, modify, display, perform, distribute, and "
        "otherwise exploit its Contributions, either on an "
        "unmodified basis, with Modifications, or as part of a "
        "Larger Work;",
    ],
    "Unlicense": [
        "This is free and unencumbered software released into the "
        "public domain. Anyone is free to copy, modify, publish, "
        "use, compile, sell, or distribute this software, either "
        "in source code form or as a compiled binary, for any "
        "purpose, commercial or non-commercial, and by any means.",
        "In jurisdictions that recognize copyright laws, the "
        "author or authors of this software dedicate any and all "
        "copyright interest in the software to the public domain. "
        "We make this dedication for the benefit of the public at "
        "large and to the detriment of our heirs and successors.",
    ],
    "Zlib": [
        "This software is provided 'as-is', without any express "
        "or implied warranty. In no event will the authors be "
        "held liable for any damages arising from the use of this "
        "software. Permission is granted to anyone to use this "
        "software for any purpose, including commercial "
        "applications, and to alter it and redistribute it "
        "freely, subject to the following restrictions: 1. The "
        "origin of this software must not be misrepresented; you "
        "must not claim that you wrote the original software.",
        "2. Altered source versions must be plainly marked as "
        "such, and must not be misrepresented as being the "
        "original software. 3. This notice may not be removed or "
        "altered from any source distribution.",
    ],
    "WTFPL": [
        "Everyone is permitted to copy and distribute verbatim or "
        "modified copies of this license document, and changing "
        "it is allowed as long as the name is changed.",
        "0. You just DO WHAT THE FUCK YOU WANT TO.",
    ],
    "CC0-1.0": [
        "Certain owners wish to permanently relinquish those "
        "rights to a Work for the purpose of contributing to a "
        "commons of creative, cultural and scientific works",
    ],
    "Artistic-2.0": [
        "This license establishes the terms under which a given "
        "free software Package may be copied, modified, "
        "distributed, and/or redistributed.",
    ],
    "BSL-1.0": [
        "Permission is hereby granted, free of charge, to any "
        "person or organization obtaining a copy of the software "
        "and accompanying documentation covered by this license "
        "(the \"Software\") to use, reproduce, display, "
        "distribute, execute, and transmit the Software, and to "
        "prepare derivative works of the Software, and to permit "
        "third-parties to whom the Software is furnished to do "
        "so",
    ],
    "PostgreSQL": [
        "Permission to use, copy, modify, and distribute this "
        "software and its documentation for any purpose, without "
        "fee, and without a written agreement is hereby granted, "
        "provided that the above copyright notice and this "
        "paragraph and the following two paragraphs appear in all "
        "copies.",
    ],
    "OFL-1.1": [
        "Permission is hereby granted, free of charge, to any "
        "person obtaining a copy of the Font Software, to use, "
        "study, copy, merge, embed, modify, redistribute, and "
        "sell modified and unmodified copies of the Font "
        "Software",
    ],
}

_compiled = None


def _corpus():
    """[(name, gramset)] sorted largest-first, built lazily (the
    reference preloads its corpus once too — classifier.go
    initLicenseDB)."""
    global _compiled
    if _compiled is None:
        entries = []
        for name, excerpts in _CORPUS_TEXTS.items():
            grams = set()
            for excerpt in excerpts:
                grams |= _grams(_tokens(excerpt))
            entries.append((name, frozenset(grams)))
        entries.sort(key=lambda e: -len(e[1]))
        _compiled = entries
    return _compiled


def corpus_matches(text: str, threshold: float = _THRESHOLD) -> list:
    """→ [(license, confidence)] for every corpus entry whose grams
    are >= threshold contained in the normalized document, with
    textual-subset candidates suppressed."""
    tokens = _tokens(text)
    if len(tokens) < _N:
        return []
    doc = _grams(tokens)

    candidates = []
    for name, grams in _corpus():
        hit = sum(1 for g in grams if g in doc)
        containment = hit / len(grams)
        if containment >= threshold:
            candidates.append((containment, len(grams), name, grams))
    # Largest entry first: real-world BSD-3 texts substitute an org
    # name into clause 3, scoring slightly below their own corpus
    # entry while the BSD-2 subset still scores 1.0 — specificity
    # must outrank raw containment, then the subset check below
    # drops the contained entry.
    candidates.sort(key=lambda c: (-c[1], -c[0]))

    accepted = []
    out = []
    for containment, _, name, grams in candidates:
        if any(len(grams & prior) / len(grams) >= 0.9
               for prior in accepted):
            continue        # textual subset of a more specific match
        accepted.append(grams)
        out.append((name, round(containment, 2)))
    return out
