"""License name normalization (reference: pkg/licensing/normalize.go
— factual mapping constants)."""

from __future__ import annotations

_MAPPING = {
    # GPL
    "GPL-1": "GPL-1.0", "GPL-1+": "GPL-1.0", "GPL 1.0": "GPL-1.0",
    "GPL 1": "GPL-1.0",
    "GPL2": "GPL-2.0", "GPL 2.0": "GPL-2.0", "GPL 2": "GPL-2.0",
    "GPL-2": "GPL-2.0", "GPL-2.0-ONLY": "GPL-2.0", "GPL2+": "GPL-2.0",
    "GPLV2+": "GPL-2.0", "GPL-2+": "GPL-2.0", "GPL-2.0+": "GPL-2.0",
    "GPL-2.0-OR-LATER": "GPL-2.0",
    "GPL-2+ WITH AUTOCONF EXCEPTION":
        "GPL-2.0-with-autoconf-exception",
    "GPL3": "GPL-3.0", "GPL 3.0": "GPL-3.0", "GPL 3": "GPL-3.0",
    "GPLV3+": "GPL-3.0", "GPL-3": "GPL-3.0",
    "GPL-3.0-ONLY": "GPL-3.0", "GPL3+": "GPL-3.0",
    "GPL-3+": "GPL-3.0", "GPL-3.0-OR-LATER": "GPL-3.0",
    # the reference maps the GPL-3 bison variant onto the GPL-2.0
    # exception id (normalize.go:31) — kept verbatim for parity; both
    # land in the restricted category either way. The spaced forms
    # are what dpkg copyright files actually contain.
    "GPL-3+-WITH-BISON-EXCEPTION": "GPL-2.0-with-bison-exception",
    "GPL-3+ WITH BISON EXCEPTION": "GPL-2.0-with-bison-exception",
    "GPL": "GPL-3.0",
    # LGPL
    "LGPL2": "LGPL-2.0", "LGPL 2": "LGPL-2.0",
    "LGPL 2.0": "LGPL-2.0", "LGPL-2": "LGPL-2.0",
    "LGPL2+": "LGPL-2.0", "LGPL-2+": "LGPL-2.0",
    "LGPL-2.0+": "LGPL-2.0",
    "LGPL-2.1": "LGPL-2.1", "LGPL 2.1": "LGPL-2.1",
    "LGPL-2.1+": "LGPL-2.1", "LGPLV2.1+": "LGPL-2.1",
    "LGPL-3": "LGPL-3.0", "LGPL 3": "LGPL-3.0",
    "LGPL-3+": "LGPL-3.0", "LGPL": "LGPL-3.0",
    # MPL
    "MPL1.0": "MPL-1.0", "MPL1": "MPL-1.0", "MPL 1.0": "MPL-1.0",
    "MPL 1": "MPL-1.0",
    "MPL2.0": "MPL-2.0", "MPL 2.0": "MPL-2.0", "MPL2": "MPL-2.0",
    "MPL 2": "MPL-2.0",
    # BSD
    "BSD": "BSD-3-Clause", "BSD-2-CLAUSE": "BSD-2-Clause",
    "BSD-3-CLAUSE": "BSD-3-Clause", "BSD-4-CLAUSE": "BSD-4-Clause",
    "APACHE": "Apache-2.0", "APACHE 2.0": "Apache-2.0",
    "RUBY": "Ruby", "ZLIB": "Zlib",
}


def normalize(name: str) -> str:
    upper = name.upper()
    if upper in _MAPPING:
        return _MAPPING[upper]
    # SPDX modifier suffixes reduce to the base id
    for suffix in ("-ONLY", "-OR-LATER"):
        if upper.endswith(suffix):
            return normalize(name[: -len(suffix)])
    return name
