"""The repo-invariant rule set (docs/static-analysis.md).

Every rule encodes a bug class this repo actually shipped and then
hand-fixed in review:

* ``monotonic-clock``     — PR-8: wall-clock arithmetic corrupts
  span durations, timeline gaps, profiler buckets and SLO windows.
* ``lock-discipline``     — PR-4: ``SchedMetrics.snapshot`` called
  the live depth gauge under its own lock (self-deadlock with any
  metrics-touching gauge); generalized to a static lock-acquisition
  graph with inter-module cycle detection.
* ``hostpool-blocking``   — PR-5: a host-pool task blocking on
  ``pool.map`` of its own pool deadlocks once every worker is such
  a task.
* ``donation-safety``     — PR-11: reading a buffer after passing
  it to a ``donate_argnums`` jit call reads donated (freed) HBM.
* ``bare-except-at-seam`` — silent swallows at concurrency/IO seams
  hide the exact failures the fault harness exists to surface.
* ``unbounded-label-cardinality`` — PR-7/PR-8: every open-keyed
  dict that becomes a prom label family needs a cap/fold
  (``max_tenants`` → anon, span names → "other", profiler stacks →
  ``<overflow>``).

Shared machinery: one :class:`Index` built lazily over the whole
module set — per-function lock scopes, a call graph with confident
(exact or unanimous) name resolution, lock-nesting edges, a
donated-callable registry, and host-pool facts.

Scoping convention: package paths (``trivy_tpu/...``) honor each
rule's directory scope; any other path (in-memory test fixtures) is
always in scope for every rule.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional

from .engine import Finding, ModuleInfo, Rule

# method names owned by builtin containers / threading primitives:
# never resolved by bare name — `d.get(...)` under a lock must not
# match some analyzed class's locking `get`
_DENY_METHODS = frozenset((
    "get", "put", "pop", "push", "add", "set", "append", "extend",
    "insert", "remove", "discard", "clear", "copy", "update",
    "keys", "values", "items", "setdefault", "popitem", "popleft",
    "appendleft", "count", "index", "sort", "reverse", "join",
    "split", "strip", "format", "encode", "decode", "startswith",
    "endswith", "replace", "lower", "upper", "wait", "notify",
    "notify_all", "acquire", "release", "locked", "is_set",
    "result", "done", "cancel", "exception", "read", "write",
    "readline", "seek", "tell", "close", "flush", "open", "next",
    "send", "get_nowait", "put_nowait", "qsize", "empty", "full",
    "task_done", "map", "submit", "shutdown", "union", "render",
))

_CALLBACK_ATTR = re.compile(r"(_fn|_cb|_hook|_gauge)$")
_METRICS_GLOBAL = re.compile(r"^[A-Z_]*METRICS$")
_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition"))
_CAP_CONSTANTS = frozenset(("<overflow>", "other", "anon"))
_METRICSY_CLASS = re.compile(r"(Metrics|Book|Histogram|Recorder)")
_POOL_GUARD_NEEDLE = "trivy-hostpool"


def _unparse(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover — malformed node
        return "<expr>"


def _call_name(node: ast.Call) -> str:
    """Terminal identifier of the callee (``x.y.z(...)`` -> z)."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _receiver_text(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return _unparse(f.value)
    return ""


class FuncFacts:
    """Per-function facts extracted once by the index."""

    def __init__(self, module: str, rel: str, cls: str, name: str,
                 node):
        self.module = module
        self.rel = rel
        self.cls = cls
        self.name = name
        self.node = node
        self.lineno = node.lineno
        self.locks: set = set()           # lock ids acquired here
        self.calls: list = []             # (held lock ids, Call)
        self.pool_guard = False           # checks trivy-hostpool
        self.pool_blocking: list = []     # (lineno, description)
        self.pool_entries: list = []      # (lineno, callee expr)
        self.params: set = set()

    @property
    def qualname(self) -> str:
        base = f"{self.cls}.{self.name}" if self.cls else self.name
        return f"{self.module}.{base}" if self.module else base


class Index:
    """Whole-tree facts shared by the rules (built once per run)."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = {mi.name: mi for mi in modules}
        self.funcs: dict = {}             # (module,cls,name)->facts
        self.local_defs: dict = {}        # (module,cls,name)->[facts]
        self.methods_by_name: dict = {}   # name -> [facts]
        self.imports: dict = {}           # module->{local:(mod,orig)}
        self.lock_attrs: dict = {}        # (module,cls)->{attr}
        self.lock_globals: dict = {}      # module -> {name}
        self.donated: dict = {}           # (module,name)->positions
        self.nest_edges: list = []        # (A, B, rel, lineno)
        for mi in modules:
            self._scan_declarations(mi)
        for mi in modules:
            self._scan_module_functions(mi)

    # --- declaration pass ---

    def _scan_declarations(self, mi: ModuleInfo) -> None:
        imps: dict = {}
        self.imports[mi.name] = imps
        self.lock_globals.setdefault(mi.name, set())
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.ImportFrom):
                src = self._resolve_from(mi, node)
                for alias in node.names:
                    imps[alias.asname or alias.name] = \
                        (src, alias.name)
            elif isinstance(node, ast.ClassDef):
                attrs = self.lock_attrs.setdefault(
                    (mi.name, node.name), set())
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and \
                            self._is_lock_ctor(sub.value):
                        for t in sub.targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) \
                                    and t.value.id == "self":
                                attrs.add(t.attr)
            elif isinstance(node, ast.Assign):
                if self._is_lock_ctor(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.lock_globals[mi.name].add(t.id)
                pos = self._donate_positions(node.value)
                if pos is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.donated[(mi.name, t.id)] = pos

    @staticmethod
    def _resolve_from(mi: ModuleInfo, node: ast.ImportFrom) -> str:
        if not node.level:
            return node.module or ""
        parts = mi.name.split(".")
        # a leaf module (`pkg.sub.mod`) drops `level` trailing
        # components; a package __init__ (whose dotted name IS the
        # package) drops one fewer — `from .queue import x` inside
        # pkg/sub/__init__.py resolves to pkg.sub.queue
        drop = node.level - 1 if getattr(mi, "is_package", False) \
            else node.level
        base = parts[:len(parts) - drop] if drop <= len(parts) \
            else []
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)

    @staticmethod
    def _is_lock_ctor(value) -> bool:
        return (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and isinstance(value.func.value, ast.Name)
                and value.func.value.id == "threading"
                and value.func.attr in _LOCK_CTORS)

    @staticmethod
    def _donate_positions(value) -> Optional[tuple]:
        """``jax.jit(f, donate_argnums=...)`` -> donated positions."""
        if not (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr == "jit"):
            return None
        for kw in value.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and \
                    isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = tuple(e.value for e in v.elts
                            if isinstance(e, ast.Constant))
                return out or None
        return None

    # --- function pass ---

    def _scan_module_functions(self, mi: ModuleInfo) -> None:
        for node in mi.tree.body:
            if isinstance(node,
                          (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_one(mi, "", node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(
                            sub,
                            (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._scan_one(mi, node.name, sub)

    def _scan_one(self, mi: ModuleInfo, cls: str, node,
                  nested: bool = False) -> None:
        facts = FuncFacts(mi.name, mi.rel, cls, node.name, node)
        facts.params = {a.arg for a in node.args.args
                        if a.arg != "self"}
        if nested:
            # nested defs get a collision-proof key (two parents
            # may each define a local `job`; dropping the second
            # would blind the hostpool rule to its facts) and a
            # by-name entry the resolver consults — bare-name
            # calls resolve to EVERY same-named local def, which
            # over-approximates reachability, the safe direction
            # for a deadlock rule
            self.funcs[(mi.name, cls,
                        f"{node.name}@{node.lineno}")] = facts
            self.local_defs.setdefault(
                (mi.name, cls, node.name), []).append(facts)
        else:
            self.funcs[(mi.name, cls, node.name)] = facts
            if cls:
                self.methods_by_name.setdefault(
                    node.name, []).append(facts)
        pool_vars: set = set()
        submit_seen = False

        def lock_id(expr) -> Optional[str]:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and cls and \
                    expr.attr in self.lock_attrs.get(
                        (mi.name, cls), ()):
                return f"{mi.name}.{cls}.{expr.attr}"
            if isinstance(expr, ast.Name) and \
                    expr.id in self.lock_globals.get(mi.name, ()):
                return f"{mi.name}.{expr.id}"
            return None

        def visit(n, held: tuple) -> None:
            nonlocal submit_seen
            if isinstance(n, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in n.items:
                    visit(item.context_expr, held)
                    lid = lock_id(item.context_expr)
                    if lid:
                        facts.locks.add(lid)
                        for h in held:
                            if h != lid:
                                self.nest_edges.append(
                                    (h, lid, mi.rel, n.lineno))
                        acquired.append(lid)
                inner = held + tuple(acquired)
                for st in n.body:
                    visit(st, inner)
                return
            if isinstance(n,
                          (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not node:
                # nested def: its body runs when CALLED, not here —
                # index it as its own function (the hostpool rule
                # traverses call edges into it)
                self._scan_one(mi, cls, n, nested=True)
                return
            if isinstance(n, ast.Call):
                facts.calls.append((held, n))
            if isinstance(n, ast.Assign):
                if any(isinstance(c, ast.Call) and
                       _call_name(c) == "get_host_pool"
                       for c in ast.walk(n.value)):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            pool_vars.add(t.id)
            for child in ast.iter_child_nodes(n):
                visit(child, held)

        for st in node.body:
            visit(st, ())
        facts.pool_guard = any(
            isinstance(c, ast.Constant) and
            isinstance(c.value, str) and
            _POOL_GUARD_NEEDLE in c.value
            for c in ast.walk(node))
        # pool-blocking / pool-entry facts from the recorded calls
        for _held, call in facts.calls:
            name = _call_name(call)
            recv = _receiver_text(call)
            from_pool = recv in pool_vars or \
                recv == "get_host_pool()"
            if name == "map" and from_pool:
                facts.pool_blocking.append(
                    (call.lineno, f"{recv}.map(...)"))
            if name == "submit" and from_pool:
                submit_seen = True
                if call.args:
                    facts.pool_entries.append(
                        (call.lineno, call.args[0]))
            if name == "map_in_pool" and call.args:
                facts.pool_entries.append(
                    (call.lineno, call.args[0]))
        if submit_seen:
            for _held, call in facts.calls:
                if _call_name(call) == "result":
                    facts.pool_blocking.append(
                        (call.lineno,
                         "joins a future of the pool it was "
                         "submitted from"))
                    break

    # --- resolution ---

    def resolve_call(self, module: str, cls: str,
                     call: ast.Call) -> List[FuncFacts]:
        """Confident candidates for a call's target: same-class
        methods and module/import-resolved functions resolve
        exactly; bare attribute calls resolve by method name only
        when few (<=3) classes define it and the name is not a
        builtin-container method."""
        f = call.func
        if isinstance(f, ast.Name):
            facts = self.funcs.get((module, "", f.id)) or \
                self.funcs.get((module, cls, f.id))
            if facts is not None:
                return [facts]
            locals_ = self.local_defs.get((module, cls, f.id)) \
                or (self.local_defs.get((module, "", f.id))
                    if cls else None)
            if locals_:
                return list(locals_)
            imp = self.imports.get(module, {}).get(f.id)
            if imp:
                facts = self.funcs.get((imp[0], "", imp[1]))
                if facts is not None:
                    return [facts]
            return []
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and \
                    f.value.id == "self" and cls:
                facts = self.funcs.get((module, cls, f.attr))
                if facts is not None:
                    return [facts]
            if f.attr in _DENY_METHODS:
                return []
            cands = self.methods_by_name.get(f.attr, [])
            if 1 <= len(cands) <= 3:
                return list(cands)
        return []


def get_index(ctx: dict) -> Index:
    idx = ctx.get("index")
    if idx is None:
        idx = ctx["index"] = Index(ctx["modules"])
    return idx


def _in_scope(rel: str, prefixes, files=()) -> bool:
    """Package paths honor the rule's directory scope; fixture
    paths (outside the package) are always in scope."""
    if not rel.startswith("trivy_tpu/"):
        return True
    return rel in files or any(rel.startswith(p) for p in prefixes)


# ---------------------------------------------------------------
# monotonic-clock
# ---------------------------------------------------------------


class MonotonicClockRule(Rule):
    """Flags ``time.time()`` used as an operand of arithmetic
    (BinOp/UnaryOp/AugAssign). Storing wall time as a label is
    fine; adding or subtracting it is never fine — a wall step
    would corrupt the math (the PR-8 invariant, previously a grep
    over ``obs/`` only, now AST-exact and tree-wide)."""

    name = "monotonic-clock"
    summary = ("No time.time() arithmetic anywhere timing math "
               "lives — wall time is labels only (PR-8).")

    @staticmethod
    def _is_wall_call(node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        return (isinstance(f, ast.Attribute) and f.attr == "time"
                and isinstance(f.value, ast.Name)
                and f.value.id == "time")

    def check(self, mi: ModuleInfo,
              ctx: dict) -> Iterable[Finding]:
        for node in ast.walk(mi.tree):
            if not self._is_wall_call(node):
                continue
            cur = node
            flagged = False
            while True:
                parent = mi.parents.get(cur)
                if parent is None or isinstance(parent, ast.stmt):
                    flagged = isinstance(parent, ast.AugAssign)
                    break
                if isinstance(parent, (ast.BinOp, ast.UnaryOp)):
                    flagged = True
                    break
                cur = parent
            if flagged:
                yield Finding(
                    self.name, mi.rel, node.lineno,
                    "time.time() used in arithmetic — durations "
                    "and deadlines must use time.monotonic(); "
                    "wall time may only be stored as a label")


# ---------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------


class LockDisciplineRule(Rule):
    """Builds a static lock-acquisition graph from ``with <lock>``
    scopes. Flags (a) stored callables (``*_fn``/``*_cb``/
    ``*_hook``/``*_gauge``) invoked under a held lock — the PR-4
    gauge class, (b) metric-sink calls under a held lock, (c)
    confidently resolved calls to another module's locking entry
    point under a held lock, and — in ``finalize`` — (d) cycles in
    the combined nesting + call-mediated lock-order graph."""

    name = "lock-discipline"
    summary = ("No gauge/metric callables or other modules' "
               "locking entry points called under a held lock; no "
               "lock-order cycles (PR-4).")

    def check(self, mi: ModuleInfo,
              ctx: dict) -> Iterable[Finding]:
        idx = get_index(ctx)
        edges = ctx.setdefault("lock_edges", [])
        for (mod, cls, _name), facts in idx.funcs.items():
            if mod != mi.name or facts.rel != mi.rel:
                continue
            for held, call in facts.calls:
                if not held:
                    continue
                callee = _call_name(call)
                recv = _receiver_text(call)
                # (a) stored callable: the body is unknowable, so
                # calling it under a lock imposes this lock on
                # every future callback implementation
                if _CALLBACK_ATTR.search(callee):
                    yield Finding(
                        self.name, mi.rel, call.lineno,
                        f"stored callable {_unparse(call.func)}() "
                        f"invoked while holding {self._fmt(held)} "
                        "— call it outside the lock (PR-4 "
                        "gauge-under-lock class)")
                    continue
                # (b) metric sinks take their own lock; calling
                # one under a held lock imposes a cross-object
                # lock order on every metrics implementation
                if self._is_metric_recv(recv):
                    yield Finding(
                        self.name, mi.rel, call.lineno,
                        f"metric call {_unparse(call.func)}() "
                        f"while holding {self._fmt(held)} — move "
                        "the metric update outside the lock")
                    continue
                # (c) resolved locking entry points: unanimous
                # candidates only (a mixed candidate set is an
                # ambiguous name, not evidence)
                cands = idx.resolve_call(mi.name, cls, call)
                if not cands or not all(c.locks for c in cands):
                    continue
                for c in cands:
                    for m in sorted(c.locks):
                        for h in held:
                            if m != h:
                                edges.append(
                                    (h, m, mi.rel, call.lineno))
                cross = sorted({c.qualname for c in cands
                                if c.module != mi.name})
                if cross:
                    yield Finding(
                        self.name, mi.rel, call.lineno,
                        f"call to locking entry point "
                        f"{cross[0]}() while holding "
                        f"{self._fmt(held)} — another module's "
                        "lock is acquired under this one")

    @staticmethod
    def _fmt(held: tuple) -> str:
        return ", ".join(h.split(".", 1)[-1] for h in held)

    @staticmethod
    def _is_metric_recv(recv: str) -> bool:
        if not recv:
            return False
        leaf = recv.split(".")[-1]
        return bool(_METRICS_GLOBAL.match(leaf)) or \
            leaf in ("metrics", "book", "_book")

    def finalize(self, ctx: dict) -> Iterable[Finding]:
        idx = get_index(ctx)
        edges = list(ctx.get("lock_edges", ()))
        edges += list(idx.nest_edges)
        adj: dict = {}
        site: dict = {}
        for a, b, rel, line in edges:
            adj.setdefault(a, set()).add(b)
            site.setdefault((a, b), (rel, line))
        seen_cycles: set = set()
        for start in sorted(adj):
            cyc = self._find_cycle(adj, start)
            if not cyc:
                continue
            canon = self._canonical(cyc)
            if canon in seen_cycles:
                continue
            seen_cycles.add(canon)
            first_hop = cyc[1] if len(cyc) > 1 else cyc[0]
            rel, line = site[(cyc[0], first_hop)]
            path = " -> ".join(
                c.split(".", 1)[-1] for c in cyc + (cyc[0],))
            yield Finding(
                self.name, rel, line,
                f"lock-order cycle: {path} — two threads taking "
                "these locks in opposite orders deadlock")

    @staticmethod
    def _find_cycle(adj: dict, start: str) -> Optional[tuple]:
        stack = [(start, (start,))]
        seen = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == start:
                    return path
                if nxt in seen or nxt in path:
                    continue
                seen.add(nxt)
                stack.append((nxt, path + (nxt,)))
        return None

    @staticmethod
    def _canonical(cyc: tuple) -> tuple:
        i = cyc.index(min(cyc))
        return cyc[i:] + cyc[:i]


# ---------------------------------------------------------------
# hostpool-blocking
# ---------------------------------------------------------------


class HostpoolBlockingRule(Rule):
    """Every callable handed to the host pool (first argument of
    ``map_in_pool`` / ``pool.submit``) is an entry; the rule walks
    the call graph from each entry and flags any reachable
    function that blocks on the pool (``pool.map``, submit-then-
    ``result()``) WITHOUT the thread-name guard
    (``"trivy-hostpool"`` check) that makes the blocking call fall
    back inline on pool threads."""

    name = "hostpool-blocking"
    summary = ("No function reachable from a host-pool task may "
               "block on the pool it runs in (PR-5).")

    def finalize(self, ctx: dict) -> Iterable[Finding]:
        idx = get_index(ctx)
        entries: list = []
        for facts in idx.funcs.values():
            for lineno, arg in facts.pool_entries:
                for target in self._entry_targets(
                        idx, facts.module, facts.cls, arg):
                    entries.append((facts, lineno, target))
        reported: set = set()
        for src, lineno, entry in entries:
            hit = self._reach_blocking(idx, entry)
            if hit is None:
                continue
            blocker, bline, desc = hit
            key = (entry.qualname, blocker.qualname)
            if key in reported:
                continue
            reported.add(key)
            yield Finding(
                self.name, blocker.rel, bline,
                f"{blocker.qualname}() blocks on the host pool "
                f"({desc}) and is reachable from pool task "
                f"{entry.qualname}() (submitted at "
                f"{src.rel}:{lineno}) — a pool task joining its "
                "own pool deadlocks under saturation (PR-5 class)")

    @staticmethod
    def _entry_targets(idx: Index, module: str, cls: str,
                       arg) -> list:
        if isinstance(arg, ast.Lambda):
            out = []
            for sub in ast.walk(arg.body):
                if isinstance(sub, ast.Call):
                    out.extend(idx.resolve_call(module, cls, sub))
            return out
        if isinstance(arg, (ast.Name, ast.Attribute)):
            fake = ast.Call(func=arg, args=[], keywords=[])
            return idx.resolve_call(module, cls, fake)
        return []

    @staticmethod
    def _reach_blocking(idx: Index,
                        entry: FuncFacts) -> Optional[tuple]:
        stack = [entry]
        seen = {entry.qualname}
        while stack:
            facts = stack.pop()
            if facts.pool_blocking and not facts.pool_guard:
                line, desc = facts.pool_blocking[0]
                return facts, line, desc
            for _held, call in facts.calls:
                for c in idx.resolve_call(facts.module, facts.cls,
                                          call):
                    if c.qualname not in seen:
                        seen.add(c.qualname)
                        stack.append(c)
        return None


# ---------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------


class DonationSafetyRule(Rule):
    """Registry of names assigned ``jax.jit(..., donate_argnums=
    ...)`` (tree-wide, imports followed); within the scoped
    modules, any load of a variable AFTER it was passed in a
    donated position — and before any rebinding — is a read of
    freed HBM."""

    name = "donation-safety"
    summary = ("No read of a buffer after it was passed to a "
               "donate_argnums jit call (PR-11).")

    SCOPE = ("trivy_tpu/ops/", "trivy_tpu/detect/")
    FILES = ("trivy_tpu/runtime/ring.py",)

    def check(self, mi: ModuleInfo,
              ctx: dict) -> Iterable[Finding]:
        if not _in_scope(mi.rel, self.SCOPE, self.FILES):
            return
        idx = get_index(ctx)

        def donated_positions(call: ast.Call) -> Optional[tuple]:
            f = call.func
            if not isinstance(f, ast.Name):
                return None
            hit = idx.donated.get((mi.name, f.id))
            if hit is not None:
                return hit
            imp = idx.imports.get(mi.name, {}).get(f.id)
            if imp:
                return idx.donated.get((imp[0], imp[1]))
            return None

        seen: set = set()
        for node in ast.walk(mi.tree):
            if isinstance(node,
                          (ast.FunctionDef, ast.AsyncFunctionDef)):
                for f in self._check_function(
                        mi, node, donated_positions):
                    key = (f.line, f.message)
                    if key not in seen:
                        seen.add(key)
                        yield f

    def _check_function(self, mi: ModuleInfo, fn,
                        donated_positions):
        donations: list = []      # (var, call END lineno, callee)
        stores: dict = {}         # var -> [store linenos]
        loads: dict = {}          # var -> [load linenos]
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call):
                pos = donated_positions(sub)
                if pos:
                    # the donation takes effect when the call
                    # returns: loads on the call's own (possibly
                    # multi-line) argument list are the handoff
                    # itself, not a use-after-donate
                    end = getattr(sub, "end_lineno", sub.lineno) \
                        or sub.lineno
                    for p in pos:
                        if p < len(sub.args) and isinstance(
                                sub.args[p], ast.Name):
                            donations.append(
                                (sub.args[p].id, end,
                                 _call_name(sub)))
            elif isinstance(sub, ast.Name):
                d = stores if isinstance(sub.ctx, ast.Store) \
                    else loads
                d.setdefault(sub.id, []).append(sub.lineno)
        for var, dline, callee in donations:
            # >= dline: `x = donated(x)` rebinds on the call's own
            # line — the donated handle is immediately replaced
            rebind = [ln for ln in stores.get(var, ())
                      if ln >= dline]
            horizon = min(rebind) if rebind else float("inf")
            bad = [ln for ln in loads.get(var, ())
                   if dline < ln <= horizon]
            if bad:
                yield Finding(
                    self.name, mi.rel, min(bad),
                    f"buffer {var!r} read after being donated to "
                    f"{callee}() at line {dline} — donated device "
                    "buffers are invalidated by the callee "
                    "(PR-11 class)")


# ---------------------------------------------------------------
# bare-except-at-seam
# ---------------------------------------------------------------


class BareExceptRule(Rule):
    """Bare ``except:`` anywhere; additionally, at the concurrency
    and IO seams, ``except Exception: pass`` (a silent swallow) —
    the exact failure the fault harness exists to surface must not
    vanish without a log line or a reasoned suppression."""

    name = "bare-except-at-seam"
    summary = ("No bare `except:` anywhere; no silent "
               "`except Exception: pass` at concurrency/IO seams.")

    # trivy_tpu/artifact/ covers the streaming-ingest modules
    # (stream.py, localreg.py, registry.py); trivy_tpu/scan/ joined
    # when the prepare seam became part of the streaming pipeline
    # (docs/performance.md §9)
    SEAMS = ("trivy_tpu/rpc/", "trivy_tpu/watch/",
             "trivy_tpu/sched/", "trivy_tpu/runtime/",
             "trivy_tpu/artifact/", "trivy_tpu/memo/",
             "trivy_tpu/obs/", "trivy_tpu/guard/",
             "trivy_tpu/faults/", "trivy_tpu/parallel/",
             "trivy_tpu/router/", "trivy_tpu/impact/",
             "trivy_tpu/scan/")

    @staticmethod
    def _is_silent(handler: ast.ExceptHandler) -> bool:
        for st in handler.body:
            if isinstance(st, ast.Pass):
                continue
            if isinstance(st, ast.Expr) and isinstance(
                    st.value, ast.Constant):
                continue
            return False
        return True

    @staticmethod
    def _catches_everything(handler: ast.ExceptHandler) -> bool:
        t = handler.type
        names = []
        if isinstance(t, ast.Name):
            names = [t.id]
        elif isinstance(t, ast.Tuple):
            names = [e.id for e in t.elts
                     if isinstance(e, ast.Name)]
        return any(n in ("Exception", "BaseException")
                   for n in names)

    def check(self, mi: ModuleInfo,
              ctx: dict) -> Iterable[Finding]:
        at_seam = _in_scope(mi.rel, self.SEAMS)
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield Finding(
                    self.name, mi.rel, node.lineno,
                    "bare `except:` catches SystemExit/"
                    "KeyboardInterrupt — name the exceptions")
            elif at_seam and self._catches_everything(node) and \
                    self._is_silent(node):
                yield Finding(
                    self.name, mi.rel, node.lineno,
                    "silent `except Exception: pass` at a "
                    "concurrency/IO seam — log, narrow, or "
                    "suppress with the reason the swallow is safe")


# ---------------------------------------------------------------
# unbounded-label-cardinality
# ---------------------------------------------------------------


class LabelCardinalityRule(Rule):
    """In metrics-flavored classes (name matches Metrics/Book/
    Histogram/Recorder, or the class exports a snapshot/raw), a
    parameter-keyed INSERT into a dict (plain subscript assign or
    ``setdefault``) is an open key domain → an unbounded prom
    label family — unless the class shows a cap/fold (a ``len()``
    comparison or an overflow constant like ``"<overflow>"``/
    ``"other"``/``"anon"``). ``d[k] += n`` is exempt: it raises on
    unknown keys, so a literal-initialized dict stays capped by
    construction."""

    name = "unbounded-label-cardinality"
    summary = ("Open-keyed metric/label dicts need a cardinality "
               "cap or overflow fold (PR-7/PR-8); tenant-keyed "
               "inserts need the fold in the same function.")

    # tenant labels are held to a STRICTER, fail-closed standard
    # (the cost plane ships tenant-keyed invoice books): an insert
    # keyed by a tenant-named parameter must show the top-K +
    # "other" fold evidence in the SAME function - cap evidence
    # elsewhere in the class does not count, because a refactor
    # that moves the capped path away silently unbounds the label
    _TENANT_PARAM = re.compile(r"^tenant(_id|_name)?$")

    def check(self, mi: ModuleInfo,
              ctx: dict) -> Iterable[Finding]:
        for node in mi.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            if not self._metricsy(node):
                continue
            class_cap = self._has_cap(node)
            for fn in node.body:
                if not isinstance(
                        fn,
                        (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                params = {a.arg for a in fn.args.args
                          if a.arg != "self"}
                fn_cap = self._fn_has_cap(fn)
                for site, key in self._open_inserts(fn, params):
                    if self._TENANT_PARAM.match(key):
                        if not fn_cap:
                            yield Finding(
                                self.name, mi.rel, site,
                                f"{node.name} books a tenant-"
                                "labeled series with no top-K + "
                                "\"other\" fold in this function "
                                "— tenant cardinality checks "
                                "fail closed: the fold must be "
                                "visible at the insert site "
                                "(PR-7/PR-8, cost-plane rule)")
                    elif not class_cap:
                        yield Finding(
                            self.name, mi.rel, site,
                            f"{node.name} inserts parameter-keyed "
                            "entries into a label/counter dict "
                            "with no cardinality cap or overflow "
                            "fold — an open key domain becomes an "
                            "unbounded prom label set (PR-7/PR-8 "
                            "class)")

    @staticmethod
    def _metricsy(node: ast.ClassDef) -> bool:
        if _METRICSY_CLASS.search(node.name):
            return True
        return any(isinstance(f, ast.FunctionDef) and f.name in
                   ("snapshot", "raw", "hist_snapshot")
                   for f in node.body)

    @staticmethod
    def _has_cap(node) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and \
                    isinstance(sub.value, str) and \
                    sub.value in _CAP_CONSTANTS:
                return True
            if isinstance(sub, ast.Compare):
                for side in [sub.left] + list(sub.comparators):
                    if isinstance(side, ast.Call) and \
                            _call_name(side) == "len":
                        return True
        return False

    @classmethod
    def _fn_has_cap(cls, fn) -> bool:
        # the same cap/fold evidence, scoped to ONE function — the
        # fail-closed bar a tenant-keyed insert must clear
        return cls._has_cap(fn)

    @staticmethod
    def _open_inserts(fn, params: set):
        """Yields ``(lineno, key_param_name)`` per open insert."""
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.slice, ast.Name) and \
                            t.slice.id in params:
                        yield sub.lineno, t.slice.id
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "setdefault" and sub.args \
                    and isinstance(sub.args[0], ast.Name) and \
                    sub.args[0].id in params:
                yield sub.lineno, sub.args[0].id


def default_rules() -> list:
    return [
        MonotonicClockRule(),
        LockDisciplineRule(),
        HostpoolBlockingRule(),
        DonationSafetyRule(),
        BareExceptRule(),
        LabelCardinalityRule(),
    ]
