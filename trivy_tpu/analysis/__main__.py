"""``python -m trivy_tpu.analysis`` — run the repo-invariant lint
over the tree; exit 1 on unsuppressed findings.

* default root: the ``trivy_tpu`` package (the whole product tree);
  positional paths narrow the sweep to files or directories;
* ``--json`` emits the stable-sorted machine report (byte-stable
  across runs over the same tree — CI artifact diffs show exactly
  the new findings);
* ``--rules a,b`` restricts to a rule subset; ``--list-rules``
  prints the catalog.
"""

from __future__ import annotations

import argparse
import os
import sys

from .engine import Engine, analyze_tree, package_root
from .rules import default_rules


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m trivy_tpu.analysis",
        description="Repo-invariant static analysis "
                    "(docs/static-analysis.md).")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze "
                         "(default: the trivy_tpu package)")
    ap.add_argument("--json", action="store_true",
                    help="stable-sorted JSON report on stdout")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    rules = default_rules()
    if args.list_rules:
        for r in sorted(rules, key=lambda r: r.name):
            print(f"{r.name}: {r.summary}")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",")
                  if r.strip()}
        known = {r.name for r in rules}
        bad = wanted - known
        if bad:
            print("unknown rule(s): " + ", ".join(sorted(bad)),
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]
    engine = Engine(rules)

    if args.paths:
        base = package_root()
        files: list = []
        for p in args.paths:
            p = os.path.abspath(p)
            if os.path.isdir(p):
                files.extend(engine.tree_paths(p))
            else:
                files.append(p)
        modules = [engine.load_module(f, base)
                   for f in sorted(set(files))]
        report = engine.analyze(modules)
    else:
        report = analyze_tree(engine=engine)

    if args.json:
        print(report.to_json())
    else:
        print(report.text())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
