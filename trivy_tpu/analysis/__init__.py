"""Repo-invariant static analysis (docs/static-analysis.md).

Two complementary halves:

* :mod:`analysis.engine` + :mod:`analysis.rules` — an AST lint
  engine whose rules encode this repo's own concurrency/donation/
  clock invariant history (the PR-4 gauge-under-lock self-deadlock,
  the PR-5 hostpool self-join, the PR-8 monotonic-clock discipline,
  the PR-11 donated-buffer reuse rules, the PR-7/PR-8 label-
  cardinality folds). ``python -m trivy_tpu.analysis`` runs every
  rule over the tree and exits 1 on unsuppressed findings;
  ``pytest -m lint`` wires the same sweep into tier-1.
* :mod:`analysis.witness` — a dynamic complement: an opt-in
  instrumented-lock wrapper (``TRIVY_TPU_LOCK_WITNESS=1``) that
  records the process-wide lock-acquisition order graph and raises
  on a cycle or on a blocking pool-join from a pool thread, wired
  into the seeded race suites so the historical deadlocks cannot
  silently return.
"""

from .engine import (  # noqa: F401
    Engine,
    Finding,
    Suppression,
    analyze_source,
    analyze_tree,
    default_engine,
    parse_suppressions,
)
from .witness import (  # noqa: F401
    LockOrderViolation,
    LockWitness,
    OrderGraph,
    PoolSelfJoinError,
    install_witness,
    uninstall_witness,
)
