"""AST lint engine: rule registry, typed findings, inline
suppressions (docs/static-analysis.md).

Rules are small objects with three hooks — ``collect`` (build
cross-module facts), ``check`` (per-module findings) and
``finalize`` (whole-tree findings, e.g. lock-order cycles) — run by
one :class:`Engine` over parsed :class:`ModuleInfo` records. Every
finding is typed (rule id, ``path:line``, message) and the report is
stable-sorted so ``--json`` diffs are reviewable.

Suppression grammar (FAILS closed):

    # lint: disable=<rule>[,<rule2>...] -- <reason>

* a suppression without a reason is itself a finding
  (``bad-suppression``) and suppresses nothing;
* a suppression naming an unknown rule is ``bad-suppression``;
* a suppression that matched no finding is ``unused-suppression`` —
  stale suppressions rot into lies, so they fail the run too.

The comment rides the flagged line or the line directly above it.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, List, Optional

# one comment per line; rule ids are kebab-case
SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Za-z0-9_,\s-]+?)"
    r"(?:\s+--\s+(.+?))?\s*$")

# meta-rule ids the engine itself emits; not suppressible
BAD_SUPPRESSION = "bad-suppression"
UNUSED_SUPPRESSION = "unused-suppression"


@dataclass
class Suppression:
    """One parsed ``# lint: disable=...`` comment."""

    rules: tuple
    reason: str
    line: int
    used: set = field(default_factory=set)

    @property
    def valid(self) -> bool:
        return bool(self.reason.strip())


@dataclass
class Finding:
    """One typed lint finding anchored at ``path:line``."""

    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""

    @property
    def sort_key(self) -> tuple:
        return (self.path, self.line, self.rule, self.message)

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "path": self.path,
             "line": self.line, "message": self.message}
        if self.suppressed:
            d["suppressed"] = True
            d["reason"] = self.reason
        return d

    def __str__(self) -> str:
        tag = " [suppressed]" if self.suppressed else ""
        return (f"{self.path}:{self.line}: {self.rule}: "
                f"{self.message}{tag}")


def parse_suppressions(lines) -> dict:
    """``{line_number: Suppression}`` over raw source lines.

    Malformed comments (no reason, empty rule list) still parse —
    with ``reason == ""`` — so the engine can fail them loudly
    instead of silently honoring or ignoring them."""
    out: dict = {}
    for i, text in enumerate(lines, 1):
        m = SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",")
                      if r.strip())
        out[i] = Suppression(rules=rules,
                             reason=(m.group(2) or "").strip(),
                             line=i)
    return out


class ModuleInfo:
    """One parsed source file: path, dotted name, lines, AST,
    suppressions, and a lazily built AST parent map."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        # trivy_tpu/obs/prom.py -> trivy_tpu.obs.prom
        base = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        self.name = base.replace("/", ".")
        # a package __init__'s dotted name IS the package — relative
        # imports resolve against it, not against a phantom leaf
        self.is_package = self.name.endswith(".__init__") or \
            self.name == "__init__"
        if self.name.endswith(".__init__"):
            self.name = self.name[:-len(".__init__")]
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.suppressions = parse_suppressions(self.lines)
        self._parents: Optional[dict] = None

    @property
    def parents(self) -> dict:
        """child AST node -> parent node (built on first use)."""
        if self._parents is None:
            p: dict = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    p[child] = node
            self._parents = p
        return self._parents


class Rule:
    """Base rule: subclasses set ``name``/``summary`` and override
    any of the three hooks."""

    name = ""
    summary = ""

    def collect(self, mi: ModuleInfo, ctx: dict) -> None:
        """First pass over every module: build cross-module facts
        into ``ctx`` before any ``check`` runs."""

    def check(self, mi: ModuleInfo,
              ctx: dict) -> Iterable[Finding]:
        return ()

    def finalize(self, ctx: dict) -> Iterable[Finding]:
        """After every module checked: whole-tree findings (the
        lock-order cycle scan lives here)."""
        return ()


class Report:
    """Stable-sorted analysis result."""

    def __init__(self, findings: List[Finding],
                 suppressed: List[Finding], rules: List[str],
                 files: int):
        self.findings = sorted(findings, key=lambda f: f.sort_key)
        self.suppressed = sorted(suppressed,
                                 key=lambda f: f.sort_key)
        self.rules = sorted(rules)
        self.files = files

    @property
    def ok(self) -> bool:
        return not self.findings

    def counts(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "files": self.files,
            "rules": self.rules,
            "counts": self.counts(),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
        }

    def to_json(self) -> str:
        # sort_keys + sorted findings: byte-stable across runs, so
        # a CI artifact diff shows exactly the new findings
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    def text(self) -> str:
        lines = [str(f) for f in self.findings]
        n = len(self.findings)
        lines.append(
            f"{n} finding{'s' if n != 1 else ''} "
            f"({len(self.suppressed)} suppressed) across "
            f"{self.files} files")
        return "\n".join(lines)


class Engine:
    """Runs a rule set over a module set and applies suppressions."""

    def __init__(self, rules: List[Rule]):
        names = [r.name for r in rules]
        assert len(names) == len(set(names)), "duplicate rule names"
        self.rules = rules
        self.rule_names = set(names)

    # --- module loading ---

    @staticmethod
    def load_module(path: str, root: str) -> ModuleInfo:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        rel = os.path.relpath(path, root)
        return ModuleInfo(path, rel, source)

    @staticmethod
    def tree_paths(root: str) -> list:
        """Every ``*.py`` under ``root``, sorted, skipping caches."""
        out = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
        return out

    # --- analysis ---

    def analyze(self, modules: List[ModuleInfo]) -> Report:
        ctx: dict = {"modules": modules}
        for rule in self.rules:
            for mi in modules:
                rule.collect(mi, ctx)
        raw: List[Finding] = []
        for rule in self.rules:
            for mi in modules:
                for f in rule.check(mi, ctx):
                    raw.append(f)
            for f in rule.finalize(ctx):
                raw.append(f)
        return self._apply_suppressions(modules, raw)

    def _apply_suppressions(self, modules: List[ModuleInfo],
                            raw: List[Finding]) -> Report:
        by_rel = {mi.rel: mi for mi in modules}
        findings: List[Finding] = []
        suppressed: List[Finding] = []
        for f in raw:
            sup = self._match_suppression(by_rel.get(f.path), f)
            if sup is not None:
                sup.used.add(f.rule)
                f.suppressed = True
                f.reason = sup.reason
                suppressed.append(f)
            else:
                findings.append(f)
        # the suppression grammar fails closed: reason-less or
        # unknown-rule comments and stale (unused) suppressions are
        # findings themselves
        for mi in modules:
            for sup in mi.suppressions.values():
                unknown = [r for r in sup.rules
                           if r not in self.rule_names]
                if not sup.valid:
                    findings.append(Finding(
                        BAD_SUPPRESSION, mi.rel, sup.line,
                        "suppression without a reason (grammar: "
                        "# lint: disable=<rule> -- <reason>)"))
                elif not sup.rules:
                    findings.append(Finding(
                        BAD_SUPPRESSION, mi.rel, sup.line,
                        "suppression with an empty rule list"))
                elif unknown:
                    findings.append(Finding(
                        BAD_SUPPRESSION, mi.rel, sup.line,
                        "suppression names unknown rule(s): "
                        + ", ".join(sorted(unknown))))
                else:
                    stale = [r for r in sup.rules
                             if r not in sup.used]
                    if stale:
                        findings.append(Finding(
                            UNUSED_SUPPRESSION, mi.rel, sup.line,
                            "suppression matched no finding for: "
                            + ", ".join(sorted(stale))))
        return Report(findings, suppressed,
                      list(self.rule_names), len(modules))

    # how far a suppression comment block may sit above its finding
    _BLOCK_MAX = 8

    @classmethod
    def _match_suppression(cls, mi: Optional[ModuleInfo],
                           f: Finding) -> Optional[Suppression]:
        """Same-line suppression, or one anywhere in the contiguous
        comment block ending directly above the finding (multi-line
        reasons wrap naturally in a 72-column tree). A trailing
        comment on a previous STATEMENT never leaks downward — only
        comment-only lines join the block."""
        if mi is None:
            return None
        sup = mi.suppressions.get(f.line)
        if sup is not None and sup.valid and f.rule in sup.rules:
            return sup
        line = f.line - 1
        steps = 0
        while line >= 1 and steps < cls._BLOCK_MAX:
            text = mi.lines[line - 1].lstrip()
            if not text.startswith("#"):
                break
            sup = mi.suppressions.get(line)
            if sup is not None and sup.valid \
                    and f.rule in sup.rules:
                return sup
            line -= 1
            steps += 1
        return None


# --- front doors ---


def default_engine() -> Engine:
    from .rules import default_rules
    return Engine(default_rules())


def package_root() -> str:
    """The repo root (parent of the ``trivy_tpu`` package)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def analyze_tree(root: str = "",
                 engine: Optional[Engine] = None) -> Report:
    """Analyze every ``*.py`` under ``root`` (default: the
    ``trivy_tpu`` package) with the default rule set."""
    eng = engine or default_engine()
    base = package_root()
    root = root or os.path.join(base, "trivy_tpu")
    modules = [eng.load_module(p, base)
               for p in eng.tree_paths(root)]
    return eng.analyze(modules)


def analyze_source(source: str, rel: str = "fixture.py",
                   engine: Optional[Engine] = None,
                   extra: Optional[dict] = None) -> Report:
    """Analyze in-memory source (rule unit fixtures). ``extra``
    maps additional ``rel`` paths to sources analyzed together —
    cross-module rules (hostpool reachability, lock graphs) see the
    whole set."""
    eng = engine or default_engine()
    modules = [ModuleInfo(rel, rel, source)]
    for other_rel, other_src in (extra or {}).items():
        modules.append(ModuleInfo(other_rel, other_rel, other_src))
    return eng.analyze(modules)
