"""Runtime lock-order witness (docs/static-analysis.md "Witness").

The static ``lock-discipline`` rule proves what it can see; the
witness catches what it can't: an opt-in instrumented-lock wrapper
that records the *process-wide* lock-acquisition order graph, keyed
by lock **creation site** (``module:line`` — the lockdep "lock
class" idea: every ``SchedMetrics._lock`` instance is one node), and

* raises :class:`LockOrderViolation` the moment two sites are ever
  acquired in opposite orders (the PR-4 deadlock class, caught even
  when the interleaving that would actually deadlock never fires);
* raises :class:`PoolSelfJoinError` on a blocking join of a host-
  pool future from a host-pool thread (the PR-5 class).

Enable with ``TRIVY_TPU_LOCK_WITNESS=1`` (the test conftest honors
it for whole runs) or programmatically via :func:`install_witness`.
The seeded race suites (test_sched / test_tenant / test_async_rt
storms) always run under an installed witness, so the historical
deadlocks cannot silently return.

Scope: only locks *constructed* by ``trivy_tpu`` modules while the
witness is installed are wrapped; the ~49Hz profiler tick path
(``trivy_tpu.obs.profiler``) is exclude-listed by module — the
sampler's cadence must not pay witness bookkeeping (test-proven).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import List, Optional

_real_Lock = threading.Lock
_real_RLock = threading.RLock
_real_Condition = threading.Condition


class LockOrderViolation(RuntimeError):
    """Two lock sites acquired in opposite orders somewhere in the
    process — a deadlock waiting for the right interleaving."""

    def __init__(self, cycle: List[str]):
        self.cycle = list(cycle)
        super().__init__(
            "lock-order cycle: " + " -> ".join(
                self.cycle + self.cycle[:1]))


class PoolSelfJoinError(RuntimeError):
    """A host-pool thread blocked on a future of its own pool."""


class OrderGraph:
    """Pure directed graph with incremental cycle detection —
    property-tested on seeded random acquisition schedules. NOT
    thread-safe; the witness serializes access."""

    def __init__(self):
        self.adj: dict = {}
        self.edge_set: set = set()

    def add_edge(self, a: str, b: str) -> Optional[List[str]]:
        """Record ``a`` held while ``b`` acquired. Returns the
        cycle path (``[a, b, ..., back-to-a]`` exclusive) if this
        edge closes one, else None. A cycle-closing edge is NOT
        recorded — recording it would make the dedup fast path
        swallow every later recurrence of the same inversion, and
        a violation that raised once into a broad except seam
        must keep raising."""
        if a == b:
            return None          # per-instance self-nesting is the
            # immediate-deadlock case Python raises on its own;
            # same-SITE different-instance nesting is legal
        if (a, b) in self.edge_set:
            return None
        # would b -> ... -> a exist already?
        cycle = self._path(b, a)
        if cycle is not None:
            return [a] + cycle
        self.edge_set.add((a, b))
        self.adj.setdefault(a, set()).add(b)
        return None

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        if src == dst:
            return [src]
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self.adj.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def edges(self) -> list:
        return sorted(self.edge_set)


class LockWitness:
    """The process-wide recorder: per-thread held stacks, the site
    graph, and the acquisition counters the bench overhead gate
    multiplies out."""

    EXCLUDE_MODULES = ("trivy_tpu.obs.profiler",)
    PREFIXES = ("trivy_tpu",)

    def __init__(self, extra_prefixes: tuple = ()):
        self.graph = OrderGraph()
        self.prefixes = self.PREFIXES + tuple(extra_prefixes)
        # raw lock: the witness's own bookkeeping must not recurse
        # into the patched factories
        self._glock = _real_Lock()
        self._tls = threading.local()
        # plain (GIL-approximate) counters: the acquire fast path
        # must not serialize every wrapped lock in the process on
        # one global lock — under-counting a storm by a few is
        # fine, a 20% contention tax is not (bench-gated <2%)
        self.acquisitions = 0
        self.nested = 0
        self.wrapped = 0
        self.pool_joins_checked = 0
        self.violations: list = []

    # --- policy ---

    def should_wrap(self, module: str) -> bool:
        if not module:
            return False
        if any(module.startswith(e) for e in self.EXCLUDE_MODULES):
            return False
        return any(module == p or module.startswith(p + ".")
                   for p in self.prefixes)

    # --- hooks (called by _WitnessLock) ---

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def on_acquire(self, site: str) -> None:
        held = self._stack()
        self.acquisitions += 1
        if held:
            self.nested += 1
            # fast path: every (held, site) edge already recorded —
            # two unlocked set lookups (GIL-safe; a stale read just
            # falls through to the locked recheck below, and
            # add_edge is idempotent)
            es = self.graph.edge_set
            if any(h != site and (h, site) not in es
                   for h in held):
                with self._glock:
                    for h in held:
                        cycle = self.graph.add_edge(h, site)
                        if cycle is not None:
                            self.violations.append(cycle)
                            held_copy = list(held)
                            raise LockOrderViolation(cycle) \
                                from _held_context(held_copy,
                                                   site)
        held.append(site)

    def on_release(self, site: str) -> None:
        held = self._stack()
        # release order may differ from acquisition order: drop the
        # LAST occurrence
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                return

    def stats(self) -> dict:
        with self._glock:
            return {
                "acquisitions": self.acquisitions,
                "nested_acquisitions": self.nested,
                "wrapped_locks": self.wrapped,
                "edges": len(self.graph.edge_set),
                "violations": len(self.violations),
                "pool_joins_checked": self.pool_joins_checked,
            }


def _held_context(held: list, site: str) -> RuntimeError:
    return RuntimeError(
        f"while holding {held} and acquiring {site}")


class _WitnessLock:
    """Wraps a real Lock/RLock; reentrancy-aware (edges recorded
    on the first acquisition only). Delegates the Condition
    protocol (``_release_save``/``_acquire_restore``/``_is_owned``)
    so ``threading.Condition`` accepts it."""

    def __init__(self, inner, site: str, witness: LockWitness):
        self._inner = inner
        self._site = site
        self._witness = witness
        self._local = threading.local()

    def _depth(self) -> int:
        return getattr(self._local, "n", 0)

    def _live(self) -> bool:
        # a lock wrapped during one witness session must go inert
        # once that witness uninstalls — it would otherwise keep
        # booking (and raising) forever after the test that
        # installed it finished
        return _ACTIVE is self._witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            # hot path: threading.local's per-thread __dict__ is
            # one lookup instead of getattr+setattr descriptor
            # round-trips (this wrapper rides every lock in the
            # witnessed process — bench-gated <2% attributed)
            d = self._local.__dict__
            n = d.get("n", 0)
            d["n"] = n + 1
            if n == 0 and _ACTIVE is self._witness:
                try:
                    self._witness.on_acquire(self._site)
                except BaseException:
                    d["n"] = n
                    self._inner.release()
                    raise
        return ok

    def release(self) -> None:
        d = self._local.__dict__
        n = d.get("n", 1)
        d["n"] = n - 1 if n > 0 else 0
        if n == 1 and _ACTIVE is self._witness:
            self._witness.on_release(self._site)
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # --- Condition protocol (RLock inner) ---

    def _release_save(self):
        if self._live():
            self._witness.on_release(self._site)
        n = self._depth()
        self._local.n = 0
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), n)
        self._inner.release()
        return (None, n)

    def _acquire_restore(self, state) -> None:
        inner_state, n = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        self._local.n = n
        if self._live():
            self._witness.on_acquire(self._site)

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return self._depth() > 0

    def __repr__(self) -> str:
        return f"<WitnessLock {self._site} of {self._inner!r}>"


_ACTIVE: Optional[LockWitness] = None
_PATCHED = False


def _caller_module(depth: int = 2) -> str:
    try:
        frame = sys._getframe(depth)
    except ValueError:  # pragma: no cover
        return ""
    return frame.f_globals.get("__name__", "") or ""


def _site(depth: int = 2) -> str:
    try:
        frame = sys._getframe(depth)
    except ValueError:  # pragma: no cover
        return "<unknown>"
    mod = frame.f_globals.get("__name__", "") or "<unknown>"
    return f"{mod}:{frame.f_lineno}"


def _make_lock():
    w = _ACTIVE
    if w is None or not w.should_wrap(_caller_module()):
        return _real_Lock()
    with w._glock:
        w.wrapped += 1
    return _WitnessLock(_real_Lock(), _site(), w)


def _make_rlock():
    w = _ACTIVE
    if w is None or not w.should_wrap(_caller_module()):
        return _real_RLock()
    with w._glock:
        w.wrapped += 1
    return _WitnessLock(_real_RLock(), _site(), w)


def _make_condition(lock=None):
    w = _ACTIVE
    if lock is None and w is not None and \
            w.should_wrap(_caller_module()):
        with w._glock:
            w.wrapped += 1
        lock = _WitnessLock(_real_RLock(), _site(), w)
    return _real_Condition(lock)


def _tag_pool(pool) -> None:
    """Mark every future the host pool hands out, so the patched
    ``Future.result`` can recognize a pool-thread self-join."""
    if pool is None or getattr(pool, "_witness_tagged", False):
        return
    orig = pool.submit

    def submit(fn, *args, **kwargs):
        fut = orig(fn, *args, **kwargs)
        fut._trivy_tpu_hostpool = True
        return fut

    pool.submit = submit
    pool._witness_tagged = True


_real_future_result = None


def _patched_result(self, timeout=None):
    w = _ACTIVE
    if w is not None and \
            getattr(self, "_trivy_tpu_hostpool", False) and \
            threading.current_thread().name.startswith(
                "trivy-hostpool"):
        with w._glock:
            w.pool_joins_checked += 1
        raise PoolSelfJoinError(
            "host-pool thread blocked on a future of its own "
            "pool — under saturation every worker waits on a "
            "worker and the pool deadlocks (PR-5 class)")
    return _real_future_result(self, timeout)


def install_witness(extra_prefixes: tuple = ()) -> LockWitness:
    """Activate the witness: patch the ``threading`` lock
    factories (caller-module filtered) and the host-pool future
    join. Returns the active witness; idempotent."""
    global _ACTIVE, _PATCHED, _real_future_result
    if _ACTIVE is not None:
        return _ACTIVE
    _ACTIVE = LockWitness(extra_prefixes=extra_prefixes)
    if not _PATCHED:
        threading.Lock = _make_lock
        threading.RLock = _make_rlock
        threading.Condition = _make_condition
        import concurrent.futures as cf
        _real_future_result = cf.Future.result
        cf.Future.result = _patched_result
        _PATCHED = True
    # tag the host pool (existing and future instances)
    try:
        from ..runtime import hostpool
        _tag_pool(hostpool._POOL)
        if not getattr(hostpool, "_witness_hooked", False):
            orig_get = hostpool.get_host_pool

            def get_host_pool():
                pool = orig_get()
                if _ACTIVE is not None:
                    _tag_pool(pool)
                return pool

            hostpool.get_host_pool = get_host_pool
            hostpool._witness_hooked = True
    except Exception:  # pragma: no cover — hostpool unavailable
        pass
    return _ACTIVE


def uninstall_witness() -> None:
    """Deactivate and restore the real factories. Locks already
    wrapped keep their wrappers but go INERT — every hook checks
    that the captured witness is still the active one, so a lock
    created during one test's witness session costs nothing and
    raises nothing afterward."""
    global _ACTIVE, _PATCHED, _real_future_result
    _ACTIVE = None
    if _PATCHED:
        threading.Lock = _real_Lock
        threading.RLock = _real_RLock
        threading.Condition = _real_Condition
        import concurrent.futures as cf
        if _real_future_result is not None:
            cf.Future.result = _real_future_result
        _PATCHED = False


def active_witness() -> Optional[LockWitness]:
    return _ACTIVE


def maybe_install_from_env() -> Optional[LockWitness]:
    """Honor ``TRIVY_TPU_LOCK_WITNESS=1`` (the opt-in contract)."""
    if os.environ.get("TRIVY_TPU_LOCK_WITNESS", "") == "1":
        return install_witness()
    return None
