"""TPU kernels (JAX/XLA, with Pallas variants where they win).

* ``keywords``  — literal/anchor blockmask sieve (secret detection;
  Pallas variant in ``keywords_pallas``).
* ``runs``      — mandatory class-run gate (secret detection).
* ``intervals`` — vectorized version-interval membership
  (vulnerability detection).
"""

from . import keywords, runs, intervals  # noqa: F401

__all__ = ["keywords", "runs", "intervals"]
