"""TPU kernels (JAX/XLA, with Pallas variants where they win).

* ``dfa``    — batched multi-pattern DFA scanning (secret detection).
* ``vercmp`` — vectorized version-constraint matching (vulnerability
  detection).
"""

from . import dfa  # noqa: F401

__all__ = ["dfa"]
