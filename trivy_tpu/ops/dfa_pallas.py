"""Pallas TPU kernel for the banded multi-pattern DFA sieve.

Same banded-table evaluation as ops/dfa.dfa_masks_impl, but one HBM
pass per segment tile: the tile loads into VMEM once, the sliding
lowered window words build in registers, then every pattern — the
literal groups AND the chain patterns' band (membership + erosion +
static rolls) — evaluates against the resident tile. The XLA scan
formulation re-reads the window-word arrays from HBM per code chunk;
here HBM traffic is 1 × L×B bytes regardless of pattern count
(the keywords_pallas.py lesson, extended to the full engine).

Layout:
  grid            = (B // TILE_B,)
  segments block  = [TILE_B, L] uint8 in VMEM
  band arrays     = 4 × [c, Kg128] uint32 per literal group,
                    scalar-prefetched to SMEM
  chain structure = STATIC (unrolled into the kernel — the chain
                    band is part of the compiled program, uploaded
                    implicitly with it; the literal band rides HBM)
  outputs         = per literal group [TILE_B, Kg128] uint32 and one
                    [TILE_B, Kc128] uint32 chain block — 128-code
                    groups accumulate in registers via lane-select
                    (dynamic lane stores must be 128-aligned), one
                    store per group

Out bit j of word [b, k] = pattern k hit inside block j of segment b
(N_BLOCKS = 16 blocks; start positions for literals, end positions
for chains — ops/dfa.py documents why decode doesn't care for
chains)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .keywords import CODE_CHUNK, MAX_CODE_LEN, N_BLOCKS, pack_code
from .dfa import chain_len

TILE_B = 32     # smaller than keywords_pallas: up to 4 shifted
                # word-pair levels live in VMEM alongside the tile,
                # and shard_map blocks can be as small as 32 rows


def _pad128(a, fill_masks: bool):
    """Pad the code axis of a [c, K] band array to a 128 multiple;
    pad columns carry match-nothing codes (0 under a full mask)."""
    c, K = a.shape
    Kp = -(-K // 128) * 128
    if Kp == K:
        return a
    pad = jnp.zeros((c, Kp - K), jnp.uint32)
    if fill_masks:
        first = jnp.full((1, Kp - K), 0xFFFFFFFF, jnp.uint32)
        pad = jnp.concatenate([first, pad[1:]], axis=0) \
            if c > 1 else first
    return jnp.concatenate([a, pad], axis=1)


def _make_kernel(table, L: int):
    groups = table.groups
    chains = table.chains
    nch = table.max_chunks
    blk = L // N_BLOCKS
    kc128 = max(1, -(-max(1, len(chains)) // 128)) * 128

    def kernel(*refs):
        g_refs = refs[:4 * len(groups)]
        seg_ref = refs[4 * len(groups)]
        out_refs = refs[4 * len(groups) + 1:]

        x = seg_ref[:].astype(jnp.int32)                 # [bT, L]
        bT = x.shape[0]
        col = jax.lax.broadcasted_iota(jnp.int32, (bT, L), 1)
        xl = jnp.where((x >= 65) & (x <= 90), x + 32, x)

        def shl(a, k):
            if k == 0:
                return a
            r = pltpu.roll(a, L - k, 1)      # left-shift by k
            return jnp.where(col < L - k, r, 0)

        def shr(a, k):
            if k == 0:
                return a
            r = pltpu.roll(a, k, 1)          # right-shift by k
            return jnp.where(col >= k, r, 0)

        xs = [shl(xl, i) for i in range(8)]
        xu = [v.astype(jnp.uint32) for v in xs]
        lo0 = xu[0] | (xu[1] << 8) | (xu[2] << 16) | (xu[3] << 24)
        hi0 = xu[4] | (xu[5] << 8) | (xu[6] << 16) | (xu[7] << 24)
        lo_sh = [lo0]
        hi_sh = [hi0]
        for j in range(1, nch):
            lo_sh.append(
                shl(lo0.astype(jnp.int32), 8 * j).astype(jnp.uint32))
            hi_sh.append(
                shl(hi0.astype(jnp.int32), 8 * j).astype(jnp.uint32))

        # block reduction rides the MXU: [bT, L] @ [L, 16] hit
        # counts are exact in f32 (≤ blk ones per block)
        pos_blk = jax.lax.broadcasted_iota(
            jnp.int32, (L, N_BLOCKS), 0) // blk
        blk_id = jax.lax.broadcasted_iota(
            jnp.int32, (L, N_BLOCKS), 1)
        ind = (pos_blk == blk_id).astype(jnp.float32)
        bit_val = (jnp.int32(1) << jax.lax.broadcasted_iota(
            jnp.int32, (bT, N_BLOCKS), 1))
        lane = jax.lax.broadcasted_iota(jnp.int32, (bT, 128), 1)

        def blockmask_col(hit):
            counts = jnp.dot(hit.astype(jnp.float32), ind,
                             preferred_element_type=jnp.float32)
            return jnp.sum(jnp.where(counts > 0, bit_val, 0),
                           axis=1, keepdims=True)     # [bT, 1]

        # --- literal groups (band arrays arrive FLATTENED 1-D:
        # chunk-major, [jc * Kg128 + k] — 1-D dynamic SMEM reads
        # are the pattern keywords_pallas.py established) ---
        for gi, g in enumerate(groups):
            lo_r, hi_r, lom_r, him_r = g_refs[4 * gi:4 * gi + 4]
            Kg128 = out_refs[gi].shape[1]
            for b128 in range(Kg128 // 128):
                def body(j, acc, b128=b128, g=g, Kg128=Kg128,
                         lo_r=lo_r, hi_r=hi_r, lom_r=lom_r,
                         him_r=him_r):
                    k = b128 * 128 + j
                    hit = None
                    for jc in range(g.chunks):
                        f = jc * Kg128 + k
                        h = ((lo_sh[jc] & lom_r[f]) == lo_r[f]) \
                            & ((hi_sh[jc] & him_r[f]) == hi_r[f])
                        hit = h if hit is None else hit & h
                    return jnp.where(lane == j,
                                     blockmask_col(hit), acc)

                acc = jax.lax.fori_loop(
                    0, 128, body, jnp.zeros((bT, 128), jnp.int32))
                out_refs[gi][:, b128 * 128:(b128 + 1) * 128] = \
                    acc.astype(jnp.uint32)

        # --- chain patterns (static unroll — the chain band is part
        # of the compiled program) ---
        if out_refs[len(groups):]:
            memb: dict = {}
            erod: dict = {}

            def membership(ranges):
                m = memb.get(ranges)
                if m is None:
                    m = jnp.zeros((bT, L), jnp.int32)
                    for a, b in ranges:
                        m = m | ((x == a).astype(jnp.int32)
                                 if a == b else
                                 ((x >= a) & (x <= b))
                                 .astype(jnp.int32))
                    memb[ranges] = m
                return m

            def erode(ranges, n):
                e = erod.get((ranges, n))
                if e is None:
                    e = membership(ranges)
                    span = 1
                    while span < n:
                        step = min(span, n - span)
                        e = e & shl(e, step)
                        span += step
                    erod[(ranges, n)] = e
                return e

            def lit_pred(data):
                p = None
                for j in range(-(-len(data) // MAX_CODE_LEN)):
                    part = data[j * MAX_CODE_LEN:
                                (j + 1) * MAX_CODE_LEN]
                    klo, khi, mlo, mhi = (jnp.uint32(v)
                                          for v in pack_code(part))
                    cmp = ((lo_sh[j] & mlo) == klo) \
                        & ((hi_sh[j] & mhi) == khi)
                    p = cmp if p is None else p & cmp
                return p.astype(jnp.int32)

            chain_ref = out_refs[len(groups)]
            for b128 in range(kc128 // 128):
                acc = jnp.zeros((bT, 128), jnp.int32)
                for j, units in enumerate(
                        chains[b128 * 128:(b128 + 1) * 128]):
                    K = chain_len(units)
                    hit = None
                    off = 0
                    for u in units:
                        if u[0] == "lit":
                            pred, ulen = lit_pred(u[1]), len(u[1])
                        else:
                            _, ranges, n = u
                            pred, ulen = erode(ranges, n), n
                        pred = shr(pred, K - 1 - off)
                        hit = pred if hit is None else hit & pred
                        off += ulen
                    acc = jnp.where(lane == j, blockmask_col(hit),
                                    acc)
                chain_ref[:, b128 * 128:(b128 + 1) * 128] = \
                    acc.astype(jnp.uint32)

    return kernel, kc128


def dfa_blockmask_pallas(segments: jax.Array, table,
                         dev_arrays: tuple,
                         interpret: bool = False) -> jax.Array:
    """[B, L] uint8 × resident band arrays → [B, n_patterns] uint32
    blockmasks. B must be a TILE_B multiple and L a multiple of
    N_BLOCKS×128 (callers bucket-pad — ops.keywords.pad_batch)."""
    B, L = segments.shape
    assert B % TILE_B == 0 and L % 128 == 0

    groups = table.groups
    padded = []
    kg128s = []
    for gi in range(len(groups)):
        for f in range(4):
            a = _pad128(dev_arrays[4 * gi + f].astype(jnp.uint32),
                        f >= 2)
            if f == 0:
                kg128s.append(a.shape[1])
            padded.append(a.reshape(-1))

    kernel, kc128 = _make_kernel(table, L)
    out_shapes = [
        jax.ShapeDtypeStruct((B, kg128s[gi]), jnp.uint32)
        for gi in range(len(groups))
    ]
    have_chains = bool(table.chains)
    if have_chains:
        out_shapes.append(jax.ShapeDtypeStruct((B, kc128),
                                               jnp.uint32))
    if not out_shapes:
        return jnp.zeros((B, 0), jnp.uint32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4 * len(groups),
        grid=(B // TILE_B,),
        in_specs=[
            pl.BlockSpec((TILE_B, L), lambda i, *_: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((TILE_B, s.shape[1]),
                         lambda i, *_: (i, 0),
                         memory_space=pltpu.VMEM)
            for s in out_shapes
        ],
    )
    outs = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid_spec=grid_spec,
        interpret=interpret,
    )(*padded, segments)

    cols = [outs[gi][:, :g.count] for gi, g in enumerate(groups)]
    if have_chains:
        cols.append(outs[len(groups)][:, :len(table.chains)])
    return jnp.concatenate(cols, axis=1) if cols else \
        jnp.zeros((B, 0), jnp.uint32)
