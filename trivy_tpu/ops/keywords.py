"""Multi-literal substring matching on TPU — the stage-1 sieve.

The reference gates every rule on a per-file substring prefilter
(MatchKeywords, pkg/fanal/secret/scanner.go:164-177) before running its
regex over the whole file. The TPU re-design extends that idea: ONE
kernel scans every segment for (a) the rules' gate keywords and (b) the
anchor literals proven mandatory-in-match by trivy_tpu.secret.rx.anchor
— returning, per (segment, code), a 16-block position bitmask. The host
then regexes only small windows around anchor hits.

This is pure elementwise work — no gathers, which do not vectorize on
the TPU VPU (the gather-DFA measured 2.3 MB/s; these compares run at
HBM rate). Each sliding 8-byte window of the lowercased input is packed
into two uint32 words; a literal of length m ≤ 8 is one masked compare
against its code; longer literals match on their first 8 bytes (a
superset — exactness is restored by host verification).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

N_BLOCKS = 16          # position resolution: L/16 bytes per block
CODE_CHUNK = 8         # literals matched per scan step
MAX_CODE_LEN = 8       # two uint32 words per window


@dataclass(frozen=True)
class CodeTable:
    """Packed literal codes (shared by gate keywords and anchors)."""

    lo: np.ndarray        # [K] uint32 — window bytes 0-3
    hi: np.ndarray        # [K] uint32 — window bytes 4-7
    lo_mask: np.ndarray   # [K] uint32
    hi_mask: np.ndarray   # [K] uint32
    literals: tuple       # K lowercased byte-strings (≤8B, dedup, sorted)

    @property
    def n_codes(self) -> int:
        return len(self.literals)

    def index(self, literal: bytes) -> int:
        return self.literals.index(_truncate(literal))


def _truncate(literal: bytes) -> bytes:
    return literal.lower()[:MAX_CODE_LEN]


def pack_code(literal: bytes) -> tuple:
    """(lo, hi, lo_mask, hi_mask) for one ≤8-byte lowercased literal."""
    b = _truncate(literal)
    m = len(b)
    assert 0 < m <= MAX_CODE_LEN
    lo = int.from_bytes(b[:4].ljust(4, b"\0"), "little")
    hi = int.from_bytes(b[4:].ljust(4, b"\0"), "little")
    lo_mask = (1 << (8 * min(m, 4))) - 1
    hi_mask = ((1 << (8 * (m - 4))) - 1) if m > 4 else 0
    return lo, hi, lo_mask, hi_mask


def build_code_table(literals) -> CodeTable:
    """Dedup + pack a set of byte-string literals."""
    uniq = sorted({_truncate(x) for x in literals if x})
    packed = [pack_code(x) for x in uniq]
    if not packed:
        packed = [(0, 0, 0xFFFFFFFF, 0xFFFFFFFF)]  # matches nothing
        uniq = [b"\x00\x00\x00\x00"]
    arr = np.array(packed, np.uint64).astype(np.uint32)
    return CodeTable(lo=arr[:, 0].copy(), hi=arr[:, 1].copy(),
                     lo_mask=arr[:, 2].copy(), hi_mask=arr[:, 3].copy(),
                     literals=tuple(uniq))


def _window_words(segments: jax.Array) -> tuple:
    """[B, L] uint8 → (lo, hi) [B, L] uint32 sliding 8-byte windows,
    zero-padded past the segment end, ASCII-lowercased."""
    x = segments.astype(jnp.uint32)
    is_upper = (x >= 65) & (x <= 90)
    x = jnp.where(is_upper, x + 32, x)

    def shifted(i):
        if i == 0:
            return x
        return jnp.pad(x[:, i:], ((0, 0), (0, i)))

    lo = (shifted(0) | (shifted(1) << 8) | (shifted(2) << 16)
          | (shifted(3) << 24))
    hi = (shifted(4) | (shifted(5) << 8) | (shifted(6) << 16)
          | (shifted(7) << 24))
    return lo, hi


def _pad_codes(arrs: tuple) -> tuple:
    K = arrs[0].shape[0]
    Kp = ((K + CODE_CHUNK - 1) // CODE_CHUNK) * CODE_CHUNK
    if Kp == K:
        return arrs
    out = []
    for i, a in enumerate(arrs):
        pad = np.zeros(Kp - K, a.dtype)
        if i >= 2:            # masks: full masks + nonzero code ⇒ no match
            pad = pad + np.uint32(0xFFFFFFFF)
        out.append(np.concatenate([np.asarray(a), pad]))
    # padded codes are 0 with full masks: only a window of 8 NULs would
    # match; NUL never appears in lowercased text windows except final
    # padding, where a hit is harmless (killed by host verify).
    return tuple(out)


def code_blockmask_impl(segments: jax.Array, lo_c: jax.Array,
                        hi_c: jax.Array, lo_m: jax.Array,
                        hi_m: jax.Array) -> jax.Array:
    """[B, L] segments × K codes → [B, K] uint32 position bitmasks
    (bit j = code hit inside block j of N_BLOCKS equal slices)."""
    B, L = segments.shape
    lo, hi = _window_words(segments)
    blk = L // N_BLOCKS
    bits = (jnp.uint32(1) << jnp.arange(N_BLOCKS, dtype=jnp.uint32))

    chunks = lo_c.shape[0] // CODE_CHUNK

    def step(_, kw):
        klo, khi, mlo, mhi = kw               # each [CODE_CHUNK]
        hit = (((lo[:, :, None] & mlo) == klo)
               & ((hi[:, :, None] & mhi) == khi))     # [B, L, C]
        hb = hit.reshape(B, N_BLOCKS, blk, CODE_CHUNK).any(axis=2)
        mask = jnp.sum(
            jnp.where(hb, bits[None, :, None], jnp.uint32(0)),
            axis=1, dtype=jnp.uint32)                 # [B, C]
        return None, mask

    xs = tuple(a.reshape(chunks, CODE_CHUNK) for a in
               (lo_c, hi_c, lo_m, hi_m))
    _, masks = lax.scan(step, None, xs)               # [chunks, B, C]
    return masks.transpose(1, 0, 2).reshape(B, -1)    # [B, Kp]


code_blockmask = jax.jit(code_blockmask_impl)


def code_blockmask_host(segments, lo_c, hi_c, lo_m, hi_m):
    """NumPy reference (differential testing)."""
    B, L = segments.shape
    x = segments.astype(np.uint32)
    x = np.where((x >= 65) & (x <= 90), x + 32, x)
    pads = [np.pad(x[:, i:], ((0, 0), (0, i))) for i in range(8)]
    lo = pads[0] | pads[1] << 8 | pads[2] << 16 | pads[3] << 24
    hi = pads[4] | pads[5] << 8 | pads[6] << 16 | pads[7] << 24
    K = lo_c.shape[0]
    blk = L // N_BLOCKS
    out = np.zeros((B, K), np.uint32)
    for k in range(K):
        hit = (((lo & lo_m[k]) == lo_c[k])
               & ((hi & hi_m[k]) == hi_c[k]))         # [B, L]
        hb = hit.reshape(B, N_BLOCKS, blk).any(axis=2)
        out[:, k] = (hb.astype(np.uint32)
                     << np.arange(N_BLOCKS, dtype=np.uint32)).sum(axis=1)
    return out


def run_blockmask(segments: np.ndarray, table: CodeTable,
                  backend: str = "tpu", mesh=None) -> np.ndarray:
    """Dispatch helper: pads codes to the chunk size and the batch to a
    shape bucket (jit-cache friendly), slices padding back off."""
    K = table.n_codes
    codes = _pad_codes((table.lo, table.hi, table.lo_mask,
                        table.hi_mask))
    if backend == "cpu-ref":
        return code_blockmask_host(segments, *codes)[:, :K]
    B = segments.shape[0]
    segments = pad_batch(segments)
    if mesh is not None:
        from ..parallel.secret_shard import sharded_blockmask
        return sharded_blockmask(mesh, segments, codes)[:B, :K]
    import jax
    if jax.default_backend() != "cpu":
        # Pallas kernel: one HBM pass per tile instead of one per code
        # chunk (the XLA scan re-reads window words every step)
        from .keywords_pallas import code_blockmask_pallas
        out = code_blockmask_pallas(jnp.asarray(segments),
                                    *(jnp.asarray(c) for c in codes))
    else:
        out = code_blockmask(jnp.asarray(segments),
                             *(jnp.asarray(c) for c in codes))
    return np.asarray(out)[:B, :K]


SIEVE_CAP = 4096       # compacted-fetch capacity (hit segments)


def _sieve_blockmask_fn(literals: tuple, platform: str):
    """Shared setup for the fused/full sieve factories: one place
    builds the code table, pads it, picks the pallas vs XLA kernel,
    and stages device constants — so the compacted and fallback
    paths cannot drift apart. Returns (n_codes, blockmask_fn)."""
    table = build_code_table(literals)
    codes = _pad_codes((table.lo, table.hi, table.lo_mask,
                        table.hi_mask))
    cdev = tuple(jnp.asarray(c) for c in codes)
    if platform != "cpu":
        from .keywords_pallas import code_blockmask_pallas

        def blockmask(segments):
            return code_blockmask_pallas(segments, *cdev)
    else:
        def blockmask(segments):
            return code_blockmask_impl(segments, *cdev)
    return table.n_codes, blockmask


@functools.lru_cache(maxsize=8)
def make_fused_sieve(literals: tuple, run_specs: tuple,
                     platform: str):
    """ONE jit dispatch for both sieve stages over a device-resident
    segment buffer: literal blockmask + class-run hits.

    Host↔device crossings dominate the sieve under the tunneled
    chip, so the segment buffer crosses ONCE, both kernels read the
    resident copy, and the fetch is COMPACTED on device: only the
    rows of segments with ≥1 code hit come back (as uint16 —
    N_BLOCKS = 16 bits used — gathered at fixed capacity SIEVE_CAP
    so shapes stay static under jit). Run hits are [B, n_specs]
    bool and come back whole: a file's mandatory class-run can sit
    in a segment with no keyword hit.

    Returns (per jit call over [B, L] segments):
      nhit   — i32 scalar, segments with ≥1 code hit
      idx    — [CAP] i32, their row indices (first nhit valid,
               ascending; CAP = min(SIEVE_CAP, B))
      cmasks — [CAP, K] uint16 blockmask rows for those segments
      hits   — [B, n_specs] bool class-run presence

    When nhit > CAP the compacted fetch is insufficient — callers
    fall back to the full-mask variant (make_full_sieve).

    Cached on (literals, run_specs, platform) so scanner instances
    share the compile — platform is in the key because
    dryrun_multichip re-points JAX at CPU mid-process."""
    n_codes, blockmask = _sieve_blockmask_fn(literals, platform)
    from .runs import run_hits_impl

    @jax.jit
    def fused(segments: jax.Array) -> tuple:
        masks = blockmask(segments)
        # slice off pad codes BEFORE seg_any: pad entries (0 with
        # full masks) hit 8-NUL windows, so counting their columns
        # would mark every zero-padded tail segment as a hit and
        # defeat the compaction whenever n_codes < padded width
        masks = masks[:, :n_codes].astype(jnp.uint16)
        B = segments.shape[0]
        cap = min(SIEVE_CAP, B)
        seg_any = (masks != 0).any(axis=1)
        nhit = seg_any.sum(dtype=jnp.int32)
        idx = jnp.nonzero(seg_any, size=cap, fill_value=0)[0]
        cmasks = masks[idx]
        if run_specs:
            hits = run_hits_impl(segments, run_specs)
        else:
            hits = jnp.zeros((B, 0), jnp.bool_)
        return nhit, idx, cmasks, hits

    return fused


@functools.lru_cache(maxsize=8)
def make_full_sieve(literals: tuple, platform: str):
    """Full-mask variant of make_fused_sieve for the rare batch
    where more than SIEVE_CAP segments hit: returns the whole
    [B, K] uint16 mask array. Run hits are NOT recomputed — the
    fused dispatch already produced them and callers keep that
    array."""
    n_codes, blockmask = _sieve_blockmask_fn(literals, platform)

    @jax.jit
    def full(segments: jax.Array) -> jax.Array:
        # drop pad-code columns
        return blockmask(segments)[:, :n_codes].astype(jnp.uint16)

    return full


def _bucket(n: int, base: int = 256, cap: int = 4096) -> int:
    """Round batch sizes up to a small set of shapes so jit caches
    stay warm (pad rows are zeros — they match nothing real).
    Powers of two from ``base`` up to ``cap``, then ``cap``-steps
    (a 40k-segment batch should not pad to 64k). The defaults are
    the segment-buffer ladder; detect/batch.py reuses this with a
    64/8192 ladder for pair rows."""
    b = base
    while b < n and b < cap:
        b *= 2
    if n <= b:
        return b
    return ((n + cap - 1) // cap) * cap


def pad_batch(segments: np.ndarray) -> np.ndarray:
    B = segments.shape[0]
    Bp = _bucket(B)
    if Bp == B:
        return segments
    return np.concatenate(
        [segments, np.zeros((Bp - B, segments.shape[1]),
                            segments.dtype)])
