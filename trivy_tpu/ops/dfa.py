"""Multi-pattern DFA engine for the secret sieve — compile once,
scan everything in one dispatch.

The round-5 sieve matched rule literals truncated to 8 bytes (one
masked-word compare per code) and left every windowed rule's real
semantics to the host. This module is the Hyperscan-style step
(ROADMAP item 2): the whole rule corpus — full-length gate keywords,
anchor literals, and the provably-finite fixed subchains of the
windowed patterns from ``secret/rx`` — compiles into ONE shared
automaton whose banded transition table is resident in HBM next to
the advisory tables, and a single kernel pass emits per-(segment,
pattern) position bitmasks.

Automaton shape. Every pattern is a *fixed chain*: states 1..k where
state ``i`` is reached from ``i-1`` iff the input byte lies in the
state's byte class. The transition table is therefore banded —
``T[s, c] ∈ {0, s+1}`` — and that band is what makes the engine
TPU-native: instead of walking ``state = T[state, byte]`` serially
(the gather-DFA measured 2.3 MB/s — gathers do not vectorize on the
VPU, see ops/keywords.py), the kernel evaluates EVERY state's band
transition in parallel per text position: a chain of k classes ends
at position t iff all k membership tests pass at t-k+1..t. Literal
runs collapse to masked sliding-window word compares (8 states per
compare) and same-class runs collapse to log-doubling erosion
(ops/runs.py), so the per-byte work is elementwise compares at HBM
rate, not K serial lookups.

Soundness. The compiler only ever OVER-approximates: a pattern hit
is necessary for the rule's Python ``re`` to match, never sufficient
— every hit is re-verified by the CPU-exact scanner, and a miss is a
proof the rule cannot fire (secret/rx/parser.py builds the AST as an
exact-or-superset byte model; boundaries are ε; Unicode-aware units
become variable atoms that break chains instead of lying about byte
widths). Case: literal patterns match on ASCII-lowercased text
(superset of any caseful literal; exact for the case-insensitive
keyword gate), class memberships run on raw bytes with the AST's own
folding.

Residency: ``DfaTable`` shares the generation/invalidation machinery
of the compiled advisory DB (db/compiled.py ResidentTables) — the
packed band arrays upload once per (rule-set hash, placement) with a
``dfa_upload`` span, and ``/metrics`` reports the amortization
(secret/metrics.py).
"""

from __future__ import annotations

import hashlib
import math
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# NOTE: the sieve kernels donate their per-batch segment buffer
# (freed as soon as the kernel consumes it — the async-runtime
# slot-reuse contract); the uint16 mask output cannot alias the
# uint8 segment input, so XLA's "Some donated buffers were not
# usable" aliasing advisory is expected. Filtered at the
# application level (cli/bench/pytest.ini), never here — see
# ops/intervals.py.

from ..db.compiled import ResidentTables
from .keywords import (CODE_CHUNK, MAX_CODE_LEN, N_BLOCKS, SIEVE_CAP,
                       pack_code, pad_batch)
from .runs import RunSpec

MAX_LIT_BYTES = 32        # literal patterns: up to 4 masked words
MAX_CHAIN_LEN = 48        # fixed-chain cap — bounds segment overlap
MIN_CHAIN_BITS = 24.0     # selectivity floor to keep a chain
MAX_CLASS_RANGES = 8      # wider classes get gap-merged (superset)
REP_EXPAND_CAP = 96       # {m} repeat expansion cap, in positions

ALL_BYTES = frozenset(range(256))


# ---------------------------------------------------------------------
# chain extraction: rx AST → fixed byte-class chains
# ---------------------------------------------------------------------

def _lower(b: int) -> int:
    return b + 32 if 65 <= b <= 90 else b


def _atoms(node) -> list:
    """Flatten an rx AST into a list of atoms: a tuple of per-byte
    classes (a FIXED stretch — every match threads through exactly
    these positions) or None (a VARIABLE stretch — chain breaker).
    Zero-width nodes vanish. Always an over-approximation: the
    fixed atoms are mandatory contiguous byte positions of every
    match of ``node``."""
    from ..secret.rx.parser import Alt, Boundary, Cat, Empty, Lit, Rep
    if isinstance(node, (Boundary, Empty)):
        return []
    if isinstance(node, Lit):
        # Unicode-aware units consume 1-4 bytes — variable
        return [(node.bytes,)] if node.ascii_only else [None]
    if isinstance(node, Cat):
        out: list = []
        for p in node.parts:
            out.extend(_atoms(p))
        return out
    if isinstance(node, Rep):
        sub = _atoms(node.node)
        if not sub:
            return []                       # repeat of zero-width
        if node.max is not None and node.min == node.max \
                and all(a is not None for a in sub):
            total = node.min * sum(len(a) for a in sub)
            if total <= REP_EXPAND_CAP:
                return [a for _ in range(node.min) for a in sub]
        return [None]
    if isinstance(node, Alt):
        flats = []
        for o in node.options:
            sub = _atoms(o)
            if any(a is None for a in sub):
                return [None]
            flats.append(tuple(c for a in sub for c in a))
        flats = [f for f in flats if f] or [()]
        if all(len(f) == len(flats[0]) for f in flats) \
                and len(flats) == len(node.options):
            # equal-length branches: positionwise class union is a
            # fixed superset (e.g. (test_|live_), (AKIA|ASIA|...))
            n = len(flats[0])
            return [tuple(frozenset().union(*(f[i] for f in flats))
                          for i in range(n))] if n else []
        return [None]
    raise TypeError(node)


def _bits(cls: frozenset) -> float:
    """Selectivity of one position in bits; case-pairs matched on
    lowered text count their folded width."""
    lows = {_lower(b) for b in cls}
    width = 2 * len(lows) if len(lows) < len(cls) or any(
        97 <= b <= 122 for b in lows) else len(cls)
    return math.log2(256 / min(256, max(1, width)))


def best_fixed_chain(node) -> Optional[tuple]:
    """The most selective fixed byte-class window (≤ MAX_CHAIN_LEN
    positions) that every match of ``node`` must contain
    contiguously — or None when nothing clears MIN_CHAIN_BITS.
    Returns a tuple of frozenset classes."""
    runs: list = []
    cur: list = []
    for a in _atoms(node):
        if a is None:
            if cur:
                runs.append(cur)
            cur = []
        else:
            cur.extend(a)
    if cur:
        runs.append(cur)
    best, best_score = None, 0.0
    for run in runs:
        bits = [_bits(c) for c in run]
        n = len(run)
        w = min(n, MAX_CHAIN_LEN)
        # best score window of width ≤ w (prefix sums)
        pre = [0.0]
        for b in bits:
            pre.append(pre[-1] + b)
        for i in range(n - w + 1) if n else ():
            score = pre[i + w] - pre[i]
            if score > best_score:
                best, best_score = tuple(run[i:i + w]), score
    if best is None or best_score < MIN_CHAIN_BITS:
        return None
    return best


def _merge_ranges(ranges: tuple) -> tuple:
    """Cap a class's range list at MAX_CLASS_RANGES by repeatedly
    merging the smallest gap — a byteset SUPERSET, so memberships
    stay a sound over-approximation."""
    rs = [list(r) for r in ranges]
    while len(rs) > MAX_CLASS_RANGES:
        gaps = [(rs[i + 1][0] - rs[i][1], i)
                for i in range(len(rs) - 1)]
        _, i = min(gaps)
        rs[i][1] = rs[i + 1][1]
        del rs[i + 1]
    return tuple((lo, hi) for lo, hi in rs)


def chain_units(classes: tuple) -> tuple:
    """Compile a fixed class chain into the band encoding the kernel
    evaluates: runs of literal-exact positions become ("lit", bytes)
    (masked word compares on lowered text); CONSECUTIVE class
    positions collapse into one ("run", ranges, n) over their byte
    UNION — a further sound over-approximation (any string matching
    the positioned classes is n bytes drawn from the union) that
    keeps the per-chain kernel work at one membership + one
    log-doubling erosion per run instead of one per position.
    Per-position unions rarely cost selectivity: the corpus's class
    stretches are token bodies ([A-Z0-9]{16}, hex{32}) whose union
    is the stretch's own alphabet."""
    units: list = []
    lit: list = []
    run: list = []               # [union byteset, length]

    def flush_lit():
        nonlocal lit
        if lit:
            units.append(("lit", bytes(lit)))
            lit = []

    def flush_run():
        nonlocal run
        if run:
            ranges = _merge_ranges(
                RunSpec.from_byteset(frozenset(run[0]), 1).ranges)
            units.append(("run", ranges, run[1]))
            run = []

    for cls in classes:
        lows = {_lower(b) for b in cls}
        if len(lows) == 1 and len(cls) <= 2:
            flush_run()
            lit.append(next(iter(lows)))
            continue
        flush_lit()
        if run:
            run = [run[0] | cls, run[1] + 1]
        else:
            run = [set(cls), 1]
    flush_lit()
    flush_run()
    return tuple(units)


def chain_len(units: tuple) -> int:
    return sum(len(u[1]) if u[0] == "lit" else u[2] for u in units)


# ---------------------------------------------------------------------
# the table
# ---------------------------------------------------------------------

@dataclass(frozen=True)
class _LitGroup:
    chunks: int          # masked words per literal
    start: int           # first pattern column of this group
    count: int           # literals in the group


class DfaTable(ResidentTables):
    """One compiled multi-pattern table: literal patterns (full-length
    keywords + anchors, grouped by word-chunk count) followed by
    chain patterns. Pattern *columns* are the index space the scan
    plan stores; ``masks[:, col]`` is a 16-block position bitmask —
    START positions for literals (window math identical to the old
    code table), END positions for chains (file-level gates)."""

    _UPLOAD_SPAN = "dfa_upload"
    _TABLE = "dfa"              # /metrics residency label

    def __init__(self, literals: list, chains: list):
        # literals: lowercased bytes, 1..MAX_LIT_BYTES, deduped by
        # caller; chains: unit tuples from chain_units, deduped
        self._init_resident()
        order = sorted(range(len(literals)),
                       key=lambda i: (-(-len(literals[i]) //
                                        MAX_CODE_LEN), literals[i]))
        self.literals = tuple(literals[i] for i in order)
        self.chains = tuple(chains)
        self._lit_col = {b: c for c, b in enumerate(self.literals)}
        self._chain_col = {u: len(self.literals) + c
                           for c, u in enumerate(self.chains)}
        self.n_patterns = len(self.literals) + len(self.chains)

        self.groups: list = []
        self._arrays: list = []
        col = 0
        i = 0
        while i < len(self.literals):
            c = -(-len(self.literals[i]) // MAX_CODE_LEN)
            j = i
            while j < len(self.literals) and \
                    -(-len(self.literals[j]) // MAX_CODE_LEN) == c:
                j += 1
            group = self.literals[i:j]
            self.groups.append(_LitGroup(chunks=c, start=col,
                                         count=len(group)))
            self._arrays.extend(self._pack_group(group, c))
            col += len(group)
            i = j
        self.rules_hash = hashlib.sha256(
            repr((self.literals, self.chains)).encode()
        ).hexdigest()[:16]
        self._fns: dict = {}
        self._fns_lock = threading.Lock()

    @staticmethod
    def _pack_group(group: tuple, chunks: int) -> list:
        Kg = len(group)
        Kp = -(-Kg // CODE_CHUNK) * CODE_CHUNK
        lo = np.zeros((chunks, Kp), np.uint64)
        hi = np.zeros((chunks, Kp), np.uint64)
        lom = np.zeros((chunks, Kp), np.uint64)
        him = np.zeros((chunks, Kp), np.uint64)
        # pad columns must never hit: code 0 under a full mask only
        # matches 8 NULs, and pad columns are sliced off before any
        # consumer sees them anyway
        lom[0, Kg:] = him[0, Kg:] = 0xFFFFFFFF
        for k, lit in enumerate(group):
            for j in range(chunks):
                part = lit[j * MAX_CODE_LEN:(j + 1) * MAX_CODE_LEN]
                if not part:
                    continue            # trailing chunk: always-true
                lo[j, k], hi[j, k], lom[j, k], him[j, k] = \
                    pack_code(part)
        return [a.astype(np.uint32) for a in (lo, hi, lom, him)]

    # --- index space (the scan plan stores these columns) ---

    def lit_col(self, literal: bytes) -> int:
        return self._lit_col[literal.lower()]

    def chain_col(self, units: tuple) -> int:
        return self._chain_col[units]

    def lit_len(self, col: int) -> int:
        return len(self.literals[col])

    @property
    def max_chunks(self) -> int:
        cs = [g.chunks for g in self.groups]
        for units in self.chains:
            cs.extend(-(-len(u[1]) // MAX_CODE_LEN)
                      for u in units if u[0] == "lit")
        return max(cs, default=1)

    # --- residency hooks (ResidentTables) ---

    def _resident_arrays(self) -> tuple:
        return tuple(self._arrays)

    def _span_attrs(self) -> dict:
        return {"patterns": self.n_patterns,
                "rules_hash": self.rules_hash}

    def _note_upload(self, nbytes: int) -> None:
        from ..secret.metrics import SECRET_METRICS
        SECRET_METRICS.note_dfa_upload(nbytes)

    def _note_dispatch(self) -> None:
        from ..secret.metrics import SECRET_METRICS
        SECRET_METRICS.inc("dfa_dispatches")

    def _note_invalidation(self) -> None:
        from ..secret.metrics import SECRET_METRICS
        SECRET_METRICS.inc("dfa_invalidations")

    # --- compiled scan functions (cached per table) ---

    def fused_sieve(self, run_specs: tuple, platform: str):
        """ONE jit dispatch over a device-resident segment buffer:
        pattern blockmasks + class-run hits, with the fetch
        COMPACTED to hit rows (ops/keywords.make_fused_sieve
        semantics: returns (nhit, idx, cmasks, run_hits))."""
        return self._fn(("fused", run_specs, platform))

    def full_sieve(self, run_specs: tuple, platform: str):
        """Full-fetch variant: (masks [B, K] uint16, run_hits). The
        single-device path falls back to it (with ``run_specs=()``)
        when a batch overflows the compaction capacity."""
        return self._fn(("full", run_specs, platform))

    def precompile(self, run_specs: tuple = (), buckets=None,
                   cache_dir: str = "", platform: str = "") -> dict:
        """Warm this table's fused sieve over the segment ladder
        into the persistent compilation cache, keyed on
        ``rules_hash`` (docs/serving.md "Elastic lifecycle") —
        stages the resident arrays as a side effect. A boot-time
        hook: the first real batch after a scale-up neither traces
        nor uploads."""
        from ..runtime.aot import (DEFAULT_SEG_BUCKETS,
                                   precompile_dfa_shapes)
        return precompile_dfa_shapes(
            self, run_specs, buckets or DEFAULT_SEG_BUCKETS,
            cache_dir, platform)

    def mesh_sieve(self, mesh, run_specs: tuple, platform: str):
        """Mesh variant: the segment rows shard over EVERY chip
        (flat — masks are row-elementwise, no collective needed),
        the band arrays replicate, and the whole sieve is ONE
        shard_map dispatch — one compile per (mesh, shape), where a
        per-device dispatch loop would compile once per DEVICE per
        shape (measured ~1.3 s × devices × shapes of pure compile
        thrash on the CPU sim). Returns (masks [B, K] uint16,
        run_hits [B, n_specs])."""
        return self._fn(("mesh", mesh, run_specs, platform))

    def _fn(self, key: tuple):
        with self._fns_lock:
            fn = self._fns.get(key)
            if fn is None:
                if key[0] == "mesh":
                    fn = _build_mesh_sieve(self, *key[1:])
                else:
                    fn = _build_sieve(self, *key)
                self._fns[key] = fn
        return fn


_TABLE_CACHE: dict = {}
_TABLE_LOCK = threading.Lock()
_TABLE_CACHE_MAX = 8


def build_table(literals, chains) -> DfaTable:
    """Compile (or fetch) the table for one rule corpus. Cached on
    the rule-set hash so every scanner instance built from the same
    rules shares one table — and therefore one HBM upload per
    placement (the ``trivy-secret.yaml`` fleet case compiles custom
    rules into their own cached table)."""
    lits = tuple(sorted({x.lower() for x in literals if x}))
    chs = tuple(sorted(set(chains), key=repr))
    fp = hashlib.sha256(repr((lits, chs)).encode()).hexdigest()
    evicted = []
    with _TABLE_LOCK:
        table = _TABLE_CACHE.get(fp)
        if table is None:
            table = DfaTable(list(lits), list(chs))
            _TABLE_CACHE[fp] = table
            while len(_TABLE_CACHE) > _TABLE_CACHE_MAX:
                # FIFO eviction; dropped tables free their HBM once
                # the last in-flight dispatch releases its buffers
                evicted.append(_TABLE_CACHE.pop(
                    next(iter(_TABLE_CACHE))))
    for old in evicted:
        # invalidate_device takes the table's ResidentTables lock —
        # outside _TABLE_LOCK (lint: lock-discipline)
        old.invalidate_device()
    return table


# ---------------------------------------------------------------------
# the kernel body (jnp interpreter — ops/dfa_pallas.py is the TPU
# kernel; both evaluate the same banded table)
# ---------------------------------------------------------------------

def _shift_left(a, k: int):
    """a[:, i] ← a[:, i+k], zero-filled at the tail."""
    if k == 0:
        return a
    return jnp.pad(a[:, k:], ((0, 0), (0, k)))


def _shift_right(a, k: int):
    """a[:, i] ← a[:, i-k], zero-filled at the head."""
    if k == 0:
        return a
    return jnp.pad(a[:, :-k], ((0, 0), (k, 0)))


def _window_words_lower(segments):
    """Sliding 8-byte windows of the ASCII-lowercased input, as
    (lo, hi) uint32 pairs for every word offset the table needs."""
    x = segments.astype(jnp.uint32)
    x = jnp.where((x >= 65) & (x <= 90), x + 32, x)
    sh = [_shift_left(x, i) for i in range(8)]
    lo = sh[0] | (sh[1] << 8) | (sh[2] << 16) | (sh[3] << 24)
    hi = sh[4] | (sh[5] << 8) | (sh[6] << 16) | (sh[7] << 24)
    return lo, hi


def _blockmask(hits, bits):
    """[B, L] bool → [B] uint32 16-block position bitmask."""
    B, L = hits.shape
    hb = hits.reshape(B, N_BLOCKS, L // N_BLOCKS).any(axis=2)
    return jnp.sum(jnp.where(hb, bits, jnp.uint32(0)), axis=1,
                   dtype=jnp.uint32)


def _membership(x, ranges):
    m = jnp.zeros(x.shape, bool)
    for lo, hi in ranges:
        m = m | (x == lo) if lo == hi else \
            m | ((x >= lo) & (x <= hi))
    return m


def _lit_pred(lo_sh, hi_sh, data: bytes):
    """[B, L] bool: full literal ``data`` starts at position t (on
    lowered text). Chunk j is one masked compare of the word at
    t + 8j."""
    p = None
    for j in range(-(-len(data) // MAX_CODE_LEN)):
        part = data[j * MAX_CODE_LEN:(j + 1) * MAX_CODE_LEN]
        klo, khi, mlo, mhi = (jnp.uint32(v) for v in pack_code(part))
        cmp = ((lo_sh[j] & mlo) == klo) & ((hi_sh[j] & mhi) == khi)
        p = cmp if p is None else p & cmp
    return p


def _erode(m, n: int):
    """e[i] = AND of m[i..i+n-1] (log-doubling, ops/runs shape)."""
    e = m
    span = 1
    while span < n:
        step = min(span, n - span)
        e = e & _shift_left(e, step)
        span += step
    return e


def dfa_masks_impl(segments, dev_arrays: tuple, table: DfaTable):
    """[B, L] uint8 × resident table → [B, n_patterns] uint32
    blockmasks. ``table`` supplies only STATIC structure (groups,
    chain units, lengths); the packed band arrays come in as device
    operands so residency is real."""
    B, L = segments.shape
    blk = L // N_BLOCKS
    bits = (jnp.uint32(1) << jnp.arange(N_BLOCKS, dtype=jnp.uint32))

    lo, hi = _window_words_lower(segments)
    nch = table.max_chunks
    lo_sh = [_shift_left(lo, 8 * j) for j in range(nch)]
    hi_sh = [_shift_left(hi, 8 * j) for j in range(nch)]

    cols = []
    ai = 0
    for g in table.groups:
        glo, ghi, glom, ghim = dev_arrays[ai:ai + 4]
        ai += 4
        c = g.chunks

        def step(_, kw, c=c):
            klo, khi, mlo, mhi = kw         # each [c, CODE_CHUNK]
            hit = None
            for j in range(c):
                h = (((lo_sh[j][:, :, None] & mlo[j]) == klo[j])
                     & ((hi_sh[j][:, :, None] & mhi[j]) == khi[j]))
                hit = h if hit is None else hit & h
            hb = hit.reshape(B, N_BLOCKS, blk, CODE_CHUNK).any(axis=2)
            mask = jnp.sum(
                jnp.where(hb, bits[None, :, None], jnp.uint32(0)),
                axis=1, dtype=jnp.uint32)   # [B, CODE_CHUNK]
            return None, mask

        xs = tuple(a.reshape(c, -1, CODE_CHUNK).transpose(1, 0, 2)
                   for a in (glo, ghi, glom, ghim))
        _, masks = lax.scan(step, None, xs)
        cols.append(masks.transpose(1, 0, 2)
                    .reshape(B, -1)[:, :g.count])

    if table.chains:
        xi = segments.astype(jnp.int32)
        memb: dict = {}
        erod: dict = {}
        chain_cols = []
        for units in table.chains:
            K = chain_len(units)
            acc = None
            off = 0
            for u in units:
                if u[0] == "lit":
                    pred = _lit_pred(lo_sh, hi_sh, u[1])
                    ulen = len(u[1])
                else:
                    _, ranges, n = u
                    m = memb.get(ranges)
                    if m is None:
                        m = memb[ranges] = _membership(xi, ranges)
                    pred = erod.get((ranges, n))
                    if pred is None:
                        pred = erod[(ranges, n)] = _erode(m, n)
                    ulen = n
                # start-position predicate, rolled to the chain END
                pred = _shift_right(pred, K - 1 - off)
                acc = pred if acc is None else acc & pred
                off += ulen
            chain_cols.append(_blockmask(acc, bits))
        cols.append(jnp.stack(chain_cols, axis=1))

    if not cols:
        return jnp.zeros((B, 0), jnp.uint32)
    return jnp.concatenate(cols, axis=1)


# ---------------------------------------------------------------------
# NumPy reference (differential testing + the cpu-ref backend)
# ---------------------------------------------------------------------

def dfa_masks_host(segments: np.ndarray, table: DfaTable) \
        -> np.ndarray:
    B, L = segments.shape
    blk = L // N_BLOCKS
    bitvals = (np.uint32(1) << np.arange(N_BLOCKS, dtype=np.uint32))

    x = segments.astype(np.uint32)
    xl = np.where((x >= 65) & (x <= 90), x + 32, x)

    def shl(a, k):
        return a if k == 0 else \
            np.pad(a[:, k:], ((0, 0), (0, k)))

    def shr(a, k):
        return a if k == 0 else \
            np.pad(a[:, :-k], ((0, 0), (k, 0)))

    sh = [shl(xl, i) for i in range(8)]
    lo = sh[0] | sh[1] << 8 | sh[2] << 16 | sh[3] << 24
    hi = sh[4] | sh[5] << 8 | sh[6] << 16 | sh[7] << 24
    nch = table.max_chunks
    lo_sh = [shl(lo, 8 * j) for j in range(nch)]
    hi_sh = [shl(hi, 8 * j) for j in range(nch)]

    def blockmask(hits):
        hb = hits.reshape(B, N_BLOCKS, blk).any(axis=2)
        return (hb.astype(np.uint32) * bitvals).sum(
            axis=1, dtype=np.uint32)

    def lit_pred(data):
        p = None
        for j in range(-(-len(data) // MAX_CODE_LEN)):
            part = data[j * MAX_CODE_LEN:(j + 1) * MAX_CODE_LEN]
            klo, khi, mlo, mhi = pack_code(part)
            cmp = ((lo_sh[j] & np.uint32(mlo)) == np.uint32(klo)) \
                & ((hi_sh[j] & np.uint32(mhi)) == np.uint32(khi))
            p = cmp if p is None else p & cmp
        return p

    out = np.zeros((B, table.n_patterns), np.uint32)
    for col, lit in enumerate(table.literals):
        out[:, col] = blockmask(lit_pred(lit))

    xi = segments.astype(np.int32)
    for ci, units in enumerate(table.chains):
        K = chain_len(units)
        acc = None
        off = 0
        for u in units:
            if u[0] == "lit":
                pred = lit_pred(u[1])
                ulen = len(u[1])
            else:
                _, ranges, n = u
                m = np.zeros(xi.shape, bool)
                for a, b in ranges:
                    m |= (xi >= a) & (xi <= b)
                e = m
                span = 1
                while span < n:
                    step = min(span, n - span)
                    e = e & shl(e, step)
                    span += step
                pred, ulen = e, n
            pred = shr(pred, K - 1 - off)
            acc = pred if acc is None else acc & pred
            off += ulen
        out[:, len(table.literals) + ci] = blockmask(acc)
    return out


# ---------------------------------------------------------------------
# fused dispatch factory (compaction shape: ops/keywords.py)
# ---------------------------------------------------------------------

def _masks_fn(table: DfaTable, platform: str):
    if platform != "cpu":
        from .dfa_pallas import dfa_blockmask_pallas

        def masks_fn(segments, dev):
            return dfa_blockmask_pallas(segments, table, dev)
    else:
        def masks_fn(segments, dev):
            return dfa_masks_impl(segments, dev, table)
    return masks_fn


def _build_mesh_sieve(table: DfaTable, mesh, run_specs: tuple,
                      platform: str):
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import (DATA_AXIS, RULES_AXIS,
                                 shard_map_compat)
    from .runs import run_hits_impl
    masks_fn = _masks_fn(table, platform)
    row = P((DATA_AXIS, RULES_AXIS), None)

    def local(segments, *dev):
        masks = masks_fn(segments, dev).astype(jnp.uint16)
        if run_specs:
            hits = run_hits_impl(segments, run_specs)
        else:
            hits = jnp.zeros((segments.shape[0], 0), jnp.bool_)
        return masks, hits

    rep = tuple(P(*([None] * a.ndim))
                for a in table._resident_arrays())
    fn = shard_map_compat(local, mesh=mesh,
                          in_specs=(row,) + rep,
                          out_specs=(row, row))
    return jax.jit(fn)


def _build_sieve(table: DfaTable, kind: str, run_specs: tuple,
                 platform: str):
    from .runs import run_hits_impl
    masks_fn = _masks_fn(table, platform)

    K = table.n_patterns

    # argnum 0 (the per-batch segment buffer) is DONATED: each
    # dispatch uploads a fresh buffer, the kernel may free/reuse its
    # HBM immediately, and collect frees the slot for the next
    # upload (docs/performance.md §8). The band/table arrays ride in
    # *dev and are NEVER donated — they are the resident state every
    # dispatch of this rule-set generation shares. Callers must not
    # reuse a segment buffer after the call (the >CAP full-fetch
    # fallback re-uploads, secret/batch._decode).
    def full(segments, *dev):
        masks = masks_fn(segments, dev).astype(jnp.uint16)
        B = segments.shape[0]
        if run_specs:
            hits = run_hits_impl(segments, run_specs)
        else:
            hits = jnp.zeros((B, 0), jnp.bool_)
        return masks, hits

    full = jax.jit(full, donate_argnums=(0,))

    if kind == "full":
        return full

    def fused(segments, *dev):
        masks = masks_fn(segments, dev).astype(jnp.uint16)
        B = segments.shape[0]
        cap = min(SIEVE_CAP, B)
        seg_any = (masks != 0).any(axis=1) if K else \
            jnp.zeros((B,), bool)
        nhit = seg_any.sum(dtype=jnp.int32)
        idx = jnp.nonzero(seg_any, size=cap, fill_value=0)[0]
        cmasks = masks[idx]
        if run_specs:
            hits = run_hits_impl(segments, run_specs)
        else:
            hits = jnp.zeros((B, 0), jnp.bool_)
        return nhit, idx, cmasks, hits

    return jax.jit(fused, donate_argnums=(0,))


__all__ = [
    "MAX_LIT_BYTES", "MAX_CHAIN_LEN", "DfaTable", "build_table",
    "best_fixed_chain", "chain_units", "chain_len",
    "dfa_masks_host", "dfa_masks_impl", "pad_batch",
]
