"""Batched DFA scanning on TPU.

The hot loop of secret detection (reference: pkg/fanal/secret/scanner.go
Scan → 83 × regexp.FindAllIndex per file) re-designed for TPU: all rule
groups' DFAs advance over a [B, L] segment batch in lock-step. Per input
byte each group does three [B]-sized gathers (byte→class, state×class→
state, state→accept-mask) on the VPU — no data-dependent control flow,
fixed shapes, one ``lax.scan`` over the segment length.

Sharding: segments are data-parallel over the mesh batch axis; DFA
tables are replicated (≈12 MB). See trivy_tpu.parallel for the mesh
plumbing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def dfa_hits_impl(segments: jax.Array, class_maps: jax.Array,
                  trans: jax.Array, accept: jax.Array) -> jax.Array:
    """Run every group DFA over every segment (traceable, un-jitted —
    usable inside shard_map; see trivy_tpu.parallel.secret_shard).

    Args:
      segments:   [B, L] uint8 padded byte buffer (pad value irrelevant —
                  padding may only create false positives, killed by host
                  verification).
      class_maps: [G, 256] int32 byte → class.
      trans:      [G, S, C] int32 dense transition tables.
      accept:     [G, S] uint32 per-state rule-hit bitmasks.

    Returns:
      hits: [B, G] uint32 — OR of accept masks along each scan.
    """
    B = segments.shape[0]
    C = trans.shape[2]
    bytes_t = segments.T.astype(jnp.int32)          # [L, B]

    def per_group(cmap, tr, acc):
        tr_flat = tr.reshape(-1)                    # [S*C]

        def step(carry, byte_col):
            state, hit = carry
            cls = cmap[byte_col]                    # [B]
            nxt = tr_flat[state * C + cls]          # [B]
            hit = hit | acc[nxt]
            return (nxt, hit), None

        init = (jnp.zeros(B, jnp.int32),
                jnp.full((B,), acc[0], jnp.uint32))
        (_, hit), _ = lax.scan(step, init, bytes_t)
        return hit                                  # [B]

    hits = jax.vmap(per_group)(class_maps, trans, accept)   # [G, B]
    return hits.T


dfa_hits = jax.jit(dfa_hits_impl)


def dfa_hits_host(segments, class_maps, trans, accept):
    """NumPy reference implementation (differential testing)."""
    import numpy as np
    B, L = segments.shape
    G, S, C = trans.shape
    out = np.zeros((B, G), dtype=np.uint32)
    for g in range(G):
        for b in range(B):
            s = 0
            hit = int(accept[g, 0])
            for ch in segments[b]:
                s = int(trans[g, s, int(class_maps[g, int(ch)])])
                hit |= int(accept[g, s])
            out[b, g] = hit
    return out
