"""Vectorized version-interval membership — the package→CVE kernel.

The reference compares versions pair-by-pair in Go (compare.go:21-56,
ospkg drivers). TPU re-design: the host parses every version string
once per batch, ranks them within their grammar's total order, and
compiles each advisory's constraints into ≤M half-open intervals in a
DOUBLED rank space (bound = 2·rank, exclusivity = ±1) — after which
"is version v vulnerable to advisory a" is pure int32 compares over a
[P, M] table, identical for every grammar and for both the library
and OS-package detectors.

Semantics bits per pair (flags):
  bit0 has_vulnerable_constraints
  bit1 force (empty-string constraint ⇒ always vulnerable)
  bit2 has_secure_constraints (patched + unaffected)

out = force | (has_vuln ? vuln_any & (has_sec ? ¬sec_any : 1)
                        : (has_sec ? ¬sec_any : 0))
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# NOTE: the donated kernels below free their per-batch payload
# buffers as soon as the kernel consumes them (the slot-reuse
# contract of the async runtime). The hit output is bool while the
# payloads are int32, so XLA can never ALIAS input to output and
# emits its "Some donated buffers were not usable" advisory — that
# advisory is expected here, not a bug. The CLI/bench entry points
# (and pytest.ini) filter it at the APPLICATION level; this library
# module deliberately does not mutate the process-global warning
# filters, so embedders keep the signal for their own jax code.

MAX_INTERVALS = 4          # per side; host falls back past this
NEG_INF = -(2 ** 31) + 1
POS_INF = 2 ** 31 - 1


def interval_hits_impl(pkg_rank: jax.Array, vuln_lo: jax.Array,
                       vuln_hi: jax.Array, sec_lo: jax.Array,
                       sec_hi: jax.Array,
                       flags: jax.Array) -> jax.Array:
    """[P] ranks × [P, M] interval tables → [P] bool vulnerable."""
    r = pkg_rank[:, None]
    vuln_any = ((vuln_lo <= r) & (r <= vuln_hi)).any(axis=1)
    sec_any = ((sec_lo <= r) & (r <= sec_hi)).any(axis=1)

    has_vuln = (flags & 1).astype(bool)
    force = (flags & 2).astype(bool)
    has_sec = (flags & 4).astype(bool)

    not_sec = jnp.where(has_sec, ~sec_any, True)
    with_vuln = vuln_any & not_sec
    without_vuln = jnp.where(has_sec, ~sec_any, False)
    return force | jnp.where(has_vuln, with_vuln, without_vuln)


interval_hits = jax.jit(interval_hits_impl)

# donated variant for the async slot runtime (docs/performance.md
# "Async device runtime"): every operand is a PER-BATCH payload
# buffer staged into a dispatch-ring slot, so the kernel may reuse
# the slot's HBM for its output — collect frees the slot for the
# next upload instead of holding two copies alive per in-flight
# batch. Callers must device_put fresh buffers per dispatch and
# never touch them again (the arrays are deleted after the call).
interval_hits_donated = jax.jit(interval_hits_impl,
                                donate_argnums=(0, 1, 2, 3, 4, 5))


def interval_hits_resident_impl(pkg_rank: jax.Array,
                                row_idx: jax.Array,
                                vuln_lo: jax.Array, vuln_hi: jax.Array,
                                sec_lo: jax.Array, sec_hi: jax.Array,
                                flags: jax.Array) -> jax.Array:
    """Resident-table variant: the [N, M] advisory tables live in HBM
    across scans (compiled once at DB load — SURVEY §7 step 5); each
    dispatch gathers only the candidate rows. [P] pkg ranks + [P] row
    indices → [P] bool."""
    return interval_hits_impl(pkg_rank, vuln_lo[row_idx],
                              vuln_hi[row_idx], sec_lo[row_idx],
                              sec_hi[row_idx], flags[row_idx])


interval_hits_resident = jax.jit(interval_hits_resident_impl)

# resident variant: ONLY the per-batch gather operands (pkg ranks +
# candidate row indices) are donated — argnums 2..6 are the
# HBM-resident advisory tables shared by every dispatch of a DB
# generation, and donating one would free the store under every
# concurrent scanner (the buffer-donation audit's hard rule:
# payload buffers yes, resident tables never).
interval_hits_resident_donated = jax.jit(
    interval_hits_resident_impl, donate_argnums=(0, 1))


def interval_hits_host(pkg_rank, vuln_lo, vuln_hi, sec_lo, sec_hi,
                       flags):
    """NumPy reference (differential testing)."""
    import numpy as np
    r = pkg_rank[:, None]
    vuln_any = ((vuln_lo <= r) & (r <= vuln_hi)).any(axis=1)
    sec_any = ((sec_lo <= r) & (r <= sec_hi)).any(axis=1)
    has_vuln = (flags & 1).astype(bool)
    force = (flags & 2).astype(bool)
    has_sec = (flags & 4).astype(bool)
    not_sec = np.where(has_sec, ~sec_any, True)
    without_vuln = np.where(has_sec, ~sec_any, False)
    return force | np.where(has_vuln, vuln_any & not_sec,
                            without_vuln)
