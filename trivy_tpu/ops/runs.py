"""Class-run detection on TPU — the run-gate sieve stage.

For each spec (byte class, run length R): does the segment contain R
consecutive bytes all in the class? Pure elementwise membership
compares + log-doubling erosion (AND of left-shifted masks) — the same
no-gather discipline as the literal sieve.

Segments must overlap by ≥ max run length so straddling runs appear
whole in one segment (trivy_tpu.secret.batch sizes the overlap from
the plan).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class RunSpec:
    ranges: tuple        # ((lo, hi), ...) inclusive byte ranges
    runlen: int

    @classmethod
    def from_byteset(cls, bs: frozenset, runlen: int) -> "RunSpec":
        ranges = []
        for b in sorted(bs):
            if ranges and b == ranges[-1][1] + 1:
                ranges[-1][1] = b
            else:
                ranges.append([b, b])
        return cls(ranges=tuple((lo, hi) for lo, hi in ranges),
                   runlen=runlen)


def _membership(x: jax.Array, spec: RunSpec) -> jax.Array:
    m = jnp.zeros(x.shape, bool)
    for lo, hi in spec.ranges:
        if lo == hi:
            m = m | (x == lo)
        else:
            m = m | ((x >= lo) & (x <= hi))
    return m


def _erode(m: jax.Array, R: int) -> jax.Array:
    """e[i] = AND of m[i..i+R-1] (log-doubling shifts)."""
    e = m
    span = 1
    while span < R:
        step = min(span, R - span)
        shifted = jnp.pad(e[:, step:], ((0, 0), (0, step)))
        e = e & shifted
        span += step
    return e


def run_hits_impl(segments: jax.Array, specs: tuple) -> jax.Array:
    """Unjitted [B, L] → [B, n_specs] bool run detector body (so the
    batch scanner can fuse it with the literal sieve into one
    dispatch over a device-resident segment buffer)."""
    x = segments.astype(jnp.int32)
    cols = []
    for spec in specs:
        m = _membership(x, spec)
        cols.append(_erode(m, spec.runlen).any(axis=1))
    return jnp.stack(cols, axis=1)


@functools.lru_cache(maxsize=16)
def make_run_hits(specs: tuple):
    """Compile a jitted [B, L] → [B, n_specs] bool run detector.
    Cached on the (hashable) spec tuple so every scanner instance
    built from the same rule set shares one compiled kernel."""

    @jax.jit
    def run_hits(segments: jax.Array) -> jax.Array:
        return run_hits_impl(segments, specs)

    return run_hits


def run_hits_host(segments: np.ndarray, specs: tuple) -> np.ndarray:
    """NumPy reference."""
    B, L = segments.shape
    out = np.zeros((B, len(specs)), bool)
    x = segments.astype(np.int32)
    for si, spec in enumerate(specs):
        m = np.zeros_like(x, bool)
        for lo, hi in spec.ranges:
            m |= (x >= lo) & (x <= hi)
        e = m
        span = 1
        while span < spec.runlen:
            step = min(span, spec.runlen - span)
            shifted = np.pad(e[:, step:], ((0, 0), (0, step)))
            e = e & shifted
            span += step
        out[:, si] = e.any(axis=1)
    return out
