"""Pallas TPU kernel for the literal blockmask sieve.

The XLA `lax.scan` formulation of trivy_tpu.ops.keywords re-reads the
[B, L] window-word arrays from HBM on every code chunk (~26 ms/chunk
measured). This kernel reads each segment tile ONCE into VMEM, builds
the sliding-window words in registers, then loops all K codes over the
resident tile — HBM traffic drops from K/8 × 2×4L×B to 1 × L×B bytes.

Layout:
  grid           = (B // TILE_B,)
  segments block = [TILE_B, L] uint8 in VMEM
  codes          = 4 × [Kp] uint32, scalar-prefetched to SMEM
  out block      = [TILE_B, Kp] uint32 — masks for 128 codes at a time
                   accumulate in registers via lane-select (dynamic
                   lane stores must be 128-aligned), one store per
                   128-code group

Out bit j of word [k, b] = code k matched somewhere in 128-byte block j
of segment b (N_BLOCKS = 16 blocks over L = 2048).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .keywords import N_BLOCKS

TILE_B = 128


def _kernel(lo_ref, hi_ref, lom_ref, him_ref, seg_ref, out_ref):
    x = seg_ref[:].astype(jnp.uint32)                    # [bT, L]
    bT, L = x.shape
    is_upper = (x >= 65) & (x <= 90)
    x = jnp.where(is_upper, x + 32, x)

    col = jax.lax.broadcasted_iota(jnp.int32, (bT, L), 1)

    def shifted(i):
        if i == 0:
            return x
        r = pltpu.roll(x, L - i, 1)    # circular left-shift by i
        return jnp.where(col < L - i, r, jnp.uint32(0))

    lo = (shifted(0) | (shifted(1) << 8) | (shifted(2) << 16)
          | (shifted(3) << 24))
    hi = (shifted(4) | (shifted(5) << 8) | (shifted(6) << 16)
          | (shifted(7) << 24))

    K = out_ref.shape[1]
    blk = L // N_BLOCKS

    # block-membership indicator: position p belongs to block p // blk.
    # The per-code block reduction rides the MXU as [bT,L] @ [L,16]
    # (hit counts are exact in f32: ≤ blk = 128 ones per block).
    pos_blk = jax.lax.broadcasted_iota(jnp.int32, (L, N_BLOCKS), 0) \
        // blk
    blk_id = jax.lax.broadcasted_iota(jnp.int32, (L, N_BLOCKS), 1)
    ind = (pos_blk == blk_id).astype(jnp.float32)         # [L, 16]
    bit_val = (jnp.int32(1) << jax.lax.broadcasted_iota(
        jnp.int32, (bT, N_BLOCKS), 1))
    lane = jax.lax.broadcasted_iota(jnp.int32, (bT, 128), 1)

    # dynamic-lane stores must be 128-aligned on TPU, so masks for 128
    # codes accumulate in registers (lane-select) and store as one tile
    for g in range(K // 128):
        def body(j, acc, g=g):
            k = g * 128 + j
            klo = lo_ref[k]
            khi = hi_ref[k]
            mlo = lom_ref[k]
            mhi = him_ref[k]
            hit = ((lo & mlo) == klo) & ((hi & mhi) == khi)  # [bT, L]
            counts = jnp.dot(hit.astype(jnp.float32), ind,
                             preferred_element_type=jnp.float32)
            mask = jnp.sum(jnp.where(counts > 0, bit_val, 0),
                           axis=1, keepdims=True)            # [bT, 1]
            return jnp.where(lane == j, mask, acc)

        acc = jax.lax.fori_loop(
            0, 128, body, jnp.zeros((bT, 128), jnp.int32))
        out_ref[:, g * 128:(g + 1) * 128] = acc.astype(jnp.uint32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def code_blockmask_pallas(segments: jax.Array, lo_c: jax.Array,
                          hi_c: jax.Array, lo_m: jax.Array,
                          hi_m: jax.Array,
                          interpret: bool = False) -> jax.Array:
    """[B, L] uint8 × K codes → [B, K] uint32 blockmasks.

    B must be a multiple of TILE_B and L a multiple of N_BLOCKS×128
    (callers bucket-pad — ops.keywords.pad_batch)."""
    B, L = segments.shape
    K0 = lo_c.shape[0]
    assert B % TILE_B == 0 and L % 128 == 0

    K = ((K0 + 127) // 128) * 128
    if K != K0:
        pad = K - K0
        z = jnp.zeros(pad, jnp.uint32)
        f = jnp.full(pad, 0xFFFFFFFF, jnp.uint32)
        lo_c = jnp.concatenate([lo_c.astype(jnp.uint32), z])
        hi_c = jnp.concatenate([hi_c.astype(jnp.uint32), z])
        lo_m = jnp.concatenate([lo_m.astype(jnp.uint32), f])
        hi_m = jnp.concatenate([hi_m.astype(jnp.uint32), f])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B // TILE_B,),
        in_specs=[
            pl.BlockSpec((TILE_B, L), lambda i, *_: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE_B, K), lambda i, *_: (i, 0),
                               memory_space=pltpu.VMEM),
    )
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((B, K), jnp.uint32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(lo_c.astype(jnp.uint32), hi_c.astype(jnp.uint32),
      lo_m.astype(jnp.uint32), hi_m.astype(jnp.uint32), segments)
    return out[:, :K0]
