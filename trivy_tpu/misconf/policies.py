"""Built-in misconfiguration policies.

Python re-implementations of the best-known defsec built-in checks
(IDs/AVD IDs/titles/severities are the compat contract — the
reference embeds these in defsec's Go checks). Each policy's
``check(doc)`` returns a list of Causes; empty list = pass.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from functools import partial
from typing import Callable

from .dockerfile import Stage


@dataclass
class Cause:
    message: str
    start_line: int = 0
    end_line: int = 0
    resource: str = ""
    file_path: str = ""      # module-scoped checks (terraform) set it


@dataclass
class Policy:
    id: str
    avd_id: str
    title: str
    description: str
    severity: str
    recommended_actions: str
    references: list
    provider: str
    service: str
    check: Callable          # (parsed doc) -> list[Cause]
    success_message: str = "No issues found"
    # custom policies (--config-policy) declare which parsed inputs
    # their check understands: dockerfile | kubernetes | terraform |
    # cloudformation | helm
    file_types: tuple = ()


# ------------------------------------------------------------ dockerfile


def _last_user(stage: Stage):
    user = None
    for inst in stage.instructions:
        if inst.cmd == "USER":
            user = inst
    return user


def _check_root_user(stages: list) -> list:
    """The FINAL stage decides who runs the container."""
    if not stages:
        return []
    stage = stages[-1]
    user = _last_user(stage)
    if user is None:
        line = max(1, stage.start_line)
        return [Cause(
            message="Specify at least 1 USER command in Dockerfile "
            "with non-root user as argument",
            start_line=line, end_line=line)]
    if user.value.split(":")[0] in ("root", "0"):
        return [Cause(
            message="Last USER command in Dockerfile should not be "
            f"'root' but it is {user.value!r}",
            start_line=user.start_line, end_line=user.end_line)]
    return []


def _check_latest_tag(stages: list) -> list:
    causes = []
    earlier_stages = set()
    for stage in stages:
        base = stage.base
        if base and base not in earlier_stages and \
                not base.startswith("$") and "@" not in base:
            # tag = whatever follows ':' in the last path segment
            segment = base.rsplit("/", 1)[-1]
            _, sep, tag = segment.partition(":")
            if not sep or tag == "latest":
                causes.append(Cause(
                    message="Specify a tag in the 'FROM' statement "
                    f"for image '{segment.split(':')[0]}'",
                    start_line=stage.start_line,
                    end_line=stage.start_line))
        if stage.alias:
            # only AS aliases are resolvable as later FROM targets
            earlier_stages.add(stage.alias)
    return causes


def _check_add(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "ADD":
                continue
            src = inst.value.split()[0] if inst.value.split() else ""
            # ADD is legitimate for remote URLs and auto-extraction
            if src.startswith(("http://", "https://")) or \
                    src.endswith((".tar", ".tar.gz", ".tgz",
                                  ".tar.bz2", ".tar.xz", ".zip")):
                continue
            causes.append(Cause(
                message=f"Consider using 'COPY {inst.value}' "
                "command instead of 'ADD' command",
                start_line=inst.start_line,
                end_line=inst.end_line))
    return causes


def _check_exposed_22(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd == "EXPOSE":
                for port in inst.value.split():
                    if port.split("/")[0] == "22":
                        causes.append(Cause(
                            message="Port 22 should not be exposed "
                            "in Dockerfile",
                            start_line=inst.start_line,
                            end_line=inst.end_line))
    return causes


def _check_healthcheck(stages: list) -> list:
    if any(inst.cmd == "HEALTHCHECK"
           for s in stages for inst in s.instructions):
        return []
    return [Cause(message="Add HEALTHCHECK instruction in your "
                  "Dockerfile", start_line=1, end_line=1)]


DOCKERFILE_POLICIES = [
    Policy(id="DS001", avd_id="AVD-DS-0001",
           title="':latest' tag used",
           description="When using a 'FROM' statement you should use "
           "a specific tag to avoid uncontrolled behavior when the "
           "image is updated.",
           severity="MEDIUM",
           recommended_actions="Add a tag to the image in the 'FROM' "
           "statement",
           references=["https://avd.aquasec.com/misconfig/ds001"],
           provider="Dockerfile", service="general",
           check=_check_latest_tag),
    Policy(id="DS002", avd_id="AVD-DS-0002",
           title="Image user should not be 'root'",
           description="Running containers with 'root' user can lead "
           "to a container escape situation. It is a best practice "
           "to run containers as non-root users, which can be done "
           "by adding a 'USER' statement to the Dockerfile.",
           severity="HIGH",
           recommended_actions="Add 'USER <non root user name>' line "
           "to the Dockerfile",
           references=["https://docs.docker.com/develop/"
                       "develop-images/dockerfile_best-practices/",
                       "https://avd.aquasec.com/misconfig/ds002"],
           provider="Dockerfile", service="general",
           check=_check_root_user),
    Policy(id="DS004", avd_id="AVD-DS-0004",
           title="Port 22 exposed",
           description="Exposing port 22 might allow users to SSH "
           "into the container.",
           severity="MEDIUM",
           recommended_actions="Remove 'EXPOSE 22' statement from "
           "the Dockerfile",
           references=["https://avd.aquasec.com/misconfig/ds004"],
           provider="Dockerfile", service="general",
           check=_check_exposed_22),
    Policy(id="DS005", avd_id="AVD-DS-0005",
           title="ADD instead of COPY",
           description="You should use COPY instead of ADD unless "
           "you want to extract a tar file. Note that an ADD command "
           "will extract a tar file, which adds the risk of Zip-based "
           "vulnerabilities. Accordingly, it is advised to use a COPY "
           "command, which does not extract tar files.",
           severity="LOW",
           recommended_actions="Use COPY instead of ADD",
           references=["https://avd.aquasec.com/misconfig/ds005"],
           provider="Dockerfile", service="general",
           check=_check_add),
    Policy(id="DS026", avd_id="AVD-DS-0026",
           title="No HEALTHCHECK defined",
           description="You should add HEALTHCHECK instruction in "
           "your docker container images to perform the health check "
           "on running containers.",
           severity="LOW",
           recommended_actions="Add HEALTHCHECK instruction in "
           "Dockerfile",
           references=["https://avd.aquasec.com/misconfig/ds026"],
           provider="Dockerfile", service="general",
           check=_check_healthcheck),
]


# ------------------------------------------------------------ kubernetes


def _k8s_containers(doc: dict):
    spec = doc.get("spec") or {}
    # workloads nest pod specs under template
    tmpl = (spec.get("template") or {}).get("spec") or {}
    pod = tmpl or spec
    for kind in ("initContainers", "containers"):
        for c in pod.get(kind) or []:
            yield c, pod


def _k8s_check_privileged(doc: dict) -> list:
    causes = []
    for c, _ in _k8s_containers(doc):
        sc = c.get("securityContext") or {}
        if sc.get("privileged"):
            causes.append(Cause(
                message=f"Container {c.get('name', '?')!r} of "
                f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should set 'securityContext.privileged' to false",
                resource=c.get("name", "")))
    return causes


def _k8s_check_priv_escalation(doc: dict) -> list:
    causes = []
    for c, _ in _k8s_containers(doc):
        sc = c.get("securityContext") or {}
        if sc.get("allowPrivilegeEscalation", True):
            causes.append(Cause(
                message=f"Container {c.get('name', '?')!r} of "
                f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should set "
                "'securityContext.allowPrivilegeEscalation' to false",
                resource=c.get("name", "")))
    return causes


def _k8s_check_run_as_nonroot(doc: dict) -> list:
    causes = []
    for c, pod in _k8s_containers(doc):
        csc = c.get("securityContext") or {}
        psc = pod.get("securityContext") or {}
        # container-level setting overrides the pod-level one
        effective = csc.get("runAsNonRoot")
        if effective is None:
            effective = psc.get("runAsNonRoot")
        if not effective:
            causes.append(Cause(
                message=f"Container {c.get('name', '?')!r} of "
                f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should set 'securityContext.runAsNonRoot' to true",
                resource=c.get("name", "")))
    return causes


def _k8s_check_readonly_rootfs(doc: dict) -> list:
    causes = []
    for c, _ in _k8s_containers(doc):
        sc = c.get("securityContext") or {}
        if not sc.get("readOnlyRootFilesystem"):
            causes.append(Cause(
                message=f"Container {c.get('name', '?')!r} of "
                f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should set 'securityContext."
                "readOnlyRootFilesystem' to true",
                resource=c.get("name", "")))
    return causes


def _k8s_check_run_as_root_group(doc: dict) -> list:
    """KSV029: explicit root primary (runAsGroup/fsGroup 0) or
    supplementary (supplementalGroups containing 0) GID."""
    causes = []
    for c, pod in _k8s_containers(doc):
        csc = c.get("securityContext") or {}
        psc = pod.get("securityContext") or {}
        group = csc.get("runAsGroup", psc.get("runAsGroup"))
        fs_group = psc.get("fsGroup")
        supplemental = psc.get("supplementalGroups") or []
        if group == 0 or fs_group == 0 or 0 in supplemental:
            causes.append(Cause(
                message=f"Container {c.get('name', '?')!r} of "
                f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should not set 'securityContext.runAsGroup' or "
                "'fsGroup' to 0",
                resource=c.get("name", "")))
    return causes


def _k8s_check_docker_sock(doc: dict) -> list:
    causes = []
    spec = doc.get("spec") or {}
    pod = (spec.get("template") or {}).get("spec") or spec
    for vol in pod.get("volumes") or []:
        host_path = (vol.get("hostPath") or {}).get("path", "")
        if host_path.rstrip("/") == "/var/run/docker.sock":
            causes.append(Cause(
                message=f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should not mount '/var/run/docker.sock'",
                resource=vol.get("name", "")))
    return causes


KUBERNETES_POLICIES = [
    Policy(id="KSV001", avd_id="AVD-KSV-0001",
           title="Process can elevate its own privileges",
           description="A program inside the container can elevate "
           "its own privileges and run as root, which might give the "
           "program control over the container and node.",
           severity="MEDIUM",
           recommended_actions="Set 'set containers[].securityContext"
           ".allowPrivilegeEscalation' to 'false'.",
           references=["https://avd.aquasec.com/misconfig/ksv001"],
           provider="Kubernetes", service="general",
           check=_k8s_check_priv_escalation),
    Policy(id="KSV006", avd_id="AVD-KSV-0006",
           title="hostPath volume mounted with docker.sock",
           description="Mounting docker.sock from the host can give "
           "the container full root access to the host.",
           severity="HIGH",
           recommended_actions="Do not specify /var/run/docker.sock "
           "in 'spec.template.volumes.hostPath.path'.",
           references=["https://avd.aquasec.com/misconfig/ksv006"],
           provider="Kubernetes", service="general",
           check=_k8s_check_docker_sock),
    Policy(id="KSV012", avd_id="AVD-KSV-0012",
           title="Runs as root user",
           description="'runAsNonRoot' forces the running image to "
           "run as a non-root user to ensure least privileges.",
           severity="MEDIUM",
           recommended_actions="Set 'containers[].securityContext."
           "runAsNonRoot' to true.",
           references=["https://avd.aquasec.com/misconfig/ksv012"],
           provider="Kubernetes", service="general",
           check=_k8s_check_run_as_nonroot),
    Policy(id="KSV014", avd_id="AVD-KSV-0014",
           title="Root file system is not read-only",
           description="An immutable root file system prevents "
           "applications from writing to their local disk.",
           severity="LOW",
           recommended_actions="Change 'containers[].securityContext"
           ".readOnlyRootFilesystem' to 'true'.",
           references=["https://avd.aquasec.com/misconfig/ksv014"],
           provider="Kubernetes", service="general",
           check=_k8s_check_readonly_rootfs),
    Policy(id="KSV029", avd_id="AVD-KSV-0029",
           title="A root primary or supplementary GID set",
           description="Containers should be forbidden from running "
           "with a root primary or supplementary GID.",
           severity="LOW",
           recommended_actions="Set 'securityContext.runAsGroup' and "
           "'fsGroup' to a non-zero GID.",
           references=["https://avd.aquasec.com/misconfig/ksv029"],
           provider="Kubernetes", service="general",
           check=_k8s_check_run_as_root_group),
    Policy(id="KSV017", avd_id="AVD-KSV-0017",
           title="Privileged container",
           description="Privileged containers share namespaces with "
           "the host system and do not offer any security. They "
           "should be used exclusively for system containers.",
           severity="HIGH",
           recommended_actions="Change 'containers[].securityContext"
           ".privileged' to 'false'.",
           references=["https://avd.aquasec.com/misconfig/ksv017"],
           provider="Kubernetes", service="general",
           check=_k8s_check_privileged),
]


def _check_copy_from_self(stages: list) -> list:
    """DS006: COPY --from references the stage's own FROM alias."""
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "COPY":
                continue
            for flag in inst.flags:
                if flag.startswith("--from=") and stage.alias and \
                        flag[len("--from="):].lower() == \
                        stage.alias.lower():
                    causes.append(Cause(
                        message=f"'COPY {flag}' references the "
                        f"current image FROM alias "
                        f"{stage.alias!r}",
                        start_line=inst.start_line,
                        end_line=inst.end_line))
    return causes


def _check_duplicate(cmd: str, stages: list) -> list:
    """Per stage, every occurrence of ``cmd`` but the last is dead."""
    causes = []
    for stage in stages:
        insts = [i for i in stage.instructions if i.cmd == cmd]
        for inst in insts[:-1]:
            causes.append(Cause(
                message=f"There are multiple {cmd} instructions; "
                "only the last one takes effect",
                start_line=inst.start_line,
                end_line=inst.end_line))
    return causes


def _check_port_range(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "EXPOSE":
                continue
            for port in inst.value.split():
                num = port.split("/")[0]
                if num.isdigit() and int(num) > 65535:
                    causes.append(Cause(
                        message=f"'EXPOSE' contains port "
                        f"{num} which is out of range",
                        start_line=inst.start_line,
                        end_line=inst.end_line))
    return causes


def _check_workdir_relative(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "WORKDIR":
                continue
            path = inst.value.strip().strip("'\"")
            if path and not path.startswith(("/", "$", "C:",
                                             "c:")):
                causes.append(Cause(
                    message=f"WORKDIR path {path!r} should be "
                    "absolute",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


def _check_run_sudo(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd == "RUN" and re.search(
                    r"(^|\s|;|&&)sudo\s", " " + inst.value):
                causes.append(Cause(
                    message="Using 'sudo' in RUN is not supported "
                    "and indicates a misconfigured image",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


def _check_run_cd(stages: list) -> list:
    """DS013: use WORKDIR, not 'RUN cd ...' as the only command."""
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd == "RUN" and re.match(
                    r"^cd\s+\S+$", inst.value.strip()):
                causes.append(Cause(
                    message=f"RUN should not be used to change "
                    f"directories ('{inst.value}'); use WORKDIR",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


def _check_apt_install_y(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "RUN":
                continue
            for part in re.split(r"&&|;|\|", inst.value):
                tokens = part.split()
                if "apt-get" not in tokens and "apt" not in tokens:
                    continue
                if "install" not in tokens:
                    continue
                confirmed = any(
                    t in ("--yes", "--assume-yes") or
                    (t.startswith("-") and not t.startswith("--")
                     and "y" in t[1:])
                    for t in tokens)
                if not confirmed:
                    causes.append(Cause(
                        message="'-y' flag is missing from "
                        "'apt-get install' — the build will hang "
                        "on the confirmation prompt",
                        start_line=inst.start_line,
                        end_line=inst.end_line))
    return causes


def _check_apk_no_cache(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "RUN":
                continue
            for part in re.split(r"&&|;|\|", inst.value):
                tokens = part.split()
                if "apk" in tokens and "add" in tokens and \
                        "--no-cache" not in tokens:
                    causes.append(Cause(
                        message="'--no-cache' is missing from "
                        "'apk add' — the package index bloats the "
                        "image",
                        start_line=inst.start_line,
                        end_line=inst.end_line))
    return causes


def _check_maintainer(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd == "MAINTAINER":
                causes.append(Cause(
                    message=f"MAINTAINER is deprecated; use "
                    f"'LABEL maintainer=\"{inst.value}\"'",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


DOCKERFILE_POLICIES += [
    Policy(id="DS006", avd_id="AVD-DS-0006",
           title="COPY '--from' references current FROM alias",
           description="COPY '--from' should not mention the "
           "current FROM alias, since it is impossible to copy from "
           "itself.",
           severity="CRITICAL",
           recommended_actions="Change the '--from' so that it "
           "references a previous build stage",
           references=["https://avd.aquasec.com/misconfig/ds006"],
           provider="Dockerfile", service="general",
           check=_check_copy_from_self),
    Policy(id="DS007", avd_id="AVD-DS-0007",
           title="Multiple ENTRYPOINT instructions listed",
           description="There can only be one ENTRYPOINT "
           "instruction in a Dockerfile; only the last one takes "
           "effect.",
           severity="CRITICAL",
           recommended_actions="Remove unnecessary ENTRYPOINT "
           "instructions",
           references=["https://avd.aquasec.com/misconfig/ds007"],
           provider="Dockerfile", service="general",
           check=partial(_check_duplicate, "ENTRYPOINT")),
    Policy(id="DS008", avd_id="AVD-DS-0008",
           title="Port out of range",
           description="UNIX ports outside the 0-65535 range are "
           "invalid.",
           severity="CRITICAL",
           recommended_actions="Use a port number within the range",
           references=["https://avd.aquasec.com/misconfig/ds008"],
           provider="Dockerfile", service="general",
           check=_check_port_range),
    Policy(id="DS009", avd_id="AVD-DS-0009",
           title="WORKDIR path not absolute",
           description="For clarity and reliability, you should "
           "always use absolute paths for your WORKDIR.",
           severity="HIGH",
           recommended_actions="Use an absolute path in WORKDIR",
           references=["https://avd.aquasec.com/misconfig/ds009"],
           provider="Dockerfile", service="general",
           check=_check_workdir_relative),
    Policy(id="DS010", avd_id="AVD-DS-0010",
           title="RUN using 'sudo'",
           description="Avoid using 'sudo' in RUN: it has "
           "unpredictable TTY and signal-forwarding behavior.",
           severity="CRITICAL",
           recommended_actions="Don't use sudo; switch users with "
           "USER",
           references=["https://avd.aquasec.com/misconfig/ds010"],
           provider="Dockerfile", service="general",
           check=_check_run_sudo),
    Policy(id="DS013", avd_id="AVD-DS-0013",
           title="'RUN cd ...' to change directory",
           description="Use WORKDIR instead of proliferating "
           "'RUN cd ...' instructions, which are hard to read and "
           "maintain.",
           severity="MEDIUM",
           recommended_actions="Use WORKDIR to change directories",
           references=["https://avd.aquasec.com/misconfig/ds013"],
           provider="Dockerfile", service="general",
           check=_check_run_cd),
    Policy(id="DS016", avd_id="AVD-DS-0016",
           title="Multiple CMD instructions listed",
           description="There can only be one CMD instruction in a "
           "Dockerfile; only the last one takes effect.",
           severity="HIGH",
           recommended_actions="Remove unnecessary CMD instructions",
           references=["https://avd.aquasec.com/misconfig/ds016"],
           provider="Dockerfile", service="general",
           check=partial(_check_duplicate, "CMD")),
    Policy(id="DS017", avd_id="AVD-DS-0017",
           title="'apt-get install' missing '-y'",
           description="Without '-y', apt-get waits for manual "
           "confirmation and the build hangs.",
           severity="HIGH",
           recommended_actions="Add '-y' to 'apt-get install'",
           references=["https://avd.aquasec.com/misconfig/ds017"],
           provider="Dockerfile", service="general",
           check=_check_apt_install_y),
    Policy(id="DS022", avd_id="AVD-DS-0022",
           title="MAINTAINER is deprecated",
           description="The MAINTAINER instruction is deprecated "
           "since Docker 1.13.0.",
           severity="LOW",
           recommended_actions="Use LABEL maintainer=... instead",
           references=["https://avd.aquasec.com/misconfig/ds022"],
           provider="Dockerfile", service="general",
           check=_check_maintainer),
    Policy(id="DS023", avd_id="AVD-DS-0023",
           title="Multiple HEALTHCHECK instructions listed",
           description="There can only be one HEALTHCHECK "
           "instruction in a Dockerfile; only the last one takes "
           "effect.",
           severity="MEDIUM",
           recommended_actions="Remove unnecessary HEALTHCHECK "
           "instructions",
           references=["https://avd.aquasec.com/misconfig/ds023"],
           provider="Dockerfile", service="general",
           check=partial(_check_duplicate, "HEALTHCHECK")),
    Policy(id="DS025", avd_id="AVD-DS-0025",
           title="'apk add' missing '--no-cache'",
           description="Cached package indexes bloat the image; "
           "'apk add --no-cache' avoids them.",
           severity="HIGH",
           recommended_actions="Add '--no-cache' to 'apk add'",
           references=["https://avd.aquasec.com/misconfig/ds025"],
           provider="Dockerfile", service="general",
           check=_check_apk_no_cache),
]
