"""Built-in misconfiguration policies.

Python re-implementations of the best-known defsec built-in checks
(IDs/AVD IDs/titles/severities are the compat contract — the
reference embeds these in defsec's Go checks). Each policy's
``check(doc)`` returns a list of Causes; empty list = pass.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from functools import partial
from typing import Callable

from .dockerfile import Stage


@dataclass
class Cause:
    message: str
    start_line: int = 0
    end_line: int = 0
    resource: str = ""
    file_path: str = ""      # module-scoped checks (terraform) set it


@dataclass
class Policy:
    id: str
    avd_id: str
    title: str
    description: str
    severity: str
    recommended_actions: str
    references: list
    provider: str
    service: str
    check: Callable          # (parsed doc) -> list[Cause]
    success_message: str = "No issues found"
    # custom policies (--config-policy) declare which parsed inputs
    # their check understands: dockerfile | kubernetes | terraform |
    # cloudformation | helm
    file_types: tuple = ()


# ------------------------------------------------------------ dockerfile


def _last_user(stage: Stage):
    user = None
    for inst in stage.instructions:
        if inst.cmd == "USER":
            user = inst
    return user


def _check_root_user(stages: list) -> list:
    """The FINAL stage decides who runs the container."""
    if not stages:
        return []
    stage = stages[-1]
    user = _last_user(stage)
    if user is None:
        # a missing USER is a whole-file finding with no location
        # (dockerfile.json.golden: CauseMetadata carries no
        # Start/EndLine for DS002)
        return [Cause(
            message="Specify at least 1 USER command in Dockerfile "
            "with non-root user as argument")]
    if user.value.split(":")[0] in ("root", "0"):
        return [Cause(
            message="Last USER command in Dockerfile should not be "
            f"'root' but it is {user.value!r}",
            start_line=user.start_line, end_line=user.end_line)]
    return []


def _check_latest_tag(stages: list) -> list:
    causes = []
    earlier_stages = set()
    for stage in stages:
        base = stage.base
        if base and base not in earlier_stages and \
                not base.startswith("$") and "@" not in base:
            # tag = whatever follows ':' in the last path segment
            segment = base.rsplit("/", 1)[-1]
            _, sep, tag = segment.partition(":")
            if not sep or tag == "latest":
                causes.append(Cause(
                    message="Specify a tag in the 'FROM' statement "
                    f"for image '{segment.split(':')[0]}'",
                    start_line=stage.start_line,
                    end_line=stage.start_line))
        if stage.alias:
            # only AS aliases are resolvable as later FROM targets
            earlier_stages.add(stage.alias)
    return causes


def _check_add(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "ADD":
                continue
            src = inst.value.split()[0] if inst.value.split() else ""
            # ADD is legitimate for remote URLs and auto-extraction
            if src.startswith(("http://", "https://")) or \
                    src.endswith((".tar", ".tar.gz", ".tgz",
                                  ".tar.bz2", ".tar.xz", ".zip")):
                continue
            causes.append(Cause(
                message=f"Consider using 'COPY {inst.value}' "
                "command instead of 'ADD' command",
                start_line=inst.start_line,
                end_line=inst.end_line))
    return causes


def _check_exposed_22(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd == "EXPOSE":
                for port in inst.value.split():
                    if port.split("/")[0] == "22":
                        causes.append(Cause(
                            message="Port 22 should not be exposed "
                            "in Dockerfile",
                            start_line=inst.start_line,
                            end_line=inst.end_line))
    return causes


def _check_healthcheck(stages: list) -> list:
    if any(inst.cmd == "HEALTHCHECK"
           for s in stages for inst in s.instructions):
        return []
    return [Cause(message="Add HEALTHCHECK instruction in your "
                  "Dockerfile", start_line=1, end_line=1)]


DOCKERFILE_POLICIES = [
    Policy(id="DS001", avd_id="AVD-DS-0001",
           title="':latest' tag used",
           description="When using a 'FROM' statement you should use "
           "a specific tag to avoid uncontrolled behavior when the "
           "image is updated.",
           severity="MEDIUM",
           recommended_actions="Add a tag to the image in the 'FROM' "
           "statement",
           references=["https://avd.aquasec.com/misconfig/ds001"],
           provider="Dockerfile", service="general",
           check=_check_latest_tag),
    Policy(id="DS002", avd_id="AVD-DS-0002",
           title="Image user should not be 'root'",
           description="Running containers with 'root' user can lead "
           "to a container escape situation. It is a best practice "
           "to run containers as non-root users, which can be done "
           "by adding a 'USER' statement to the Dockerfile.",
           severity="HIGH",
           recommended_actions="Add 'USER <non root user name>' line "
           "to the Dockerfile",
           references=["https://docs.docker.com/develop/"
                       "develop-images/dockerfile_best-practices/",
                       "https://avd.aquasec.com/misconfig/ds002"],
           provider="Dockerfile", service="general",
           check=_check_root_user),
    Policy(id="DS004", avd_id="AVD-DS-0004",
           title="Port 22 exposed",
           description="Exposing port 22 might allow users to SSH "
           "into the container.",
           severity="MEDIUM",
           recommended_actions="Remove 'EXPOSE 22' statement from "
           "the Dockerfile",
           references=["https://avd.aquasec.com/misconfig/ds004"],
           provider="Dockerfile", service="general",
           check=_check_exposed_22),
    Policy(id="DS005", avd_id="AVD-DS-0005",
           title="ADD instead of COPY",
           description="You should use COPY instead of ADD unless "
           "you want to extract a tar file. Note that an ADD command "
           "will extract a tar file, which adds the risk of Zip-based "
           "vulnerabilities. Accordingly, it is advised to use a COPY "
           "command, which does not extract tar files.",
           severity="LOW",
           recommended_actions="Use COPY instead of ADD",
           references=["https://avd.aquasec.com/misconfig/ds005"],
           provider="Dockerfile", service="general",
           check=_check_add),
]

# DS026 (no HEALTHCHECK) exists in later defsec but NOT in this
# reference vintage's embedded set: dockerfile.json.golden evaluates
# exactly 22 checks and passes a HEALTHCHECK-less Dockerfile, so
# registering it would break count and verdict parity. The check
# function (_check_healthcheck) stays for custom policy reuse.


# ------------------------------------------------------------ kubernetes


def _k8s_containers(doc: dict):
    spec = doc.get("spec") or {}
    # workloads nest pod specs under template
    tmpl = (spec.get("template") or {}).get("spec") or {}
    pod = tmpl or spec
    for kind in ("initContainers", "containers"):
        for c in pod.get(kind) or []:
            yield c, pod


def _k8s_check_privileged(doc: dict) -> list:
    causes = []
    for c, _ in _k8s_containers(doc):
        sc = c.get("securityContext") or {}
        if sc.get("privileged"):
            causes.append(Cause(
                message=f"Container {c.get('name', '?')!r} of "
                f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should set 'securityContext.privileged' to false",
                resource=c.get("name", "")))
    return causes


def _k8s_check_priv_escalation(doc: dict) -> list:
    causes = []
    for c, _ in _k8s_containers(doc):
        sc = c.get("securityContext") or {}
        if sc.get("allowPrivilegeEscalation", True):
            causes.append(Cause(
                message=f"Container {c.get('name', '?')!r} of "
                f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should set "
                "'securityContext.allowPrivilegeEscalation' to false",
                resource=c.get("name", "")))
    return causes


def _k8s_check_run_as_nonroot(doc: dict) -> list:
    causes = []
    for c, pod in _k8s_containers(doc):
        csc = c.get("securityContext") or {}
        psc = pod.get("securityContext") or {}
        # container-level setting overrides the pod-level one
        effective = csc.get("runAsNonRoot")
        if effective is None:
            effective = psc.get("runAsNonRoot")
        if not effective:
            causes.append(Cause(
                message=f"Container {c.get('name', '?')!r} of "
                f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should set 'securityContext.runAsNonRoot' to true",
                resource=c.get("name", "")))
    return causes


def _k8s_check_readonly_rootfs(doc: dict) -> list:
    causes = []
    for c, _ in _k8s_containers(doc):
        sc = c.get("securityContext") or {}
        if not sc.get("readOnlyRootFilesystem"):
            causes.append(Cause(
                message=f"Container {c.get('name', '?')!r} of "
                f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should set 'securityContext."
                "readOnlyRootFilesystem' to true",
                resource=c.get("name", "")))
    return causes


def _k8s_check_run_as_root_group(doc: dict) -> list:
    """KSV029: explicit root primary (runAsGroup/fsGroup 0) or
    supplementary (supplementalGroups containing 0) GID."""
    causes = []
    for c, pod in _k8s_containers(doc):
        csc = c.get("securityContext") or {}
        psc = pod.get("securityContext") or {}
        group = csc.get("runAsGroup", psc.get("runAsGroup"))
        fs_group = psc.get("fsGroup")
        supplemental = psc.get("supplementalGroups") or []
        if group == 0 or fs_group == 0 or 0 in supplemental:
            causes.append(Cause(
                message=f"Container {c.get('name', '?')!r} of "
                f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should not set 'securityContext.runAsGroup' or "
                "'fsGroup' to 0",
                resource=c.get("name", "")))
    return causes


def _k8s_check_docker_sock(doc: dict) -> list:
    causes = []
    spec = doc.get("spec") or {}
    pod = (spec.get("template") or {}).get("spec") or spec
    for vol in pod.get("volumes") or []:
        host_path = (vol.get("hostPath") or {}).get("path", "")
        if host_path.rstrip("/") == "/var/run/docker.sock":
            causes.append(Cause(
                message=f"{doc.get('kind', '?')} "
                f"{(doc.get('metadata') or {}).get('name', '?')!r} "
                "should not mount '/var/run/docker.sock'",
                resource=vol.get("name", "")))
    return causes


KUBERNETES_POLICIES = [
    Policy(id="KSV001", avd_id="AVD-KSV-0001",
           title="Process can elevate its own privileges",
           description="A program inside the container can elevate "
           "its own privileges and run as root, which might give the "
           "program control over the container and node.",
           severity="MEDIUM",
           recommended_actions="Set 'set containers[].securityContext"
           ".allowPrivilegeEscalation' to 'false'.",
           references=["https://avd.aquasec.com/misconfig/ksv001"],
           provider="Kubernetes", service="general",
           check=_k8s_check_priv_escalation),
    Policy(id="KSV006", avd_id="AVD-KSV-0006",
           title="hostPath volume mounted with docker.sock",
           description="Mounting docker.sock from the host can give "
           "the container full root access to the host.",
           severity="HIGH",
           recommended_actions="Do not specify /var/run/docker.sock "
           "in 'spec.template.volumes.hostPath.path'.",
           references=["https://avd.aquasec.com/misconfig/ksv006"],
           provider="Kubernetes", service="general",
           check=_k8s_check_docker_sock),
    Policy(id="KSV012", avd_id="AVD-KSV-0012",
           title="Runs as root user",
           description="'runAsNonRoot' forces the running image to "
           "run as a non-root user to ensure least privileges.",
           severity="MEDIUM",
           recommended_actions="Set 'containers[].securityContext."
           "runAsNonRoot' to true.",
           references=["https://avd.aquasec.com/misconfig/ksv012"],
           provider="Kubernetes", service="general",
           check=_k8s_check_run_as_nonroot),
    Policy(id="KSV014", avd_id="AVD-KSV-0014",
           title="Root file system is not read-only",
           description="An immutable root file system prevents "
           "applications from writing to their local disk.",
           severity="LOW",
           recommended_actions="Change 'containers[].securityContext"
           ".readOnlyRootFilesystem' to 'true'.",
           references=["https://avd.aquasec.com/misconfig/ksv014"],
           provider="Kubernetes", service="general",
           check=_k8s_check_readonly_rootfs),
    Policy(id="KSV029", avd_id="AVD-KSV-0029",
           title="A root primary or supplementary GID set",
           description="Containers should be forbidden from running "
           "with a root primary or supplementary GID.",
           severity="LOW",
           recommended_actions="Set 'securityContext.runAsGroup' and "
           "'fsGroup' to a non-zero GID.",
           references=["https://avd.aquasec.com/misconfig/ksv029"],
           provider="Kubernetes", service="general",
           check=_k8s_check_run_as_root_group),
    Policy(id="KSV017", avd_id="AVD-KSV-0017",
           title="Privileged container",
           description="Privileged containers share namespaces with "
           "the host system and do not offer any security. They "
           "should be used exclusively for system containers.",
           severity="HIGH",
           recommended_actions="Change 'containers[].securityContext"
           ".privileged' to 'false'.",
           references=["https://avd.aquasec.com/misconfig/ksv017"],
           provider="Kubernetes", service="general",
           check=_k8s_check_privileged),
]


def _check_copy_from_self(stages: list) -> list:
    """DS006: COPY --from references the stage's own FROM alias."""
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "COPY":
                continue
            for flag in inst.flags:
                if flag.startswith("--from=") and stage.alias and \
                        flag[len("--from="):].lower() == \
                        stage.alias.lower():
                    causes.append(Cause(
                        message=f"'COPY {flag}' references the "
                        f"current image FROM alias "
                        f"{stage.alias!r}",
                        start_line=inst.start_line,
                        end_line=inst.end_line))
    return causes


def _check_duplicate(cmd: str, stages: list) -> list:
    """Per stage, every occurrence of ``cmd`` but the last is dead."""
    causes = []
    for stage in stages:
        insts = [i for i in stage.instructions if i.cmd == cmd]
        for inst in insts[:-1]:
            causes.append(Cause(
                message=f"There are multiple {cmd} instructions; "
                "only the last one takes effect",
                start_line=inst.start_line,
                end_line=inst.end_line))
    return causes


def _check_port_range(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "EXPOSE":
                continue
            for port in inst.value.split():
                num = port.split("/")[0]
                if num.isdigit() and int(num) > 65535:
                    causes.append(Cause(
                        message=f"'EXPOSE' contains port "
                        f"{num} which is out of range",
                        start_line=inst.start_line,
                        end_line=inst.end_line))
    return causes


def _check_workdir_relative(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "WORKDIR":
                continue
            path = inst.value.strip().strip("'\"")
            if path and not path.startswith(("/", "$", "C:",
                                             "c:")):
                causes.append(Cause(
                    message=f"WORKDIR path {path!r} should be "
                    "absolute",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


def _check_run_sudo(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd == "RUN" and re.search(
                    r"(^|\s|;|&&)sudo\s", " " + inst.value):
                causes.append(Cause(
                    message="Using 'sudo' in RUN is not supported "
                    "and indicates a misconfigured image",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


def _check_run_cd(stages: list) -> list:
    """DS013: use WORKDIR, not 'RUN cd ...' as the only command."""
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd == "RUN" and re.match(
                    r"^cd\s+\S+$", inst.value.strip()):
                causes.append(Cause(
                    message=f"RUN should not be used to change "
                    f"directories ('{inst.value}'); use WORKDIR",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


def _check_apt_install_y(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "RUN":
                continue
            for part in re.split(r"&&|;|\|", inst.value):
                tokens = part.split()
                if "apt-get" not in tokens and "apt" not in tokens:
                    continue
                if "install" not in tokens:
                    continue
                confirmed = any(
                    t in ("--yes", "--assume-yes") or
                    (t.startswith("-") and not t.startswith("--")
                     and "y" in t[1:])
                    for t in tokens)
                if not confirmed:
                    causes.append(Cause(
                        message="'-y' flag is missing from "
                        "'apt-get install' — the build will hang "
                        "on the confirmation prompt",
                        start_line=inst.start_line,
                        end_line=inst.end_line))
    return causes


def _check_apk_no_cache(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "RUN":
                continue
            for part in re.split(r"&&|;|\|", inst.value):
                tokens = part.split()
                if "apk" in tokens and "add" in tokens and \
                        "--no-cache" not in tokens:
                    causes.append(Cause(
                        message="'--no-cache' is missing from "
                        "'apk add' — the package index bloats the "
                        "image",
                        start_line=inst.start_line,
                        end_line=inst.end_line))
    return causes


def _check_maintainer(stages: list) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd == "MAINTAINER":
                causes.append(Cause(
                    message=f"MAINTAINER is deprecated; use "
                    f"'LABEL maintainer=\"{inst.value}\"'",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes



def _check_update_alone(stages: list) -> list:
    """DS017: 'RUN <pm> update' without an install in the same RUN
    leaves a stale package index baked into the layer."""
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "RUN":
                continue
            value = inst.value
            has_update = re.search(
                r"\b(apt-get|apt|yum|apk|zypper)\b[^&|;]*"
                r"\b(update|check-update|ref(?:resh)?)\b", value)
            if has_update and "install" not in value and \
                    "add" not in value.split():
                causes.append(Cause(
                    message="The instruction "
                    "'RUN <package-manager> update' should always "
                    "be followed by '<package-manager> install' "
                    "in the same RUN statement",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


def _check_copy_multiple_dest(stages: list) -> list:
    """DS011: COPY with more than two arguments needs a directory
    destination ending with '/'."""
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "COPY":
                continue
            args = [t for t in inst.value.split()
                    if not t.startswith("--")]
            if len(args) > 2 and not args[-1].endswith("/"):
                causes.append(Cause(
                    message=f"When COPY with more than two "
                    f"arguments, the last one must end with '/' "
                    f"('{args[-1]}')",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


def _check_duplicate_alias(stages: list) -> list:
    """DS012: the same alias must not be used in multiple FROMs."""
    causes = []
    seen: dict = {}
    for stage in stages:
        alias = stage.alias
        if not alias:
            continue
        if alias.lower() in seen:
            causes.append(Cause(
                message=f"Duplicate aliases '{alias}' are defined "
                "in multiple FROMs",
                start_line=stage.start_line,
                end_line=stage.start_line))
        seen[alias.lower()] = True
    return causes


def _check_wget_and_curl(stages: list) -> list:
    """DS014: don't use both wget and curl — pick one tool."""
    used = {"wget": None, "curl": None}
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "RUN":
                continue
            for part in re.split(r"&&|;|\|", inst.value):
                tokens = part.split()
                for tool in ("wget", "curl"):
                    if tool in tokens and used[tool] is None:
                        used[tool] = inst
    if used["wget"] is not None and used["curl"] is not None:
        inst = used["curl"]
        return [Cause(
            message="Shouldn't use both curl and wget",
            start_line=inst.start_line, end_line=inst.end_line)]
    return []


def _pm_cleanup_missing(stages, pm, use_re, clean_re,
                        message) -> list:
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd != "RUN":
                continue
            if re.search(use_re, inst.value) and not \
                    re.search(clean_re, inst.value):
                causes.append(Cause(
                    message=message,
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


def _check_yum_clean(stages: list) -> list:
    """DS015: 'yum install' without 'yum clean all' bloats the
    layer with the package cache."""
    return _pm_cleanup_missing(
        stages, "yum",
        r"\byum\b[^&|;]*\binstall\b",
        r"\byum\s+clean\s+all\b",
        "'yum clean all' is missed")


def _check_zypper_clean(stages: list) -> list:
    """DS019: 'zypper install' without 'zypper clean'."""
    return _pm_cleanup_missing(
        stages, "zypper",
        r"\bzypper\b[^&|;]*\b(install|in)\b",
        r"\bzypper\s+(clean|cc)\b",
        "'zypper clean' is missed")


def _check_dist_upgrade(stages: list) -> list:
    """DS024: 'apt-get dist-upgrade' should not be used in an
    image build."""
    causes = []
    for stage in stages:
        for inst in stage.instructions:
            if inst.cmd == "RUN" and re.search(
                    r"\bapt-get\b[^&|;]*\bdist-upgrade\b",
                    inst.value):
                causes.append(Cause(
                    message="'apt-get dist-upgrade' should not be "
                    "used in a Dockerfile",
                    start_line=inst.start_line,
                    end_line=inst.end_line))
    return causes


DOCKERFILE_POLICIES += [
    Policy(id="DS006", avd_id="AVD-DS-0006",
           title="COPY '--from' references current FROM alias",
           description="COPY '--from' should not mention the "
           "current FROM alias, since it is impossible to copy from "
           "itself.",
           severity="CRITICAL",
           recommended_actions="Change the '--from' so that it "
           "references a previous build stage",
           references=["https://avd.aquasec.com/misconfig/ds006"],
           provider="Dockerfile", service="general",
           check=_check_copy_from_self),
    Policy(id="DS007", avd_id="AVD-DS-0007",
           title="Multiple ENTRYPOINT instructions listed",
           description="There can only be one ENTRYPOINT "
           "instruction in a Dockerfile; only the last one takes "
           "effect.",
           severity="CRITICAL",
           recommended_actions="Remove unnecessary ENTRYPOINT "
           "instructions",
           references=["https://avd.aquasec.com/misconfig/ds007"],
           provider="Dockerfile", service="general",
           check=partial(_check_duplicate, "ENTRYPOINT")),
    Policy(id="DS008", avd_id="AVD-DS-0008",
           title="Port out of range",
           description="UNIX ports outside the 0-65535 range are "
           "invalid.",
           severity="CRITICAL",
           recommended_actions="Use a port number within the range",
           references=["https://avd.aquasec.com/misconfig/ds008"],
           provider="Dockerfile", service="general",
           check=_check_port_range),
    Policy(id="DS009", avd_id="AVD-DS-0009",
           title="WORKDIR path not absolute",
           description="For clarity and reliability, you should "
           "always use absolute paths for your WORKDIR.",
           severity="HIGH",
           recommended_actions="Use an absolute path in WORKDIR",
           references=["https://avd.aquasec.com/misconfig/ds009"],
           provider="Dockerfile", service="general",
           check=_check_workdir_relative),
    Policy(id="DS010", avd_id="AVD-DS-0010",
           title="RUN using 'sudo'",
           description="Avoid using 'sudo' in RUN: it has "
           "unpredictable TTY and signal-forwarding behavior.",
           severity="CRITICAL",
           recommended_actions="Don't use sudo; switch users with "
           "USER",
           references=["https://avd.aquasec.com/misconfig/ds010"],
           provider="Dockerfile", service="general",
           check=_check_run_sudo),
    Policy(id="DS013", avd_id="AVD-DS-0013",
           title="'RUN cd ...' to change directory",
           description="Use WORKDIR instead of proliferating "
           "'RUN cd ...' instructions, which are hard to read and "
           "maintain.",
           severity="MEDIUM",
           recommended_actions="Use WORKDIR to change directories",
           references=["https://avd.aquasec.com/misconfig/ds013"],
           provider="Dockerfile", service="general",
           check=_check_run_cd),
    Policy(id="DS016", avd_id="AVD-DS-0016",
           title="Multiple CMD instructions listed",
           description="There can only be one CMD instruction in a "
           "Dockerfile; only the last one takes effect.",
           severity="HIGH",
           recommended_actions="Remove unnecessary CMD instructions",
           references=["https://avd.aquasec.com/misconfig/ds016"],
           provider="Dockerfile", service="general",
           check=partial(_check_duplicate, "CMD")),
    Policy(id="DS017", avd_id="AVD-DS-0017",
           title="'RUN <package-manager> update' instruction alone",
           description="The instruction 'RUN <package-manager> "
           "update' should always be followed by '<package-manager> "
           "install' in the same RUN statement.",
           severity="HIGH",
           recommended_actions="Combine the update and install "
           "instructions in one RUN",
           references=["https://avd.aquasec.com/misconfig/ds017"],
           provider="Dockerfile", service="general",
           check=_check_update_alone),
    Policy(id="DS021", avd_id="AVD-DS-0021",
           title="'apt-get install' missing '-y'",
           description="Without '-y', apt-get waits for manual "
           "confirmation and the build hangs.",
           severity="HIGH",
           recommended_actions="Add '-y' to 'apt-get install'",
           references=["https://avd.aquasec.com/misconfig/ds021"],
           provider="Dockerfile", service="general",
           check=_check_apt_install_y),
    Policy(id="DS022", avd_id="AVD-DS-0022",
           title="MAINTAINER is deprecated",
           description="The MAINTAINER instruction is deprecated "
           "since Docker 1.13.0.",
           severity="LOW",
           recommended_actions="Use LABEL maintainer=... instead",
           references=["https://avd.aquasec.com/misconfig/ds022"],
           provider="Dockerfile", service="general",
           check=_check_maintainer),
    Policy(id="DS023", avd_id="AVD-DS-0023",
           title="Multiple HEALTHCHECK instructions listed",
           description="There can only be one HEALTHCHECK "
           "instruction in a Dockerfile; only the last one takes "
           "effect.",
           severity="MEDIUM",
           recommended_actions="Remove unnecessary HEALTHCHECK "
           "instructions",
           references=["https://avd.aquasec.com/misconfig/ds023"],
           provider="Dockerfile", service="general",
           check=partial(_check_duplicate, "HEALTHCHECK")),
    Policy(id="DS025", avd_id="AVD-DS-0025",
           title="'apk add' missing '--no-cache'",
           description="Cached package indexes bloat the image; "
           "'apk add --no-cache' avoids them.",
           severity="HIGH",
           recommended_actions="Add '--no-cache' to 'apk add'",
           references=["https://avd.aquasec.com/misconfig/ds025"],
           provider="Dockerfile", service="general",
           check=_check_apk_no_cache),
    Policy(id="DS011", avd_id="AVD-DS-0011",
           title="COPY with multiple sources needs a directory "
           "destination",
           description="When a COPY command has more than two "
           "arguments, the last one must end with '/' so it is "
           "treated as a directory.",
           severity="CRITICAL",
           recommended_actions="End the destination with '/'",
           references=["https://avd.aquasec.com/misconfig/ds011"],
           provider="Dockerfile", service="general",
           check=_check_copy_multiple_dest),
    Policy(id="DS012", avd_id="AVD-DS-0012",
           title="Duplicate aliases defined in multiple FROMs",
           description="Multiple FROM instructions must not use "
           "the same alias.",
           severity="CRITICAL",
           recommended_actions="Rename the duplicate alias",
           references=["https://avd.aquasec.com/misconfig/ds012"],
           provider="Dockerfile", service="general",
           check=_check_duplicate_alias),
    Policy(id="DS014", avd_id="AVD-DS-0014",
           title="'wget' and 'curl' used together",
           description="Pick one HTTP tool; installing both bloats "
           "the image and confuses maintenance.",
           severity="LOW",
           recommended_actions="Use either wget or curl, not both",
           references=["https://avd.aquasec.com/misconfig/ds014"],
           provider="Dockerfile", service="general",
           check=_check_wget_and_curl),
    Policy(id="DS015", avd_id="AVD-DS-0015",
           title="'yum clean all' missing",
           description="The package cache left by 'yum install' "
           "bloats the layer.",
           severity="HIGH",
           recommended_actions="Add 'yum clean all' after the "
           "install",
           references=["https://avd.aquasec.com/misconfig/ds015"],
           provider="Dockerfile", service="general",
           check=_check_yum_clean),
    Policy(id="DS019", avd_id="AVD-DS-0019",
           title="'zypper clean' missing",
           description="The package cache left by 'zypper install' "
           "bloats the layer.",
           severity="HIGH",
           recommended_actions="Add 'zypper clean' after the "
           "install",
           references=["https://avd.aquasec.com/misconfig/ds019"],
           provider="Dockerfile", service="general",
           check=_check_zypper_clean),
    Policy(id="DS024", avd_id="AVD-DS-0024",
           title="'apt-get dist-upgrade' used",
           description="Full distribution upgrades inside an image "
           "build are unpredictable; upgrade the base image "
           "instead.",
           severity="HIGH",
           recommended_actions="Remove 'apt-get dist-upgrade'",
           references=["https://avd.aquasec.com/misconfig/ds024"],
           provider="Dockerfile", service="general",
           check=_check_dist_upgrade),
]
