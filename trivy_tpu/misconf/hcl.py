"""HCL2 subset parser for Terraform misconfiguration scanning.

The reference evaluates Terraform through defsec's full HCL engine
(/root/reference/pkg/fanal/handler/misconf/misconf.go:19-29 pulls in
defsec's terraform scanner). This is a deliberately small re-design:
the policy checks (misconf.policies) need resource blocks, attribute
literals, and enough expression evaluation to resolve ``var.*``
defaults and ``local.*`` values — not a general Terraform interpreter.
Anything beyond the subset (function calls, arithmetic, for-
expressions, module references) evaluates to ``Unresolved``, which
checks treat as "unknown" and never fail on (defsec's checks behave
the same way on unresolvable values: they only flag provable
misconfigurations).

Grammar covered:
  block     = IDENT (STRING | IDENT)* "{" body "}"
  body      = (attribute | block)*
  attribute = IDENT "=" expr
  expr      = STRING (with ${...} interpolation) | HEREDOC | NUMBER
            | BOOL | NULL | list | map | reference | <unresolved>
Comments: ``#``, ``//``, ``/* */``. Heredocs: ``<<EOF`` / ``<<-EOF``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional


class Unresolved:
    """Value the subset evaluator cannot determine statically."""

    __slots__ = ("why",)

    def __init__(self, why: str = ""):
        self.why = why

    def __repr__(self):
        return f"Unresolved({self.why!r})"

    def __bool__(self):
        # unknowns are never treated as a provable misconfiguration
        return False

    def __eq__(self, other):
        return isinstance(other, Unresolved)

    def __hash__(self):
        return hash("<unresolved>")


@dataclass
class Attr:
    name: str
    value: object
    line: int = 0


@dataclass
class Block:
    type: str
    labels: list = field(default_factory=list)
    attrs: dict = field(default_factory=dict)     # name → Attr
    blocks: list = field(default_factory=list)    # nested Blocks
    start_line: int = 0
    end_line: int = 0

    def attr(self, name: str, default=None):
        a = self.attrs.get(name)
        return a.value if a is not None else default

    def attr_line(self, name: str) -> int:
        a = self.attrs.get(name)
        return a.line if a is not None else self.start_line

    def find_blocks(self, btype: str) -> list:
        return [b for b in self.blocks if b.type == btype]

    def first_block(self, btype: str) -> Optional["Block"]:
        for b in self.blocks:
            if b.type == btype:
                return b
        return None


# ---------------------------------------------------------------- lexer

_TOKEN_RE = re.compile(r"""
    (?P<ws>[ \t\r]+)
  | (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<heredoc><<-?(?P<hd_tag>[A-Za-z_][A-Za-z0-9_]*)\n)
  | (?P<nl>\n)
  | (?P<string>"(?:\\.|\$\{[^}]*\}|[^"\\])*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.*\-]*)
  | (?P<punct>[{}\[\]=,:()])
  | (?P<other>.)
""", re.VERBOSE | re.DOTALL)


@dataclass
class _Tok:
    kind: str
    text: str
    line: int


def _lex(src: str) -> list:
    toks = []
    line = 1
    pos = 0
    n = len(src)
    while pos < n:
        m = _TOKEN_RE.match(src, pos)
        if m is None:       # pragma: no cover - 'other' catches all
            break
        kind = m.lastgroup
        text = m.group()
        if kind == "heredoc":
            # consume lines until the terminator tag
            tag = m.group("hd_tag")
            body_start = m.end()
            term = re.compile(
                rf"^[ \t]*{re.escape(tag)}[ \t]*$", re.MULTILINE)
            tm = term.search(src, body_start)
            body_end = tm.start() if tm else n
            body = src[body_start:body_end]
            toks.append(_Tok("string_lit", body, line))
            # the terminator line's own newline is NOT consumed here
            # (pos stops at tm.end()); it is lexed next as an nl token,
            # so counting it here would double-shift later line numbers
            line += text.count("\n") + body.count("\n")
            pos = tm.end() if tm else n
            continue
        if kind == "nl":
            toks.append(_Tok("nl", "\n", line))
            line += 1
        elif kind == "comment":
            line += text.count("\n")
        elif kind not in ("ws",):
            toks.append(_Tok(kind, text, line))
        pos = m.end()
    return toks


# --------------------------------------------------------------- parser

_INTERP_RE = re.compile(r"\$\{([^}]*)\}")
_ESCAPES = {"n": "\n", "t": "\t", '"': '"', "\\": "\\"}


class _Parser:
    def __init__(self, toks: list, ctx: dict):
        self.toks = [t for t in toks]
        self.i = 0
        self.ctx = ctx          # "var" → {name: value}, "local" → {...}

    def _peek(self, skip_nl=True) -> Optional[_Tok]:
        j = self.i
        while j < len(self.toks):
            t = self.toks[j]
            if skip_nl and t.kind == "nl":
                j += 1
                continue
            return t
        return None

    def _next(self, skip_nl=True) -> Optional[_Tok]:
        while self.i < len(self.toks):
            t = self.toks[self.i]
            self.i += 1
            if skip_nl and t.kind == "nl":
                continue
            return t
        return None

    def parse_body(self, top=False) -> tuple:
        """Returns (attrs dict, blocks list, end_line)."""
        attrs: dict = {}
        blocks: list = []
        end_line = 0
        while True:
            t = self._peek()
            if t is None:
                break
            if t.kind == "punct" and t.text == "}":
                self._next()
                end_line = t.line
                break
            if t.kind != "ident":
                self._next()        # skip stray token, stay robust
                continue
            name_tok = self._next()
            nxt = self._peek()
            if nxt is not None and nxt.kind == "punct" \
                    and nxt.text == "=":
                self._next()
                value = self.parse_expr()
                attrs[name_tok.text] = Attr(
                    name=name_tok.text, value=value,
                    line=name_tok.line)
                continue
            # block: labels then {
            labels = []
            while True:
                nxt = self._peek()
                if nxt is None:
                    break
                if nxt.kind == "string":
                    labels.append(_string_value(
                        self._next().text, self.ctx))
                    continue
                if nxt.kind == "ident":
                    labels.append(self._next().text)
                    continue
                break
            nxt = self._peek()
            if nxt is not None and nxt.kind == "punct" \
                    and nxt.text == "{":
                self._next()
                a, bl, end = self.parse_body()
                blocks.append(Block(
                    type=name_tok.text,
                    labels=[x if isinstance(x, str) else str(x)
                            for x in labels],
                    attrs=a, blocks=bl,
                    start_line=name_tok.line, end_line=end))
            # else: not a block — ignore (robustness)
        return attrs, blocks, end_line

    def parse_expr(self):
        t = self._next()
        if t is None:
            return Unresolved("eof")
        if t.kind == "string":
            return self._maybe_binop(_string_value(t.text, self.ctx))
        if t.kind == "string_lit":
            return _interp(t.text, self.ctx)
        if t.kind == "number":
            v = float(t.text) if "." in t.text else int(t.text)
            return self._maybe_binop(v)
        if t.kind == "ident":
            if t.text == "true":
                return self._maybe_binop(True)
            if t.text == "false":
                return self._maybe_binop(False)
            if t.text == "null":
                return self._maybe_binop(None)
            nxt = self._peek()
            if nxt is not None and nxt.kind == "punct" \
                    and nxt.text == "(":
                self._skip_parens()
                return Unresolved(f"call {t.text}()")
            if nxt is not None and nxt.kind == "punct" \
                    and nxt.text == "[":
                # index/splat expression: outside the subset
                self._skip_brackets()
                return Unresolved(f"index {t.text}[...]")
            return self._maybe_binop(
                _resolve_ref(t.text, self.ctx))
        if t.kind == "punct" and t.text == "[":
            out = []
            while True:
                nxt = self._peek()
                if nxt is None:
                    break
                if nxt.kind == "punct" and nxt.text == "]":
                    self._next()
                    break
                if nxt.kind == "punct" and nxt.text == ",":
                    self._next()
                    continue
                out.append(self.parse_expr())
            return out
        if t.kind == "punct" and t.text == "{":
            out = {}
            while True:
                nxt = self._peek()
                if nxt is None:
                    break
                if nxt.kind == "punct" and nxt.text == "}":
                    self._next()
                    break
                if nxt.kind == "punct" and nxt.text == ",":
                    self._next()
                    continue
                key_tok = self._next()
                key = key_tok.text
                if key_tok.kind == "string":
                    key = _string_value(key, self.ctx)
                sep = self._peek()
                if sep is not None and sep.kind == "punct" \
                        and sep.text in ("=", ":"):
                    self._next()
                    out[key] = self.parse_expr()
                else:
                    out[key] = Unresolved("bad map entry")
            return out
        return Unresolved(t.text)

    def _maybe_binop(self, value):
        """The subset doesn't evaluate operators — a trailing binary
        operator poisons the whole expression to Unresolved. After a
        complete value the only structural followers are newline, a
        closing brace/bracket/paren, a separator, or EOF; anything
        else ('+', '==' — whose first '=' lexes as punct —, '?', ...)
        starts an operator expression."""
        nxt = self._peek(skip_nl=False)
        if nxt is not None and (
                nxt.kind == "other"
                or (nxt.kind == "punct"
                    and nxt.text not in ("}", "]", ")", ",", ":"))):
            # consume the rest of the line
            while True:
                t = self._peek(skip_nl=False)
                if t is None or t.kind == "nl":
                    break
                self._next(skip_nl=False)
            return Unresolved("operator expression")
        return value

    def _skip_parens(self):
        self._skip_nested("(", ")")

    def _skip_brackets(self):
        self._skip_nested("[", "]")

    def _skip_nested(self, open_t: str, close_t: str):
        depth = 0
        while True:
            t = self._next()
            if t is None:
                return
            if t.kind == "punct" and t.text == open_t:
                depth += 1
            elif t.kind == "punct" and t.text == close_t:
                depth -= 1
                if depth == 0:
                    return


def _string_value(raw: str, ctx: dict):
    body = raw[1:-1]
    out = []
    i = 0
    n = len(body)
    while i < n:
        ch = body[i]
        if ch == "\\" and i + 1 < n:
            out.append(_ESCAPES.get(body[i + 1], body[i + 1]))
            i += 2
            continue
        out.append(ch)
        i += 1
    return _interp("".join(out), ctx)


def _interp(s: str, ctx: dict):
    """Resolve ``${ref}`` interpolations; a non-literal part makes
    the whole string Unresolved ONLY if nothing else is known —
    partial resolution keeps the literal text with ``${...}`` left
    in place so prefix checks (e.g. image tags) still see shape."""
    def sub(m):
        v = _resolve_ref(m.group(1).strip(), ctx)
        if isinstance(v, Unresolved):
            return m.group(0)
        return str(v)
    return _INTERP_RE.sub(sub, s)


def _resolve_ref(ref: str, ctx: dict):
    parts = ref.split(".")
    if len(parts) >= 2 and parts[0] in ("var", "local"):
        scope = ctx.get(parts[0], {})
        v = scope.get(parts[1], Unresolved(ref))
        for p in parts[2:]:
            if isinstance(v, dict):
                v = v.get(p, Unresolved(ref))
            else:
                return Unresolved(ref)
        return v
    return Unresolved(ref)


# ----------------------------------------------------------- public API

def parse_file(src: str, ctx: Optional[dict] = None) -> list:
    """Parse one .tf file into top-level Blocks."""
    p = _Parser(_lex(src), ctx or {"var": {}, "local": {}})
    _attrs, blocks, _ = p.parse_body(top=True)
    return blocks


def parse_module(files: dict) -> list:
    """Parse a set of ``{path: source}`` .tf files as one module:
    pass 1 collects ``variable`` defaults and ``locals``, pass 2
    evaluates everything with those in scope (the defsec scanner
    evaluates a module directory the same way). Returns all top-level
    blocks across files, each annotated with ``src_path``."""
    ctx = {"var": {}, "local": {}}
    parsed0 = {p: parse_file(s) for p, s in files.items()}
    for blocks in parsed0.values():
        for b in blocks:
            if b.type == "variable" and b.labels:
                # no default (value supplied at plan/apply time) or an
                # explicit null means the value is UNKNOWN here — it
                # must never satisfy a provable-misconfiguration check
                v = b.attr("default")
                if "default" not in b.attrs or v is None:
                    v = Unresolved(f"var.{b.labels[0]}")
                ctx["var"][b.labels[0]] = v
            elif b.type == "locals":
                for name, attr in b.attrs.items():
                    ctx["local"][name] = attr.value
    out = []
    for path, src in files.items():
        for b in parse_file(src, ctx):
            b.src_path = path
            out.append(b)
    return out


def unresolved_trace(blocks: list) -> list:
    """Per-module evaluation visibility (the reference's rego
    --trace analog, pkg/flag/rego_flags.go:21-26): one line per
    attribute whose value the HCL subset could not evaluate, so a
    user can tell "no findings" apart from "couldn't evaluate".
    → [(src_path, "path:line block ref: attr = <unresolved: why>")]
    — structured so callers group by the real source path rather
    than re-splitting the display string (paths may contain
    colons)."""
    lines = []

    def walk_value(v, emit):
        if isinstance(v, Unresolved):
            emit(v.why)
        elif isinstance(v, list):
            for item in v:
                walk_value(item, emit)
        elif isinstance(v, dict):
            for item in v.values():
                walk_value(item, emit)

    def walk_block(b, src):
        ref = " ".join([b.type] + [f"{l!r}" for l in b.labels])
        for name, attr in b.attrs.items():
            walk_value(attr.value, lambda why, n=name, a=attr:
                       lines.append((src,
                                     f"{src}:{a.line} {ref}: {n} = "
                                     f"<unresolved: {why}>")))
        for nested in b.blocks:
            walk_block(nested, src)

    for b in blocks:
        walk_block(b, getattr(b, "src_path", ""))
    return lines
