"""Misconfiguration scanning engine
(reference: pkg/fanal/handler/misconf/misconf.go:149-338 + defsec).

Evaluates policy sets against collected ConfigFiles and produces
blob-level Misconfigurations: per file, every applicable policy lands
in ``failures`` (with cause lines) or ``successes`` —
resultsToMisconf's shape (misconf.go:338-). Host-side: policy
evaluation is irregular tree-walking, not kernel work.

File types handled (reference misconf.go:19-29 scanner fleet):
  dockerfile        — instruction checks (policies.DOCKERFILE_POLICIES)
  kubernetes        — yaml/json manifests (KUBERNETES_POLICIES)
  terraform         — .tf modules via the HCL subset (terraform.py)
  cloudformation    — templates via the resource walker
  helm              — charts rendered to k8s docs (helm.py), then the
                      Kubernetes policy set

User extension point (the reference's custom-rego analog,
misconf.go:202-238 policy paths): ``configure(policy_dirs=[...])``
loads Python modules defining ``POLICIES = [Policy(...)]``; each
policy declares ``file_types`` naming the inputs it understands.
Custom policies run with namespace ``user.<file_type>.<id>``.
WARNING: policy modules execute with full interpreter rights (like
--ignore-policy), unlike the reference's sandboxed Rego.
"""

from __future__ import annotations

import json as json_mod
import posixpath
import os

from ..types import Misconfiguration
from ..types.report import CauseMetadata, MisconfResult
from ..utils import get_logger
from . import dockerfile as dockerfile_mod
from .policies import (DOCKERFILE_POLICIES, KUBERNETES_POLICIES,
                       Policy)

log = get_logger("misconf")

try:
    import yaml as yaml_mod
except ImportError:          # pragma: no cover
    yaml_mod = None


_SCANNER_NAMES = {
    "dockerfile": "Dockerfile",
    "kubernetes": "Kubernetes",
    "terraform": "Terraform",
    "cloudformation": "CloudFormation",
    "helm": "Helm",
}


class MisconfOptions:
    """Engine options (reference config.ScannerOption subset)."""

    def __init__(self, policy_dirs=None, helm_value_files=None,
                 helm_set_values=None, trace=False):
        self.policy_dirs = list(policy_dirs or [])
        self.helm_value_files = list(helm_value_files or [])
        self.helm_set_values = list(helm_set_values or [])
        self.custom_policies = _load_custom(self.policy_dirs)
        self.trace = bool(trace)


def configure(policy_dirs=None, helm_value_files=None,
              helm_set_values=None, trace=False) -> None:
    """Install engine options (called by the CLI before scanning)."""
    global _options
    _options = MisconfOptions(policy_dirs, helm_value_files,
                              helm_set_values, trace)


def _load_custom(dirs: list) -> dict:
    """{file_type: [Policy]} from user policy modules."""
    out: dict = {}
    import types as _types
    for d in dirs:
        try:
            names = sorted(os.listdir(d))
        except OSError as e:
            raise ValueError(f"--config-policy {d}: {e}")
        for name in names:
            if not name.endswith(".py"):
                continue
            path = os.path.join(d, name)
            mod = _types.ModuleType(f"trivy_config_policy_{name[:-3]}")
            with open(path, encoding="utf-8") as f:
                src = f.read()
            try:
                exec(compile(src, path, "exec"), mod.__dict__)
            except Exception as e:       # noqa: BLE001
                raise ValueError(f"config policy {path}: {e!r}")
            policies = getattr(mod, "POLICIES", None)
            if not isinstance(policies, (list, tuple)):
                raise ValueError(
                    f"config policy {path} must define POLICIES = "
                    f"[Policy(...)]")
            for p in policies:
                for ft in (p.file_types or ("kubernetes",)):
                    out.setdefault(ft, []).append(p)
    return out


_options = MisconfOptions()


def _is_kubernetes(doc) -> bool:
    return isinstance(doc, dict) and "apiVersion" in doc and \
        "kind" in doc


def _parse_docs(config_file):
    """ConfigFile → (file_type, parsed docs or None)."""
    if config_file.type == "dockerfile":
        return "dockerfile", dockerfile_mod.parse(config_file.content)
    if config_file.type in ("yaml", "helm", "json"):
        from .cloudformation import parse_template
        cfn = parse_template(config_file.content)
        if cfn is not None:
            return "cloudformation", cfn
    if config_file.type in ("yaml", "helm"):
        if yaml_mod is None:
            return None, None
        try:
            docs = [d for d in yaml_mod.safe_load_all(
                config_file.content.decode("utf-8", "replace"))
                if d is not None]
        except yaml_mod.YAMLError as e:
            log.debug("yaml parse error in %s: %s",
                      config_file.file_path, e)
            return None, None
        k8s = [d for d in docs if _is_kubernetes(d)]
        if k8s:
            return "kubernetes", k8s
        return None, None
    if config_file.type == "json":
        try:
            doc = json_mod.loads(config_file.content)
        except ValueError:
            return None, None
        if _is_kubernetes(doc):
            return "kubernetes", [doc]
        return None, None
    return None, None


def _result(policy: Policy, file_type: str, message: str,
            cause=None, custom: bool = False) -> MisconfResult:
    ns = (f"user.{file_type}.{policy.id}" if custom
          else f"builtin.{file_type}.{policy.id}")
    scanner = _SCANNER_NAMES.get(file_type, file_type.title())
    return MisconfResult(
        namespace=ns,
        query=f"data.{ns}.deny",
        message=message,
        id=policy.id,
        avd_id=policy.avd_id,
        type=f"{scanner} Security Check",
        title=policy.title,
        description=policy.description,
        severity=policy.severity,
        recommended_actions=policy.recommended_actions,
        references=list(policy.references),
        cause_metadata=CauseMetadata(
            resource=getattr(cause, "resource", "") or "",
            provider=policy.provider,
            service=policy.service,
            start_line=getattr(cause, "start_line", 0),
            end_line=getattr(cause, "end_line", 0)),
    )


def _policies_for(file_type: str) -> list:
    builtin = {
        "dockerfile": DOCKERFILE_POLICIES,
        "kubernetes": KUBERNETES_POLICIES,
        "helm": KUBERNETES_POLICIES,
    }.get(file_type)
    if builtin is None:
        if file_type == "terraform":
            from .terraform import TERRAFORM_POLICIES
            builtin = TERRAFORM_POLICIES
        elif file_type == "cloudformation":
            from .cloudformation import CLOUDFORMATION_POLICIES
            builtin = CLOUDFORMATION_POLICIES
        else:
            builtin = []
    custom = _options.custom_policies.get(file_type, [])
    return [(p, False) for p in builtin] + [(p, True) for p in custom]


def _evaluate(file_type: str, docs, file_path: str,
              check_input=None) -> Misconfiguration:
    """Run every applicable policy over one file's parsed docs."""
    successes, failures = [], []
    for policy, custom in _policies_for(file_type):
        causes = []
        if file_type == "dockerfile":
            causes = policy.check(docs)
        elif file_type in ("kubernetes", "helm"):
            for doc in docs:
                causes.extend(policy.check(doc))
        else:
            causes = policy.check(check_input
                                  if check_input is not None else docs)
        if causes:
            for cause in causes:
                failures.append(_result(
                    policy, file_type, cause.message, cause, custom))
        else:
            successes.append(_result(
                policy, file_type, policy.success_message,
                custom=custom))
    successes.sort(key=lambda r: (r.avd_id,
                                  r.cause_metadata.start_line))
    failures.sort(key=lambda r: (r.avd_id,
                                 r.cause_metadata.start_line))
    return Misconfiguration(
        file_type=file_type, file_path=file_path,
        successes=successes, failures=failures)


def _scan_terraform(tf_files: list) -> list:
    """Group .tf ConfigFiles by directory into modules, evaluate the
    module, then attribute each cause to the file its resource lives
    in (defsec reports per-resource-location files the same way).
    Successes attach to every file in the module."""
    from .hcl import parse_module
    by_dir: dict = {}
    for cf in tf_files:
        by_dir.setdefault(posixpath.dirname(cf.file_path), []).append(cf)
    out = []
    for _d, files in sorted(by_dir.items()):
        sources = {cf.file_path: cf.content.decode("utf-8", "replace")
                   for cf in files}
        try:
            blocks = parse_module(sources)
        except Exception as e:       # noqa: BLE001 - stay robust
            log.debug("terraform parse error in %s: %s", _d, e)
            continue
        # --trace: evaluation visibility — where the HCL subset
        # bailed to Unresolved, grouped per source file (the rego
        # --trace analog; checks never fail on unknowns, so these
        # are exactly the spots "clean" might mean "couldn't
        # evaluate")
        trace_by_file: dict = {}
        if _options.trace:
            from .hcl import unresolved_trace
            for src, line in unresolved_trace(blocks):
                trace_by_file.setdefault(src, []).append(line)
        # evaluate once per module; split causes per source file
        per_file: dict = {cf.file_path: ([], []) for cf in files}
        for policy, custom in _policies_for("terraform"):
            causes = policy.check(blocks)
            if causes:
                for cause in causes:
                    fp = getattr(cause, "file_path", "") or \
                        files[0].file_path
                    per_file.setdefault(fp, ([], []))[1].append(
                        _result(policy, "terraform", cause.message,
                                cause, custom))
            else:
                for cf in files:
                    per_file[cf.file_path][0].append(_result(
                        policy, "terraform", policy.success_message,
                        custom=custom))
        for fp, (succ, fail) in sorted(per_file.items()):
            succ.sort(key=lambda r: (r.avd_id,
                                     r.cause_metadata.start_line))
            fail.sort(key=lambda r: (r.avd_id,
                                     r.cause_metadata.start_line))
            out.append(Misconfiguration(
                file_type="terraform", file_path=fp,
                successes=succ, failures=fail,
                traces=trace_by_file.get(fp, [])))
    return out


def _scan_helm_charts(config_files: list) -> tuple:
    """Render detected charts; returns ([Misconfiguration],
    set of paths consumed by chart rendering)."""
    from .helm import find_charts, render_chart
    files = {cf.file_path: cf.content for cf in config_files
             if cf.type in ("yaml", "helm")}
    charts = find_charts(list(files))
    overrides = []
    for vf in _options.helm_value_files:
        try:
            with open(vf, encoding="utf-8") as f:
                overrides.append(f.read())
        except OSError as e:
            log.warning("--helm-values %s: %s", vf, e)
    out, consumed = [], set()
    for root, tpls in sorted(charts.items()):
        consumed.update(tpls)
        # posixpath.join: a chart at the scan root has root == "" and
        # plain concat would yield "/Chart.yaml", never matching the
        # real path, so those files got re-scanned as plain configs
        consumed.add(posixpath.join(root, "Chart.yaml"))
        consumed.add(posixpath.join(root, "values.yaml"))
        rendered = render_chart(
            files, root, tpls, overrides,
            _options.helm_set_values)
        for path, text in sorted(rendered.items()):
            if yaml_mod is None:
                continue
            try:
                docs = [d for d in yaml_mod.safe_load_all(text)
                        if d is not None]
            except yaml_mod.YAMLError as e:
                log.debug("rendered helm template %s: %s", path, e)
                continue
            k8s = [d for d in docs if _is_kubernetes(d)]
            if k8s:
                out.append(_evaluate("helm", k8s, path))
    return out, consumed


def scan_config_files(config_files: list) -> list:
    """[ConfigFile] → [Misconfiguration], sorted per
    misconf.go:300-321."""
    out = []

    helm_results, consumed = _scan_helm_charts(config_files)
    out.extend(helm_results)

    tf = [cf for cf in config_files if cf.type == "terraform"]
    if tf:
        out.extend(_scan_terraform(tf))

    for cf in config_files:
        if cf.type == "terraform" or cf.file_path in consumed:
            continue
        file_type, docs = _parse_docs(cf)
        if file_type is None:
            continue
        out.append(_evaluate(file_type, docs, cf.file_path,
                             check_input=docs))
    out.sort(key=lambda m: m.file_path)
    return out
