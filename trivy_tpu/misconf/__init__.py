"""Misconfiguration scanning engine
(reference: pkg/fanal/handler/misconf/misconf.go:149-338 + defsec).

Evaluates the built-in policy sets against collected ConfigFiles and
produces blob-level Misconfigurations: per file, every applicable
policy lands in ``failures`` (with cause lines) or ``successes`` —
resultsToMisconf's shape (misconf.go:338-). Host-side: policy
evaluation is irregular tree-walking, not kernel work.
"""

from __future__ import annotations

import json as json_mod

from ..types import Misconfiguration
from ..types.report import CauseMetadata, MisconfResult
from ..utils import get_logger
from . import dockerfile as dockerfile_mod
from .policies import (DOCKERFILE_POLICIES, KUBERNETES_POLICIES,
                       Policy)

log = get_logger("misconf")

try:
    import yaml as yaml_mod
except ImportError:          # pragma: no cover
    yaml_mod = None


def _is_kubernetes(doc) -> bool:
    return isinstance(doc, dict) and "apiVersion" in doc and \
        "kind" in doc


def _parse_docs(config_file):
    """ConfigFile → (file_type, parsed docs or None)."""
    if config_file.type == "dockerfile":
        return "dockerfile", dockerfile_mod.parse(config_file.content)
    if config_file.type in ("yaml", "helm"):
        if yaml_mod is None:
            return None, None
        try:
            docs = [d for d in yaml_mod.safe_load_all(
                config_file.content.decode("utf-8", "replace"))
                if d is not None]
        except yaml_mod.YAMLError as e:
            log.debug("yaml parse error in %s: %s",
                      config_file.file_path, e)
            return None, None
        k8s = [d for d in docs if _is_kubernetes(d)]
        if k8s:
            return "kubernetes", k8s
        return None, None
    if config_file.type == "json":
        try:
            doc = json_mod.loads(config_file.content)
        except ValueError:
            return None, None
        if _is_kubernetes(doc):
            return "kubernetes", [doc]
        return None, None
    return None, None


def _result(policy: Policy, file_type: str, message: str,
            cause=None) -> MisconfResult:
    return MisconfResult(
        namespace=f"builtin.{file_type}.{policy.id}",
        query=f"data.builtin.{file_type}.{policy.id}.deny",
        message=message,
        id=policy.id,
        avd_id=policy.avd_id,
        type=f"{'Dockerfile' if file_type == 'dockerfile' else 'Kubernetes'} Security Check",
        title=policy.title,
        description=policy.description,
        severity=policy.severity,
        recommended_actions=policy.recommended_actions,
        references=list(policy.references),
        cause_metadata=CauseMetadata(
            provider=policy.provider,
            service=policy.service,
            start_line=getattr(cause, "start_line", 0),
            end_line=getattr(cause, "end_line", 0)),
    )


def scan_config_files(config_files: list) -> list:
    """[ConfigFile] → [Misconfiguration], sorted per
    misconf.go:300-321."""
    out = []
    for cf in config_files:
        file_type, docs = _parse_docs(cf)
        if file_type is None:
            continue
        policies = DOCKERFILE_POLICIES if file_type == "dockerfile" \
            else KUBERNETES_POLICIES
        successes, failures = [], []
        for policy in policies:
            causes = []
            if file_type == "dockerfile":
                causes = policy.check(docs)
            else:
                for doc in docs:
                    causes.extend(policy.check(doc))
            if causes:
                for cause in causes:
                    failures.append(_result(
                        policy, file_type, cause.message, cause))
            else:
                successes.append(_result(
                    policy, file_type, policy.success_message))
        successes.sort(key=lambda r: (r.avd_id,
                                      r.cause_metadata.start_line))
        failures.sort(key=lambda r: (r.avd_id,
                                     r.cause_metadata.start_line))
        out.append(Misconfiguration(
            file_type=file_type,
            file_path=cf.file_path,
            successes=successes,
            failures=failures,
        ))
    out.sort(key=lambda m: m.file_path)
    return out
