"""Terraform misconfiguration checks.

Re-implementations of defsec's best-known AWS Terraform checks (the
reference embeds them through defsec's terraform scanner,
/root/reference/pkg/fanal/handler/misconf/misconf.go:19-29; IDs /
severities follow the public AVD registry the reference reports).
Checks only fail on PROVABLE misconfigurations: Unresolved values
(variables without defaults, function calls) never fail — defsec's
checks behave the same on unresolvable values.

Input: the module's top-level blocks from hcl.parse_module. Causes
carry the resource address (``aws_s3_bucket.logs``) and source lines.
"""

from __future__ import annotations

from .hcl import Block, Unresolved
from .policies import Cause, Policy


def _resources(blocks: list, rtype: str) -> list:
    return [b for b in blocks
            if b.type == "resource" and b.labels
            and b.labels[0] == rtype]


def _addr(b: Block) -> str:
    return ".".join(b.labels[:2]) if len(b.labels) >= 2 else \
        (b.labels[0] if b.labels else "resource")


def _ref_target(v) -> str:
    """'aws_s3_bucket.b.id' → 'aws_s3_bucket.b' (link resolution for
    cross-resource references that the subset keeps as Unresolved)."""
    if isinstance(v, Unresolved):
        parts = v.why.split(".")
        if len(parts) >= 2:
            return ".".join(parts[:2])
    return ""


def _cause(b: Block, msg: str, line: int = 0) -> Cause:
    return Cause(message=msg, resource=_addr(b),
                 start_line=line or b.start_line,
                 end_line=line or b.end_line,
                 file_path=getattr(b, "src_path", ""))


# ------------------------------------------------------------------- S3

def _s3_buckets(blocks):
    return _resources(blocks, "aws_s3_bucket")


def _aux_index(blocks, rtype: str) -> dict:
    """One-pass lookup of aux resources (versioning / encryption /
    logging / public-access-block) keyed by BOTH the reference target
    (aws_s3_bucket.b) and the literal bucket-name string, so configs
    that link by `bucket = "my-bucket"` count the same as references."""
    idx = {}
    for r in _resources(blocks, rtype):
        v = r.attrs.get("bucket")
        if v is None:
            continue
        t = _ref_target(v.value)
        if t:
            idx.setdefault(t, r)
        if isinstance(v.value, str):
            idx.setdefault(v.value, r)
    return idx


def _aux_lookup(idx: dict, bucket: Block):
    r = idx.get(_addr(bucket))
    if r is not None:
        return r
    name = bucket.attr("bucket")
    return idx.get(name) if isinstance(name, str) else None


def _check_s3_public_access_block(blocks) -> list:
    """AVD-AWS-0094 aws-s3-specify-public-access-block."""
    out = []
    pabs = _aux_index(blocks, "aws_s3_bucket_public_access_block")
    for b in _s3_buckets(blocks):
        if _aux_lookup(pabs, b) is None:
            out.append(_cause(
                b, "Bucket does not have a corresponding public "
                   "access block."))
    return out


def _pab_flag_check(flag: str, message: str):
    def check(blocks) -> list:
        out = []
        pabs = _aux_index(
            blocks, "aws_s3_bucket_public_access_block")
        for b in _s3_buckets(blocks):
            pab = _aux_lookup(pabs, b)
            if pab is None:
                continue          # AVD-AWS-0094 reports the absence
            v = pab.attr(flag)
            if v is True or isinstance(v, Unresolved):
                continue
            out.append(_cause(pab, message, pab.attr_line(flag)
                              if flag in pab.attrs else 0))
        return out
    return check


def _check_s3_encryption(blocks) -> list:
    """AVD-AWS-0088 aws-s3-enable-bucket-encryption."""
    out = []
    enc = _aux_index(
        blocks, "aws_s3_bucket_server_side_encryption_configuration")
    for b in _s3_buckets(blocks):
        if b.first_block("server_side_encryption_configuration"):
            continue
        if _aux_lookup(enc, b):
            continue
        out.append(_cause(
            b, "Bucket does not have encryption enabled"))
    return out


def _check_s3_versioning(blocks) -> list:
    """AVD-AWS-0090 aws-s3-enable-versioning."""
    out = []
    ver_idx = _aux_index(blocks, "aws_s3_bucket_versioning")
    for b in _s3_buckets(blocks):
        ver = b.first_block("versioning")
        if ver is not None:
            v = ver.attr("enabled", True)
            if v is False:
                out.append(_cause(
                    b, "Bucket does not have versioning enabled",
                    ver.start_line))
            continue
        r = _aux_lookup(ver_idx, b)
        if r is not None:
            cfg = r.first_block("versioning_configuration")
            if cfg is not None and cfg.attr("status") not in (
                    "Enabled", None) and not isinstance(
                    cfg.attr("status"), Unresolved):
                out.append(_cause(
                    r, "Bucket does not have versioning enabled",
                    cfg.start_line))
            continue
        out.append(_cause(
            b, "Bucket does not have versioning enabled"))
    return out


def _check_s3_public_acl(blocks) -> list:
    """AVD-AWS-0092 aws-s3-no-public-access-with-acl (public-read /
    public-read-write / website ACLs on the bucket itself)."""
    out = []
    for b in _s3_buckets(blocks):
        acl = b.attr("acl")
        if isinstance(acl, str) and acl.startswith("public-"):
            out.append(_cause(
                b, f"Bucket has a public ACL: {acl!r}.",
                b.attr_line("acl")))
    return out


def _check_s3_logging(blocks) -> list:
    """AVD-AWS-0089 aws-s3-enable-bucket-logging."""
    out = []
    logging_idx = _aux_index(blocks, "aws_s3_bucket_logging")
    for b in _s3_buckets(blocks):
        if b.first_block("logging") or _aux_lookup(logging_idx, b):
            continue
        if isinstance(b.attr("acl"), str) and \
                b.attr("acl") == "log-delivery-write":
            continue            # the log bucket itself
        out.append(_cause(b, "Bucket does not have logging enabled"))
    return out


# -------------------------------------------------------- security group

_PUBLIC_CIDRS = ("0.0.0.0/0", "::/0")


def _cidr_causes(b: Block, rule: Block, kind: str) -> list:
    out = []
    for attr_name in ("cidr_blocks", "ipv6_cidr_blocks"):
        v = rule.attr(attr_name)
        if isinstance(v, list):
            for cidr in v:
                if cidr in _PUBLIC_CIDRS:
                    out.append(_cause(
                        b, f"Security group rule allows {kind} from "
                           f"public internet: {cidr!r}",
                        rule.attr_line(attr_name)))
    return out


def _check_sg_public_ingress(blocks) -> list:
    """AVD-AWS-0107 aws-ec2-no-public-ingress-sgr."""
    out = []
    for b in _resources(blocks, "aws_security_group"):
        for rule in b.find_blocks("ingress"):
            out.extend(_cidr_causes(b, rule, "ingress"))
    for b in _resources(blocks, "aws_security_group_rule"):
        if b.attr("type") == "ingress":
            out.extend(_cidr_causes(b, b, "ingress"))
    return out


def _check_sg_public_egress(blocks) -> list:
    """AVD-AWS-0104 aws-ec2-no-public-egress-sgr."""
    out = []
    for b in _resources(blocks, "aws_security_group"):
        for rule in b.find_blocks("egress"):
            out.extend(_cidr_causes(b, rule, "egress"))
    for b in _resources(blocks, "aws_security_group_rule"):
        if b.attr("type") == "egress":
            out.extend(_cidr_causes(b, b, "egress"))
    return out


def _check_sg_description(blocks) -> list:
    """AVD-AWS-0099 aws-ec2-add-description-to-security-group."""
    out = []
    for b in _resources(blocks, "aws_security_group"):
        d = b.attr("description")
        if d is None or d == "":
            out.append(_cause(
                b, "Security group does not have a description."))
    return out


# ------------------------------------------------------------------ IAM

def _policy_docs(b: Block):
    """Inline policy JSON documents in a policy attr (jsonencode is a
    call → Unresolved, but heredoc/literal JSON is resolvable)."""
    import json
    v = b.attr("policy")
    if isinstance(v, str):
        try:
            return [json.loads(v)]
        except ValueError:
            return []
    return []


def _check_iam_wildcards(blocks) -> list:
    """AVD-AWS-0057 aws-iam-no-policy-wildcards."""
    out = []
    for rtype in ("aws_iam_policy", "aws_iam_role_policy",
                  "aws_iam_user_policy", "aws_iam_group_policy"):
        for b in _resources(blocks, rtype):
            for doc in _policy_docs(b):
                stmts = doc.get("Statement") or []
                if isinstance(stmts, dict):
                    stmts = [stmts]
                for s in stmts:
                    if s.get("Effect", "Allow") != "Allow":
                        continue
                    for key in ("Action", "Resource"):
                        vals = s.get(key)
                        vals = [vals] if isinstance(vals, str) \
                            else (vals or [])
                        for v in vals:
                            if v == "*":
                                out.append(_cause(
                                    b, f"IAM policy document uses "
                                       f"wildcard {key.lower()} "
                                       f"'{v}'",
                                    b.attr_line("policy")))
    return out


# ---------------------------------------------------------- EC2/EBS/RDS

def _check_imds_tokens(blocks) -> list:
    """AVD-AWS-0028 aws-ec2-enforce-http-token-imds."""
    out = []
    for b in _resources(blocks, "aws_instance") + \
            _resources(blocks, "aws_launch_template"):
        mo = b.first_block("metadata_options")
        if mo is None:
            out.append(_cause(
                b, "Instance does not require IMDS access to require "
                   "a token"))
            continue
        v = mo.attr("http_tokens")
        if v is not None and not isinstance(v, Unresolved) \
                and v != "required":
            out.append(_cause(
                b, "Instance does not require IMDS access to require "
                   "a token", mo.attr_line("http_tokens")))
    return out


def _check_ebs_encryption(blocks) -> list:
    """AVD-AWS-0026 aws-ebs-enable-volume-encryption."""
    out = []
    for b in _resources(blocks, "aws_ebs_volume"):
        v = b.attr("encrypted")
        if v is True or isinstance(v, Unresolved):
            continue
        out.append(_cause(
            b, "EBS volume does not have encryption enabled",
            b.attr_line("encrypted") if "encrypted" in b.attrs else 0))
    for b in _resources(blocks, "aws_instance"):
        for dev in (b.find_blocks("root_block_device")
                    + b.find_blocks("ebs_block_device")):
            v = dev.attr("encrypted")
            if v is True or isinstance(v, Unresolved):
                continue
            out.append(_cause(
                b, "Block device does not have encryption enabled",
                dev.start_line))
    return out


def _check_rds_encryption(blocks) -> list:
    """AVD-AWS-0080 aws-rds-encrypt-instance-storage-data."""
    out = []
    for b in _resources(blocks, "aws_db_instance"):
        v = b.attr("storage_encrypted")
        if v is True or isinstance(v, Unresolved):
            continue
        out.append(_cause(
            b, "Instance does not have storage encryption enabled",
            b.attr_line("storage_encrypted")
            if "storage_encrypted" in b.attrs else 0))
    return out


def _p(pid, avd, title, sev, service, check, actions="",
       refs=()) -> Policy:
    return Policy(
        id=pid, avd_id=avd, title=title,
        description=title, severity=sev,
        recommended_actions=actions, references=list(refs),
        provider="AWS", service=service, check=check)


TERRAFORM_POLICIES = [
    _p("AVD-AWS-0094", "AVD-AWS-0094",
       "S3 buckets should each define an aws_s3_bucket_public_access_block",
       "LOW", "s3", _check_s3_public_access_block),
    _p("AVD-AWS-0086", "AVD-AWS-0086",
       "S3 Access block should block public ACL",
       "HIGH", "s3", _pab_flag_check(
           "block_public_acls",
           "Public access block does not block public ACLs")),
    _p("AVD-AWS-0087", "AVD-AWS-0087",
       "S3 Access block should block public policy",
       "HIGH", "s3", _pab_flag_check(
           "block_public_policy",
           "Public access block does not block public policies")),
    _p("AVD-AWS-0091", "AVD-AWS-0091",
       "S3 Access Block should Ignore Public Acl",
       "HIGH", "s3", _pab_flag_check(
           "ignore_public_acls",
           "Public access block does not ignore public ACLs")),
    _p("AVD-AWS-0092", "AVD-AWS-0092",
       "S3 buckets should not be publicly accessible via ACL",
       "HIGH", "s3", _check_s3_public_acl),
    _p("AVD-AWS-0088", "AVD-AWS-0088",
       "Unencrypted S3 bucket",
       "HIGH", "s3", _check_s3_encryption),
    _p("AVD-AWS-0090", "AVD-AWS-0090",
       "S3 Data should be versioned",
       "MEDIUM", "s3", _check_s3_versioning),
    _p("AVD-AWS-0089", "AVD-AWS-0089",
       "S3 Bucket Logging",
       "LOW", "s3", _check_s3_logging),
    _p("AVD-AWS-0107", "AVD-AWS-0107",
       "An ingress security group rule allows traffic from /0",
       "CRITICAL", "ec2", _check_sg_public_ingress),
    _p("AVD-AWS-0104", "AVD-AWS-0104",
       "An egress security group rule allows traffic to /0",
       "CRITICAL", "ec2", _check_sg_public_egress),
    _p("AVD-AWS-0099", "AVD-AWS-0099",
       "Missing description for security group",
       "LOW", "ec2", _check_sg_description),
    _p("AVD-AWS-0057", "AVD-AWS-0057",
       "IAM policy should avoid use of wildcards",
       "HIGH", "iam", _check_iam_wildcards),
    _p("AVD-AWS-0028", "AVD-AWS-0028",
       "aws_instance should activate session tokens for Instance "
       "Metadata Service (IMDSv2)",
       "HIGH", "ec2", _check_imds_tokens),
    _p("AVD-AWS-0026", "AVD-AWS-0026",
       "EBS volumes must be encrypted",
       "HIGH", "ebs", _check_ebs_encryption),
    _p("AVD-AWS-0080", "AVD-AWS-0080",
       "RDS encryption has not been enabled at a DB Instance level",
       "HIGH", "rds", _check_rds_encryption),
]
