"""CloudFormation misconfiguration checks.

The reference routes CloudFormation templates through defsec's
cfscanner (/root/reference/pkg/fanal/handler/misconf/misconf.go:25).
This walker evaluates the same core AWS checks (shared AVD IDs with
the Terraform set) directly over the template's ``Resources`` map.

YAML templates use intrinsic tags (!Ref, !GetAtt, !Sub...); a
tolerant loader maps them to ``Intrinsic`` markers so parsing never
fails and checks treat them as unresolvable (never a provable FAIL).
"""

from __future__ import annotations

import json
from typing import Optional

from .policies import Cause, Policy

try:
    import yaml as yaml_mod
except ImportError:          # pragma: no cover
    yaml_mod = None


class Intrinsic:
    """An unresolved CFN intrinsic (!Ref / Fn::* / !Sub ...)."""

    __slots__ = ("tag", "value")

    def __init__(self, tag: str, value):
        self.tag = tag
        self.value = value

    def __repr__(self):
        return f"Intrinsic({self.tag})"

    def __bool__(self):
        return False


def _make_loader():
    class _Loader(yaml_mod.SafeLoader):
        pass

    def intrinsic(loader, tag_suffix, node):
        if isinstance(node, yaml_mod.ScalarNode):
            v = loader.construct_scalar(node)
        elif isinstance(node, yaml_mod.SequenceNode):
            v = loader.construct_sequence(node)
        else:
            v = loader.construct_mapping(node)
        return Intrinsic(tag_suffix, v)

    _Loader.add_multi_constructor("!", intrinsic)
    return _Loader


def parse_template(content: bytes) -> Optional[dict]:
    """Parse a CFN template (JSON or YAML); None if not CFN-shaped."""
    text = content.decode("utf-8", "replace")
    doc = None
    stripped = text.lstrip()
    if stripped.startswith("{"):
        try:
            doc = json.loads(text)
        except ValueError:
            return None
    elif yaml_mod is not None:
        try:
            doc = yaml_mod.load(text, Loader=_make_loader())
        except yaml_mod.YAMLError:
            return None
    if not isinstance(doc, dict):
        return None
    if "AWSTemplateFormatVersion" not in doc and \
            "Resources" not in doc:
        return None
    resources = doc.get("Resources")
    if not isinstance(resources, dict) or not all(
            isinstance(r, dict) and "Type" in r
            for r in resources.values()):
        return None
    return doc


def is_cloudformation(content: bytes) -> bool:
    return parse_template(content) is not None


def _rs(doc: dict, rtype: str) -> list:
    """[(logical name, properties dict)] for one resource type."""
    out = []
    for name, r in (doc.get("Resources") or {}).items():
        if isinstance(r, dict) and r.get("Type") == rtype:
            props = r.get("Properties")
            out.append((name, props if isinstance(props, dict)
                        else {}))
    return out


def _cause(name: str, msg: str) -> Cause:
    return Cause(message=msg, resource=name)


# ------------------------------------------------------------------- S3

def _check_s3_public_access_block(doc) -> list:
    out = []
    for name, props in _rs(doc, "AWS::S3::Bucket"):
        v = props.get("PublicAccessBlockConfiguration")
        if isinstance(v, (dict, Intrinsic)):
            continue      # present, or unresolvable (!If whole-prop)
        out.append(_cause(
            name, "Bucket does not have a corresponding public "
                  "access block."))
    return out


def _pab_flag_check(flag: str, message: str):
    def check(doc) -> list:
        out = []
        for name, props in _rs(doc, "AWS::S3::Bucket"):
            pab = props.get("PublicAccessBlockConfiguration")
            if not isinstance(pab, dict):
                continue
            v = pab.get(flag)
            if v is True or isinstance(v, Intrinsic):
                continue
            out.append(_cause(name, message))
        return out
    return check


def _check_s3_encryption(doc) -> list:
    out = []
    for name, props in _rs(doc, "AWS::S3::Bucket"):
        v = props.get("BucketEncryption")
        if v or isinstance(v, Intrinsic):
            continue
        out.append(_cause(
            name, "Bucket does not have encryption enabled"))
    return out


def _check_s3_versioning(doc) -> list:
    out = []
    for name, props in _rs(doc, "AWS::S3::Bucket"):
        vc = props.get("VersioningConfiguration")
        if isinstance(vc, Intrinsic):
            continue      # whole-property !If: unresolvable
        status = vc.get("Status") if isinstance(vc, dict) else None
        if status != "Enabled" and not isinstance(status, Intrinsic):
            out.append(_cause(
                name, "Bucket does not have versioning enabled"))
    return out


def _check_s3_public_acl(doc) -> list:
    out = []
    for name, props in _rs(doc, "AWS::S3::Bucket"):
        acl = props.get("AccessControl")
        if isinstance(acl, str) and acl in (
                "PublicRead", "PublicReadWrite", "AuthenticatedRead"):
            out.append(_cause(
                name, f"Bucket has a public ACL: {acl!r}."))
    return out


# -------------------------------------------------------- security group

_PUBLIC_CIDRS = ("0.0.0.0/0", "::/0")


def _sg_rule_causes(name, rules, kind) -> list:
    out = []
    if not isinstance(rules, list):
        return out
    for rule in rules:
        if not isinstance(rule, dict):
            continue
        for key in ("CidrIp", "CidrIpv6"):
            v = rule.get(key)
            if v in _PUBLIC_CIDRS:
                out.append(_cause(
                    name, f"Security group rule allows {kind} from "
                          f"public internet: {v!r}"))
    return out


def _check_sg_public_ingress(doc) -> list:
    out = []
    for name, props in _rs(doc, "AWS::EC2::SecurityGroup"):
        out.extend(_sg_rule_causes(
            name, props.get("SecurityGroupIngress"), "ingress"))
    for name, props in _rs(doc, "AWS::EC2::SecurityGroupIngress"):
        out.extend(_sg_rule_causes(name, [props], "ingress"))
    return out


def _check_sg_public_egress(doc) -> list:
    out = []
    for name, props in _rs(doc, "AWS::EC2::SecurityGroup"):
        out.extend(_sg_rule_causes(
            name, props.get("SecurityGroupEgress"), "egress"))
    return out


def _check_sg_description(doc) -> list:
    out = []
    for name, props in _rs(doc, "AWS::EC2::SecurityGroup"):
        v = props.get("GroupDescription")
        if v or isinstance(v, Intrinsic):
            continue
        out.append(_cause(
            name, "Security group does not have a description."))
    return out


# ------------------------------------------------------------------ IAM

def _check_iam_wildcards(doc) -> list:
    out = []
    for rtype in ("AWS::IAM::Policy", "AWS::IAM::ManagedPolicy",
                  "AWS::IAM::Role", "AWS::IAM::User",
                  "AWS::IAM::Group"):
        for name, props in _rs(doc, rtype):
            docs = []
            if isinstance(props.get("PolicyDocument"), dict):
                docs.append(props["PolicyDocument"])
            for p in props.get("Policies") or []:
                if isinstance(p, dict) and \
                        isinstance(p.get("PolicyDocument"), dict):
                    docs.append(p["PolicyDocument"])
            for d in docs:
                stmts = d.get("Statement") or []
                if isinstance(stmts, dict):
                    stmts = [stmts]
                for s in stmts:
                    if not isinstance(s, dict) or \
                            s.get("Effect", "Allow") != "Allow":
                        continue
                    for key in ("Action", "Resource"):
                        vals = s.get(key)
                        vals = [vals] if isinstance(vals, str) \
                            else (vals or [])
                        if "*" in [v for v in vals
                                   if isinstance(v, str)]:
                            out.append(_cause(
                                name, f"IAM policy document uses "
                                      f"wildcard {key.lower()} '*'"))
    return out


# ------------------------------------------------------------- EBS/RDS

def _check_ebs_encryption(doc) -> list:
    out = []
    for name, props in _rs(doc, "AWS::EC2::Volume"):
        v = props.get("Encrypted")
        if v is True or isinstance(v, Intrinsic):
            continue
        out.append(_cause(
            name, "EBS volume does not have encryption enabled"))
    return out


def _check_rds_encryption(doc) -> list:
    out = []
    for name, props in _rs(doc, "AWS::RDS::DBInstance"):
        v = props.get("StorageEncrypted")
        if v is True or isinstance(v, Intrinsic):
            continue
        out.append(_cause(
            name, "Instance does not have storage encryption "
                  "enabled"))
    return out


def _p(pid, title, sev, service, check) -> Policy:
    return Policy(
        id=pid, avd_id=pid, title=title, description=title,
        severity=sev, recommended_actions="", references=[],
        provider="AWS", service=service, check=check)


CLOUDFORMATION_POLICIES = [
    _p("AVD-AWS-0094",
       "S3 buckets should each define an "
       "aws_s3_bucket_public_access_block",
       "LOW", "s3", _check_s3_public_access_block),
    _p("AVD-AWS-0086", "S3 Access block should block public ACL",
       "HIGH", "s3", _pab_flag_check(
           "BlockPublicAcls",
           "Public access block does not block public ACLs")),
    _p("AVD-AWS-0087", "S3 Access block should block public policy",
       "HIGH", "s3", _pab_flag_check(
           "BlockPublicPolicy",
           "Public access block does not block public policies")),
    _p("AVD-AWS-0091", "S3 Access Block should Ignore Public Acl",
       "HIGH", "s3", _pab_flag_check(
           "IgnorePublicAcls",
           "Public access block does not ignore public ACLs")),
    _p("AVD-AWS-0092",
       "S3 buckets should not be publicly accessible via ACL",
       "HIGH", "s3", _check_s3_public_acl),
    _p("AVD-AWS-0088", "Unencrypted S3 bucket",
       "HIGH", "s3", _check_s3_encryption),
    _p("AVD-AWS-0090", "S3 Data should be versioned",
       "MEDIUM", "s3", _check_s3_versioning),
    _p("AVD-AWS-0107",
       "An ingress security group rule allows traffic from /0",
       "CRITICAL", "ec2", _check_sg_public_ingress),
    _p("AVD-AWS-0104",
       "An egress security group rule allows traffic to /0",
       "CRITICAL", "ec2", _check_sg_public_egress),
    _p("AVD-AWS-0099", "Missing description for security group",
       "LOW", "ec2", _check_sg_description),
    _p("AVD-AWS-0057", "IAM policy should avoid use of wildcards",
       "HIGH", "iam", _check_iam_wildcards),
    _p("AVD-AWS-0026", "EBS volumes must be encrypted",
       "HIGH", "ebs", _check_ebs_encryption),
    _p("AVD-AWS-0080",
       "RDS encryption has not been enabled at a DB Instance level",
       "HIGH", "rds", _check_rds_encryption),
]
