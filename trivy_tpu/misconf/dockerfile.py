"""Dockerfile instruction parser for policy evaluation.

Line-oriented with continuation handling, comment stripping, and
multi-stage tracking — the subset of buildkit's parser the built-in
checks need (reference: defsec's dockerfile parser feeding its
Go checks).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Instruction:
    cmd: str                  # upper-cased, e.g. "FROM", "USER"
    value: str                # raw argument string
    start_line: int = 0
    end_line: int = 0
    flags: list = field(default_factory=list)   # --flag=... args


@dataclass
class Stage:
    name: str                 # "AS" name or the base image ref
    base: str                 # base image ref
    alias: str = ""           # explicit "AS" name only
    instructions: list = field(default_factory=list)
    start_line: int = 0


def _take_token(s: str) -> tuple:
    """Split off the leading token, treating quoted spans as atomic —
    a flag value like ``--mount=type=secret,id="my id"`` must not
    leak its tail into the instruction value (buildkit's shell-word
    flag lexing)."""
    j, q = 0, ""
    while j < len(s):
        ch = s[j]
        if q:
            if ch == q:
                q = ""
        elif ch in "\"'":
            q = ch
        elif ch.isspace():
            break
        j += 1
    return s[:j], s[j:].strip()


def parse(content: bytes) -> list:
    """→ list[Stage]; a file with no FROM yields one anonymous
    stage so instruction-level checks still run."""
    stages: list = []
    cur: Stage = None
    lines = content.decode("utf-8", "replace").splitlines()

    i = 0
    while i < len(lines):
        raw = lines[i].strip()
        start = i + 1
        if not raw or raw.startswith("#"):
            i += 1
            continue
        # continuations; blank and comment lines inside a
        # continuation are skipped (buildkit accepts them)
        while raw.endswith("\\") and i + 1 < len(lines):
            i += 1
            nxt = lines[i].strip()
            if not nxt or nxt.startswith("#"):
                continue
            raw = raw[:-1].rstrip() + " " + nxt
        end = i + 1
        i += 1

        parts = raw.split(None, 1)
        cmd = parts[0].upper()
        rest = parts[1] if len(parts) > 1 else ""
        flags = []
        while rest.startswith("--"):
            flag, rest = _take_token(rest)
            flags.append(flag)
        inst = Instruction(cmd=cmd, value=rest, start_line=start,
                           end_line=end, flags=flags)

        if cmd == "FROM":
            tokens = rest.split()
            base = tokens[0] if tokens else ""
            name, alias = base, ""
            for j, t in enumerate(tokens):
                if t.upper() == "AS" and j + 1 < len(tokens):
                    name = alias = tokens[j + 1]
            cur = Stage(name=name, base=base, alias=alias,
                        start_line=start)
            stages.append(cur)
            continue
        if cur is None:
            cur = Stage(name="", base="", start_line=start)
            stages.append(cur)
        cur.instructions.append(inst)
    return stages
