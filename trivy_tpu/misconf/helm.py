"""Helm chart rendering for misconfiguration scanning.

The reference renders charts through defsec's helm scanner with
value-file overrides (/root/reference/pkg/fanal/handler/misconf/
misconf.go:210-227 ScannerWithValuesFile/...WithValues). This module
implements a Go-template SUBSET sufficient to render typical chart
manifests into Kubernetes documents, which then flow through the same
Kubernetes policy set:

  - ``{{ .Values.a.b }}`` / ``{{ .Release.Name }}`` / ``{{ .Chart.Name
    }}`` value references (with ``-`` whitespace trimming)
  - ``|`` pipelines with ``default``, ``quote``, ``upper``, ``lower``
  - ``{{ if <ref> }} ... {{ else }} ... {{ end }}`` truthiness blocks
  - ``{{ include "..." . }}`` and other unsupported actions render
    empty (charts that depend on them still render their scalar
    fields, which is what the checks read)

Values precedence mirrors helm: chart values.yaml, then ``--helm-values``
files, then ``--set``-style string values — later wins.
"""

from __future__ import annotations

import posixpath
import re
from typing import Optional

try:
    import yaml as yaml_mod
except ImportError:          # pragma: no cover
    yaml_mod = None


def find_charts(paths: list) -> dict:
    """Group collected file paths into charts:
    {chart_root: [template paths]} for every directory holding a
    Chart.yaml with a templates/ subtree among ``paths``."""
    roots = {posixpath.dirname(p) for p in paths
             if posixpath.basename(p) == "Chart.yaml"}
    charts = {}
    for root in roots:
        tpl_prefix = posixpath.join(root, "templates") + "/"
        tpls = [p for p in paths if p.startswith(tpl_prefix)
                and p.endswith((".yaml", ".yml", ".tpl"))]
        if tpls:
            charts[root] = sorted(tpls)
    return charts


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def chart_values(files: dict, root: str,
                 value_overrides: Optional[list] = None,
                 set_values: Optional[list] = None) -> dict:
    """values.yaml + --helm-values files + --set pairs (later wins)."""
    values: dict = {}
    vpath = posixpath.join(root, "values.yaml")
    if vpath in files and yaml_mod is not None:
        try:
            v = yaml_mod.safe_load(
                files[vpath].decode("utf-8", "replace"))
            if isinstance(v, dict):
                values = v
        except yaml_mod.YAMLError:
            pass
    for content in value_overrides or []:
        try:
            v = yaml_mod.safe_load(content)
            if isinstance(v, dict):
                values = _deep_merge(values, v)
        except yaml_mod.YAMLError:
            pass
    for pair in set_values or []:
        if "=" not in pair:
            continue
        key, _, val = pair.partition("=")
        node = values = dict(values)
        parts = key.split(".")
        for p in parts[:-1]:
            nxt = node.get(p)
            nxt = dict(nxt) if isinstance(nxt, dict) else {}
            node[p] = nxt
            node = nxt
        node[parts[-1]] = yaml_mod.safe_load(val) \
            if yaml_mod is not None else val
    return values


_ACTION_RE = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.DOTALL)
_TRIM_LEFT_RE = re.compile(r"[ \t]*\n?[ \t]*\{\{-")
_TRIM_RIGHT_RE = re.compile(r"-\}\}[ \t]*\n?")


def _lookup(ref: str, scope: dict):
    cur = scope
    for part in ref.split("."):
        if not part:
            continue
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None
    return cur


def _eval_expr(expr: str, scope: dict):
    """One pipeline expression → value (None if unresolvable)."""
    stages = [s.strip() for s in expr.split("|")]
    head = stages[0]
    if head.startswith('"') and head.endswith('"'):
        val = head[1:-1]
    elif head.startswith("."):
        val = _lookup(head[1:], scope)
    elif re.fullmatch(r"-?\d+(\.\d+)?", head):
        val = float(head) if "." in head else int(head)
    elif head in ("true", "false"):
        val = head == "true"
    else:
        return None
    for stage in stages[1:]:
        parts = stage.split(None, 1)
        fn = parts[0]
        arg = parts[1].strip() if len(parts) > 1 else ""
        if fn == "default":
            if val in (None, "", False):
                val = _eval_expr(arg, scope)
        elif fn == "quote":
            val = f'"{val if val is not None else ""}"'
        elif fn == "upper" and isinstance(val, str):
            val = val.upper()
        elif fn == "lower" and isinstance(val, str):
            val = val.lower()
        elif fn in ("toYaml", "nindent", "indent", "trim"):
            # formatting helpers for nested structures are outside
            # the subset: drop the value rather than emit garbage
            if fn in ("nindent", "indent"):
                return None
    return val


def render(template: str, values: dict, release: str = "release",
           chart_name: str = "chart") -> str:
    """Render one template with the action subset. Unknown actions
    render as empty text."""
    scope = {
        "Values": values,
        "Release": {"Name": release, "Namespace": "default",
                    "Service": "Helm"},
        "Chart": {"Name": chart_name, "Version": "0.1.0"},
    }
    # normalize whitespace-trim markers so plain re substitution works
    text = _TRIM_LEFT_RE.sub("{{", template)
    text = _TRIM_RIGHT_RE.sub("}}", text)

    out = []
    pos = 0
    # if/else-if/else nesting: each frame tracks whether the current
    # branch emits and whether ANY branch of the chain has already
    # been taken (an else/else-if after a taken branch never emits)
    emit_stack = [{"emit": True, "done": True}]

    def _emitting():
        return all(f["emit"] for f in emit_stack)

    for m in _ACTION_RE.finditer(text):
        if _emitting():
            out.append(text[pos:m.start()])
        pos = m.end()
        action = m.group(1).strip()
        if action.startswith("if "):
            cond = bool(_eval_expr(action[3:].strip(), scope))
            emit_stack.append({"emit": cond, "done": cond})
        elif action.startswith("else if "):
            f = emit_stack[-1]
            if f["done"]:
                f["emit"] = False
            else:
                cond = bool(_eval_expr(action[8:].strip(), scope))
                f["emit"] = cond
                f["done"] = cond
        elif action == "else":
            f = emit_stack[-1]
            f["emit"] = not f["done"]
            f["done"] = True
        elif action == "end":
            if len(emit_stack) > 1:
                emit_stack.pop()
        elif action.startswith(("range ", "with ", "define ",
                                "include", "template", "/*")):
            # outside the subset: ranges/includes render empty; a
            # define..end swallows its body via the emit stack
            if action.startswith(("range ", "with ", "define ")):
                emit_stack.append({"emit": False, "done": True})
        else:
            if _emitting():
                v = _eval_expr(action, scope)
                if v is not None:
                    out.append(str(v))
    if _emitting():
        out.append(text[pos:])
    return "".join(out)


def render_chart(files: dict, root: str, tpl_paths: list,
                 value_overrides: Optional[list] = None,
                 set_values: Optional[list] = None) -> dict:
    """{template path: rendered text} for one chart."""
    values = chart_values(files, root, value_overrides, set_values)
    chart_name = posixpath.basename(root) or "chart"
    out = {}
    for p in tpl_paths:
        if p.endswith(".tpl"):
            continue        # helper definitions, not manifests
        src = files[p].decode("utf-8", "replace")
        out[p] = render(src, values, chart_name=chart_name)
    return out
