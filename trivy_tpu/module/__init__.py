"""In-process extension modules (reference: pkg/module — WASM via
wazero).

The reference loads ``~/.trivy/modules/*.wasm`` and registers each as
an analyzer and/or post-scanner through a handshake of exports
(module.go:573-680). The TPU-native analog loads
``~/.trivy-tpu/modules/*.py`` with the same handshake as module-level
attributes:

    name = "spring4shell"
    version = 1
    api_version = 1
    is_analyzer = True          # implement required()/analyze()
    is_post_scanner = True      # implement post_scan(results)
    required_files = [r"\\.java$"]   # regex list, like Required()

Analyzer modules see (path, content) and return a dict of custom
resource data (surfaced as CustomResources); post-scanner modules
rewrite the results list (INSERT/UPDATE/DELETE by returning the
modified list, api/api.go's action set collapsed into
return-the-new-results).
"""

from __future__ import annotations

import os
import re
import types as types_mod
from typing import Optional

from ..analyzer.analyzer import (AnalysisResult, Analyzer,
                                 register_analyzer)
from ..scan.post import register_post_scanner
from ..types.artifact import CustomResource
from ..utils import get_logger

log = get_logger("module")

SUPPORTED_API_VERSION = 1

# absolute paths already registered this process — repeated
# cli.main() calls must not re-register analyzers (the global
# analyzer registry appends without dedup)
_LOADED: set = set()


def modules_dir() -> str:
    return os.environ.get(
        "TRIVY_MODULE_DIR",
        os.path.join(os.path.expanduser("~"), ".trivy-tpu",
                     "modules"))


class _ModuleAnalyzer(Analyzer):
    def __init__(self, mod):
        self.mod = mod
        self.type = f"module:{mod.name}"
        self.version = getattr(mod, "version", 1)
        self._patterns = [re.compile(p) for p in
                          getattr(mod, "required_files", [])]

    def required(self, path: str, size: Optional[int] = None) -> bool:
        if hasattr(self.mod, "required"):
            return bool(self.mod.required(path, size))
        return any(p.search(path) for p in self._patterns)

    def analyze(self, path: str, content: bytes) -> AnalysisResult:
        r = AnalysisResult()
        data = self.mod.analyze(path, content)
        if data:
            r.custom_resources.append(CustomResource(
                type=self.type, file_path=path, data=data))
        return r


class _ModulePostScanner:
    def __init__(self, mod):
        self.mod = mod
        self.name = mod.name
        self.version = getattr(mod, "version", 1)

    def post_scan(self, results: list) -> list:
        return self.mod.post_scan(results)


class Manager:
    """Loads and registers modules (ref module.go:80-149)."""

    def __init__(self, directory: str = ""):
        self.directory = directory or modules_dir()
        self.modules: list = []

    def load(self) -> list:
        if not os.path.isdir(self.directory):
            return []
        for fname in sorted(os.listdir(self.directory)):
            if not fname.endswith(".py") or fname.startswith("_"):
                continue
            path = os.path.abspath(
                os.path.join(self.directory, fname))
            if path in _LOADED:
                continue
            try:
                mod = self._load_one(path)
                _LOADED.add(path)
            except Exception as e:      # noqa: BLE001 — a broken
                # module must not brick the scanner
                log.warning("failed to load module %s: %r",
                            path, e)
                continue
            self.modules.append(mod)
        return self.modules

    def _load_one(self, path: str):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        mod = types_mod.ModuleType(
            "trivy_module_" +
            os.path.basename(path).removesuffix(".py"))
        exec(compile(source, path, "exec"), mod.__dict__)
        name = getattr(mod, "name", "")
        api = getattr(mod, "api_version", 1)
        if not name:
            raise ValueError("module must set `name`")
        if api > SUPPORTED_API_VERSION:
            raise ValueError(
                f"module {name} requires api_version {api} > "
                f"{SUPPORTED_API_VERSION}")
        if getattr(mod, "is_analyzer", False):
            register_analyzer(_ModuleAnalyzer(mod))
            log.info("registered module analyzer %s", name)
        if getattr(mod, "is_post_scanner", False):
            register_post_scanner(_ModulePostScanner(mod))
            log.info("registered module post-scanner %s", name)
        return mod
