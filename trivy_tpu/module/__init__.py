"""In-process extension modules (reference: pkg/module — WASM via
wazero).

The reference loads ``~/.trivy/modules/*.wasm`` and registers each as
an analyzer and/or post-scanner through a handshake of exports
(module.go:573-680). The TPU-native analog loads
``~/.trivy-tpu/modules/*.py`` with the same handshake as module-level
attributes:

    name = "spring4shell"
    version = 1
    api_version = 1
    is_analyzer = True          # implement required()/analyze()
    is_post_scanner = True      # implement post_scan(results)
    required_files = [r"\\.java$"]   # regex list, like Required()

Analyzer modules see (path, content) and return either a dict with
EXACTLY the keys ``{"type", "data"}`` — a self-typed custom resource
(serialize.CustomResource shape: the declared type plus a bare
payload) — or any other dict, stored opaquely under the module's own
``module:<name>`` type. Payload dicts that legitimately need keys
named type+data must add any third key to stay opaque.
Post-scanner modules
rewrite the results list (INSERT/UPDATE/DELETE by returning the
modified list, api/api.go's action set collapsed into
return-the-new-results).
"""

from __future__ import annotations

import os
import re
import types as types_mod
from typing import Optional

from ..analyzer.analyzer import (AnalysisResult, Analyzer,
                                 register_analyzer)
from ..scan.post import register_post_scanner
from ..types.artifact import CustomResource
from ..utils import get_logger

log = get_logger("module")

SUPPORTED_API_VERSION = 1

# absolute paths already registered this process — repeated
# cli.main() calls must not re-register analyzers (the global
# analyzer registry appends without dedup)
_LOADED: set = set()


def modules_dir() -> str:
    return os.environ.get(
        "TRIVY_MODULE_DIR",
        os.path.join(os.path.expanduser("~"), ".trivy-tpu",
                     "modules"))


class _ModuleAnalyzer(Analyzer):
    def __init__(self, mod):
        self.mod = mod
        self.type = f"module:{mod.name}"
        self.version = getattr(mod, "version", 1)
        self._patterns = [re.compile(p) for p in
                          getattr(mod, "required_files", [])]

    def required(self, path: str, size: Optional[int] = None) -> bool:
        if hasattr(self.mod, "required"):
            return bool(self.mod.required(path, size))
        return any(p.search(path) for p in self._patterns)

    def analyze(self, path: str, content: bytes) -> AnalysisResult:
        r = AnalysisResult()
        # modules see rooted paths (module.go:390 prefixes "/")
        file_path = path if path.startswith("/") else "/" + path
        data = self.mod.analyze(file_path, content)
        if data:
            rtype, payload = self.type, data
            if isinstance(data, dict) and \
                    set(data) == {"type", "data"}:
                # EXACTLY {type, data}: the module declares its own
                # resource type + bare payload
                # (serialize.CustomResource{Type, Data} shape);
                # any other dict is an opaque legacy payload
                rtype, payload = str(data["type"]), data["data"]
            r.custom_resources.append(CustomResource(
                type=rtype, file_path=file_path, data=payload))
        return r


class _ModulePostScanner:
    def __init__(self, mod):
        self.mod = mod
        self.name = mod.name
        self.version = getattr(mod, "version", 1)

    def post_scan(self, results: list) -> list:
        return self.mod.post_scan(results)


class Manager:
    """Loads and registers modules (ref module.go:80-149)."""

    def __init__(self, directory: str = ""):
        self.directory = directory or modules_dir()
        self.modules: list = []

    def load(self) -> list:
        if not os.path.isdir(self.directory):
            return []
        for fname in sorted(os.listdir(self.directory)):
            if not fname.endswith(".py") or fname.startswith("_"):
                continue
            path = os.path.abspath(
                os.path.join(self.directory, fname))
            if path in _LOADED:
                continue
            try:
                mod = self._load_one(path)
                _LOADED.add(path)
            except Exception as e:      # noqa: BLE001 — a broken
                # module must not brick the scanner
                log.warning("failed to load module %s: %r",
                            path, e)
                continue
            self.modules.append(mod)
        return self.modules

    def _load_one(self, path: str):
        mod = _exec_module(path)
        name = mod.name
        if getattr(mod, "is_analyzer", False):
            register_analyzer(_ModuleAnalyzer(mod))
            log.info("registered module analyzer %s", name)
        if getattr(mod, "is_post_scanner", False):
            register_post_scanner(_ModulePostScanner(mod))
            log.info("registered module post-scanner %s", name)
        return mod


# --- management commands (ref pkg/commands/app.go:693 + pkg/module
# Install/Uninstall; the reference pulls modules from an OCI
# repository — the registry fetch is the documented egress seam, so
# install here takes a local .py file or a directory of them) ---

def _exec_module(path: str):
    """Execute a module file and check the handshake: it must set
    `name` and a supported `api_version` (module.go's export
    validation). Shared by loading, install validation and
    listing. Any exec-time failure surfaces as ValueError so
    callers print one clean error."""
    mod = types_mod.ModuleType(
        "trivy_module_" +
        os.path.basename(path).removesuffix(".py"))
    try:
        with open(path, encoding="utf-8") as f:
            exec(compile(f.read(), path, "exec"), mod.__dict__)
    except Exception as e:          # noqa: BLE001 — module code
        # can fail arbitrarily; it must not traceback the CLI
        raise ValueError(f"{path}: {e!r}") from e
    if not getattr(mod, "name", ""):
        raise ValueError(f"{path}: module must set `name`")
    api = getattr(mod, "api_version", 1)
    if api > SUPPORTED_API_VERSION:
        raise ValueError(
            f"{path}: module {mod.name} requires api_version "
            f"{api} > {SUPPORTED_API_VERSION}")
    return mod


def install(source: str, directory: str = "") -> list:
    """Copy module file(s) into the modules dir. Every file is
    validated before any is copied, so a bad file in a directory
    install leaves nothing half-installed. → installed names."""
    import shutil
    directory = directory or modules_dir()
    if os.path.isfile(source):
        files = [source]
    elif os.path.isdir(source):
        files = [os.path.join(source, f)
                 for f in sorted(os.listdir(source))
                 if f.endswith(".py") and not f.startswith("_")]
    else:
        raise ValueError(f"no such file or directory: {source}")
    if not files:
        raise ValueError(f"no module files in {source}")
    for f in files:
        if not f.endswith(".py"):
            raise ValueError(f"not a Python module: {f}")
        _exec_module(f)
    installed = []
    os.makedirs(directory, exist_ok=True)
    for f in files:
        dest = os.path.join(directory, os.path.basename(f))
        shutil.copyfile(f, dest)
        installed.append(
            os.path.basename(f).removesuffix(".py"))
    return installed


def uninstall(name: str, directory: str = "") -> bool:
    # names are bare module stems — reject separators so a crafted
    # name cannot traverse out of the modules dir
    if name != os.path.basename(name) or ".." in name or \
            "/" in name or "\\" in name:
        return False
    directory = directory or modules_dir()
    path = os.path.join(directory, name + ".py")
    if not os.path.isfile(path):
        return False
    os.remove(path)
    return True


def list_installed(directory: str = "") -> list:
    """→ [(file-stem, declared name, version)] without registering
    anything."""
    directory = directory or modules_dir()
    if not os.path.isdir(directory):
        return []
    out = []
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith(".py") or fname.startswith("_"):
            continue
        path = os.path.join(directory, fname)
        try:
            mod = _exec_module(path)
            name, version = mod.name, getattr(mod, "version", 1)
        except ValueError:
            name, version = "<broken>", 0
        out.append((fname.removesuffix(".py"), name, version))
    return out
