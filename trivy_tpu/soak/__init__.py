"""Registry-scale soak harness (docs/robustness.md "Soak & chaos
testing"): a compressed week of production against a synthetic
million-image registry, rendered as a pass/fail verdict.

Four pieces, composable and all seeded:

* :mod:`registry` — a content-addressed synthetic registry:
  10⁵–10⁶ distinct layer identities behind generated manifests
  (index-bound, never materialized as tarballs) with the realistic
  cross-image layer reuse PR 9's fleet builder established;
* :mod:`scenario` — a declarative scenario script: typed steps on a
  virtual timeline (diurnal Poisson pushes with tenant mix, rolling
  DB hot swaps, replica kills, autoscale cycles, event storms,
  brownouts, hostile trickle), composing the ``faults/`` scenarios,
  compressed onto a wall clock;
* :mod:`runner` — drives a routed multi-replica fleet + watch loop
  + PR-13 federation through the script and enforces the global
  books invariant (fleet-wide ``lost == 0``);
* :mod:`audit` — the steady-state leak audit: RSS/fds/threads and
  every long-lived bounded structure, sampled per epoch; any series
  that grows without bound fails the run.

Surface: ``trivy-tpu soak``, ``bench.py --config soak`` (full) and
``--config soak-smoke`` (tier-1-safe), ``pytest -m soak``.
"""

from .audit import ResourceAudit
from .registry import RegistrySpec, SyntheticRegistry
from .runner import SoakRunner, run_soak
from .scenario import (SCENARIOS, Scenario, ScenarioSpec, Step,
                       load_scenario)

__all__ = [
    "ResourceAudit", "RegistrySpec", "SCENARIOS", "Scenario",
    "ScenarioSpec", "SoakRunner", "Step", "SyntheticRegistry",
    "load_scenario", "run_soak",
]
