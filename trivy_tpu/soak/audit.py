"""Steady-state resource audit: the leak detector a soak run rides.

Per epoch the audit samples the process self-stats
(obs/procstats.py: RSS, open fds, interpreter threads) plus every
long-lived *bounded-by-design* structure the fleet carries —
recorder rings and dump dirs, idempotency windows, the admission
verdict cache, tenant books, impact postings, the watch cursor's
ack window — via registered probe callables. At the end of the run
:meth:`ResourceAudit.verdict` renders one pass/fail per series: a
series that keeps growing after warm-up fails the run, because over
a compressed week "slow leak" and "unbounded" are the same thing.

The bounded-growth test is deliberately simple and robust to noise:
drop a warm-up prefix, split the rest into a head and a tail third,
and fail if the tail's *minimum* clears the head's *maximum* by
more than tolerance + slack — monotone creep trips it, a plateau
(however noisy) never does.
"""

from __future__ import annotations

import threading

from ..obs.procstats import process_self_stats

# per-series absolute slack: jitter below this never fails
_DEFAULT_SLACK = {
    "rss_bytes": 24 * 1024 * 1024,   # allocator quantization
    "open_fds": 16,                  # transient sockets
    "threads": 8,                    # pool spin-up
}
_SLACK_OTHER = 4.0                   # structure sizes (entries)


class ResourceAudit:
    """Epoch sampler + flat-after-warm-up verdict.

    ``probes`` maps series name → zero-arg callable returning a
    number (a structure's current size). Probe errors record -1 for
    that epoch (absent, never fatal — a killed replica's probe must
    not crash the audit)."""

    def __init__(self, probes: dict = None,
                 warmup_frac: float = 0.25,
                 tolerance: float = 0.10):
        self.warmup_frac = min(0.75, max(0.0, warmup_frac))
        self.tolerance = max(0.0, tolerance)
        self._lock = threading.Lock()
        self._probes: dict = dict(probes or {})
        self._ungated: set = set()
        self._series: dict = {}      # name -> [value per epoch]
        self.epochs = 0

    def add_probe(self, name: str, fn, gate: bool = True) -> None:
        """``gate=False`` records the series for visibility but
        excludes it from the verdict — for structures bounded by the
        corpus rather than by a cap (a registry index saturates at
        ``images``; within a short run it only ever grows)."""
        with self._lock:
            self._probes[name] = fn
            if not gate:
                self._ungated.add(name)
            else:
                self._ungated.discard(name)

    def sample(self) -> dict:
        """One epoch: process self-stats + every registered probe.
        Returns the sample (also appended to the series)."""
        row = dict(process_self_stats())
        with self._lock:
            probes = list(self._probes.items())
        for name, fn in probes:
            try:
                row[name] = float(fn())
            except Exception:        # noqa: BLE001 — a probe over a
                # dead replica must degrade, not kill the audit
                row[name] = -1.0
        with self._lock:
            self.epochs += 1
            for name, v in row.items():
                self._series.setdefault(name, []).append(
                    float(v))
        return row

    @staticmethod
    def _bounded(values: list, warmup_frac: float,
                 tolerance: float, slack: float) -> dict:
        """One series → verdict. ``values`` may contain -1 sentinels
        (no data that epoch) — they are ignored."""
        vals = [v for v in values if v >= 0]
        if len(vals) < 6:
            return {"ok": True, "reason": "too few samples",
                    "samples": len(vals)}
        body = vals[int(len(vals) * warmup_frac):]
        third = max(1, len(body) // 3)
        head, tail = body[:third], body[-third:]
        head_max, tail_min = max(head), min(tail)
        limit = head_max * (1.0 + tolerance) + slack
        ok = tail_min <= limit
        return {"ok": ok,
                "head_max": head_max, "tail_min": tail_min,
                "limit": round(limit, 3),
                "peak": max(vals), "last": vals[-1],
                "samples": len(vals)}

    def verdict(self) -> dict:
        """Every series judged. ``ok`` is the AND over GATED series
        — one unbounded gated series fails the soak; ungated series
        carry their verdict for the report but never fail it."""
        with self._lock:
            series = {k: list(v) for k, v in self._series.items()}
            ungated = set(self._ungated)
        out = {"ok": True, "epochs": self.epochs, "series": {}}
        for name in sorted(series):
            slack = _DEFAULT_SLACK.get(name, _SLACK_OTHER)
            v = self._bounded(series[name], self.warmup_frac,
                              self.tolerance, slack)
            v["gated"] = name not in ungated
            out["series"][name] = v
            if not v["ok"] and v["gated"]:
                out["ok"] = False
        return out

    def series(self, name: str) -> list:
        with self._lock:
            return list(self._series.get(name, ()))
