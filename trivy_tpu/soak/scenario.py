"""Declarative, seeded soak scenarios: typed steps on a virtual
timeline, compressed onto the wall clock.

A :class:`Scenario` is a pure function of its spec: ``schedule()``
returns the complete run — every push arrival (diurnal Poisson with
tenant mix and duplicate-tag bursts) and every disruption step —
as one canonical JSON document. Same seed ⇒ byte-identical schedule;
the runner merely *executes* it, so a failing soak replays exactly.

Disruption steps compose the existing ``faults/`` scenarios instead
of reinventing them: a step's ``fault`` string is parsed by
``faults.spec.parse_fault_specs`` (the comma-composition grammar,
independently derived sub-seeds included), and the runner applies
whatever the fleet expresses — storm shapes become registry push
bursts, ``replica_kill_after`` arms the kill, chaos windows steer
the live replicas' ``POST /chaos`` knobs.

The virtual clock: step/arrival times are in *virtual seconds*;
``compression`` maps them onto real time (``real = virtual /
compression``), so "a week of chaos" compresses into an afternoon —
or a tier-1-safe smoke into seconds — without touching the script.
"""

from __future__ import annotations

import hashlib
import json
import math
import random
from dataclasses import asdict, dataclass, field, replace

from ..faults.spec import combine_fault_specs, parse_fault_specs
from .registry import RegistrySpec

STEP_KINDS = (
    "storm",          # registry push burst (event-storm shape)
    "kill",           # hard-kill one replica, no drain
    "scale_up",       # add a replica to the ring
    "scale_down",     # drain → quiesce → stop one replica
    "hot_swap",       # rolling DB generation bump across replicas
    "brownout",       # error window on every replica (500s)
    "flaky",          # response-drop window (lost responses)
    "cache_outage",   # cache-tier op failure window
)


@dataclass(frozen=True)
class Step:
    """One scripted disruption at virtual time ``t``."""

    t: float                      # virtual seconds from run start
    kind: str
    duration: float = 0.0         # virtual seconds (window steps)
    value: float = 0.0            # rate for window steps (0 → 1.0)
    fault: str = ""               # faults/ spec composition string
    expect_trip: bool = False     # this step is DESIGNED to trip
                                  # the fleet SLO (gated exactly)

    def __post_init__(self):
        if self.kind not in STEP_KINDS:
            raise ValueError(
                f"unknown soak step kind {self.kind!r} "
                f"(choose from {', '.join(STEP_KINDS)})")
        if self.t < 0 or self.duration < 0:
            raise ValueError("step times must be >= 0")

    def fault_spec(self):
        """The composed FaultSpec this step carries (merged across
        comma-combined scenarios; None when the step has none)."""
        if not self.fault:
            return None
        return combine_fault_specs(parse_fault_specs(self.fault))


@dataclass(frozen=True)
class ScenarioSpec:
    """Everything a soak run derives from — one seed to rule the
    arrivals, the tenant mix, and every sub-seeded fault stream."""

    name: str = "custom"
    seed: int = 20260807
    duration_s: float = 48.0        # virtual seconds
    compression: float = 3.0        # virtual seconds per real second
    base_rate: float = 30.0         # pushes per virtual second
    diurnal_amplitude: float = 0.6  # rate swing over one "day"
    dup_rate: float = 0.2           # share of arrivals that burst
    burst: int = 3                  # max extra pushes in a burst
    registry: RegistrySpec = field(default_factory=RegistrySpec)
    steps: tuple = ()

    def __post_init__(self):
        if self.duration_s <= 0 or self.compression <= 0:
            raise ValueError("duration and compression must be > 0")
        if self.base_rate <= 0:
            raise ValueError("base_rate must be > 0")
        for st in self.steps:
            if st.t > self.duration_s:
                raise ValueError(
                    f"step {st.kind!r} at t={st.t} lands after "
                    f"duration {self.duration_s}")


class Scenario:
    """A spec plus its deterministic schedule."""

    def __init__(self, spec: ScenarioSpec):
        self.spec = spec
        self._schedule = None

    def rate_at(self, t: float) -> float:
        """Diurnal arrival rate: one sinusoidal "day" spans the run
        (peak mid-run), swinging ``diurnal_amplitude`` around the
        base rate — the day/night shape real registries show."""
        s = self.spec
        phase = 2.0 * math.pi * (t / s.duration_s)
        return max(s.base_rate * 0.05,
                   s.base_rate * (1.0 + s.diurnal_amplitude
                                  * math.sin(phase)))

    def arrivals(self) -> list:
        """Seeded inhomogeneous-Poisson push schedule via thinning:
        ``[(t_virtual, image_index), ...]``, with duplicate-tag
        bursts (the same image repushed within ~50 virtual ms — the
        pattern debounce exists for) and popularity-skewed image
        choice so hot images re-push often."""
        s = self.spec
        rng = random.Random(f"{s.seed}:arrivals".encode())
        peak = s.base_rate * (1.0 + s.diurnal_amplitude)
        out = []
        t = 0.0
        while True:
            t += rng.expovariate(peak)
            if t >= s.duration_s:
                break
            if rng.random() > self.rate_at(t) / peak:
                continue             # thinned: off-peak hour
            # popularity skew: square the draw so a hot head of
            # images dominates re-pushes (realistic tag churn)
            i = int(rng.random() ** 2 * s.registry.images)
            out.append((round(t, 6), i))
            if rng.random() < s.dup_rate:
                for j in range(1 + rng.randrange(
                        max(1, s.burst))):
                    tb = t + (j + 1) * 0.05
                    if tb < s.duration_s:
                        out.append((round(tb, 6), i))
        out.sort()
        return out

    def schedule(self) -> dict:
        """The full deterministic run plan, canonical and cached."""
        if self._schedule is None:
            s = self.spec
            self._schedule = {
                "name": s.name,
                "seed": s.seed,
                "duration_s": s.duration_s,
                "compression": s.compression,
                "registry": asdict(s.registry),
                "arrivals": self.arrivals(),
                "steps": [asdict(st) for st in
                          sorted(s.steps, key=lambda st:
                                 (st.t, st.kind))],
            }
        return self._schedule

    def to_json(self) -> str:
        """Canonical bytes: the same-seed ⇒ byte-identical contract
        (and the thing the schedule digest is taken over)."""
        return json.dumps(self.schedule(), sort_keys=True,
                          separators=(",", ":"))

    def digest(self) -> str:
        return "sha256:" + hashlib.sha256(
            self.to_json().encode()).hexdigest()


def _smoke_steps() -> tuple:
    """The smoke script: every step kind once, overlapping where the
    emergent-behavior questions live (a hot swap during a scale-up
    during storm recovery), with exactly one designed SLO trip."""
    return (
        Step(t=6.0, kind="storm",
             fault="event-storm:storm_events=160,storm_digests=8,"
                   "storm_malformed=12"),
        Step(t=10.0, kind="kill", fault="replica-kill"),
        Step(t=12.0, kind="scale_up"),
        Step(t=16.0, kind="hot_swap", duration=6.0),
        Step(t=20.0, kind="cache_outage", duration=4.0,
             value=0.5, fault="cache-flaky"),
        Step(t=26.0, kind="flaky", duration=4.0, value=0.15,
             fault="replica-flaky"),
        Step(t=31.0, kind="scale_down"),
        Step(t=36.0, kind="brownout", duration=10.0, value=1.0,
             expect_trip=True),
    )


SCENARIOS = {
    # tier-1-safe: seconds of wall clock, every step kind, one
    # designed trip — the harness exercising itself on every PR
    "soak-smoke": ScenarioSpec(
        name="soak-smoke", seed=20260807,
        duration_s=48.0, compression=3.0, base_rate=30.0,
        registry=RegistrySpec(seed=20260807, layers=100_000,
                              images=20_000, hostile_rate=0.01),
        steps=_smoke_steps()),
    # the full gated run: a compressed "week" against a
    # million-layer registry — ≥10⁴ scans, chaos cycles repeating
    # so leak trends have room to show
    "soak": ScenarioSpec(
        name="soak", seed=20260807,
        duration_s=720.0, compression=6.0, base_rate=40.0,
        registry=RegistrySpec(seed=20260807, layers=1_000_000,
                              images=200_000, hostile_rate=0.005),
        steps=(
            Step(t=60.0, kind="storm",
                 fault="event-storm:storm_events=512,"
                       "storm_digests=24,storm_malformed=32"),
            Step(t=120.0, kind="kill", fault="replica-kill"),
            Step(t=150.0, kind="scale_up"),
            Step(t=200.0, kind="hot_swap", duration=60.0),
            Step(t=280.0, kind="cache_outage", duration=40.0,
                 value=0.5, fault="cache-flaky"),
            Step(t=340.0, kind="flaky", duration=40.0, value=0.1,
                 fault="replica-flaky"),
            Step(t=400.0, kind="scale_down"),
            Step(t=430.0, kind="storm",
                 fault="event-storm:storm_events=512,"
                       "storm_digests=24,storm_malformed=32"),
            Step(t=470.0, kind="kill", fault="replica-kill"),
            Step(t=500.0, kind="scale_up"),
            Step(t=540.0, kind="hot_swap", duration=60.0),
            Step(t=620.0, kind="brownout", duration=100.0,
                 value=1.0, expect_trip=True),
        )),
}


def _step_from_dict(doc: dict) -> Step:
    known = {"t", "kind", "duration", "value", "fault",
             "expect_trip"}
    extra = set(doc) - known
    if extra:
        raise ValueError(f"unknown step fields {sorted(extra)}")
    return Step(**doc)


def load_scenario(name_or_path: str, seed: int = 0,
                  duration_s: float = 0.0,
                  compression: float = 0.0) -> Scenario:
    """``--scenario NAME`` (preset) or ``--scenario FILE`` (a JSON
    ScenarioSpec document). CLI overrides (seed/duration/compression
    > 0) apply on top of either."""
    import os
    if name_or_path in SCENARIOS:
        spec = SCENARIOS[name_or_path]
    elif os.path.exists(name_or_path):
        with open(name_or_path, encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError("scenario file must hold a JSON "
                             "object")
        reg = RegistrySpec(**(doc.pop("registry", None) or {}))
        steps = tuple(_step_from_dict(d)
                      for d in doc.pop("steps", None) or ())
        spec = ScenarioSpec(registry=reg, steps=steps, **doc)
    else:
        raise ValueError(
            f"unknown scenario {name_or_path!r} (presets: "
            f"{', '.join(sorted(SCENARIOS))}; or a JSON file path)")
    overrides = {}
    if seed:
        overrides["seed"] = seed
        overrides["registry"] = replace(spec.registry, seed=seed)
    if duration_s > 0:
        overrides["duration_s"] = duration_s
    if compression > 0:
        overrides["compression"] = compression
    if overrides:
        spec = replace(spec, **overrides)
    return Scenario(spec)
