"""The soak runner: executes a scenario schedule against a live
routed fleet and renders the verdict.

Fleet shape (all in one process group, CPU-sim devices):

* a :class:`router.core.ScanRouter` + HTTP front
  (``router.front.RouterServer``) with a health prober;
* N sim replicas (``router.sim.SimReplica``) — in-process by
  default, one OS process each with ``--mode subprocess`` — each
  carrying its own SLO engine and ``/metrics/snapshot``;
* the watch loop (``watch.loop.WatchLoop``) fed by a
  ``WebhookSource``: every push arrival and storm envelope enters
  as a registry notification, debounces, and submits through the
  router — watch traffic rides the same fleet as everything else;
* the PR-13 federation plane (``obs.federate.Federator``) pulling
  replica SLO exports for the fleet burn-rate verdict, and a local
  tracer + flight recorder whose trip-transition dumps are the
  evidence trail for designed SLO trips.

Invariants enforced at quiesce (the run FAILS on any):

* global books: every accepted request reaches exactly one terminal
  state — router ``lost == 0``, watch ``events == scans + deduped +
  shed``, every submitted scan resolved;
* SLO trips exactly: no fleet ``slo_ok == False`` epoch before the
  first step designed to trip, and every ``expect_trip`` step does
  trip (with flight-recorder dumps from the disruption window);
* the leak audit's flat-after-warm-up verdict
  (:class:`soak.audit.ResourceAudit`).

The report is schema-stable JSON (``sort_keys``); its ``stable``
subtree is byte-identical across same-seed runs, with every
wall-clock-dependent measurement quarantined elsewhere.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..router.core import SCAN_PATH, HealthProber, ScanRouter
from ..router.metrics import ROUTER_METRICS
from ..router.sim import TENANT_HEADER
from ..utils import get_logger
from ..watch.loop import WatchConfig, WatchLoop
from ..watch.source import WebhookSource
from .audit import ResourceAudit
from .registry import SyntheticRegistry
from .scenario import Scenario

log = get_logger("soak.runner")

REPORT_SCHEMA = 1
# real-seconds margin added to disruption windows when classifying
# "steady" epochs for the sustained-throughput measurement
_STEADY_MARGIN_S = 1.0


class _ScanResult:
    """What the watch loop reaps: ``error`` empty means the scan
    reached a good terminal state."""

    __slots__ = ("status", "payload", "error", "replica",
                 "memo_hit", "degraded")

    def __init__(self, status, payload, error=""):
        self.status = status
        self.payload = payload or {}
        self.error = error
        self.replica = self.payload.get("routed_replica", "")
        self.memo_hit = bool(self.payload.get("memo_hit"))
        self.degraded = bool(self.payload.get("degraded"))


class _ScanRequest:
    """Future-like handle satisfying the WatchLoop contract
    (``.done``, ``.result(timeout)``, ``.trace_id``)."""

    __slots__ = ("_event", "_result", "trace_id")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self.trace_id = ""

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("scan not resolved")
        return self._result

    def finish(self, result) -> None:
        self._result = result
        self._event.set()


class RouterSubmitRunner:
    """``submit_path`` adapter: watch submissions become routed
    twirp Scans through the fleet front, each under its own trace
    span, each booked into the local SLO engine (trip dumps)."""

    backend = "cpu"

    def __init__(self, soak: "SoakRunner", max_workers: int):
        self.soak = soak
        self.pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="soak-scan")

    def submit_path(self, path, options, tenant: str = "",
                    priority: int = 0, trace_id: str = "",
                    parent_span_id: str = "") -> _ScanRequest:
        manifest = self.soak.registry.resolve_path(path)
        req = _ScanRequest()
        self.pool.submit(self._work, req, manifest, tenant,
                         trace_id, parent_span_id)
        return req

    def _work(self, req, manifest, tenant, trace_id,
              parent_span_id) -> None:
        soak = self.soak
        span = soak.tracer.start_request(
            manifest["digest"][:19], trace_id=trace_id,
            parent_span_id=parent_span_id)
        req.trace_id = span.trace_id
        key = f"{manifest['digest']}:{soak.next_key()}"
        raw = json.dumps(
            soak.registry.scan_body(manifest,
                                    idempotency_key=key)).encode()
        t0 = time.monotonic()
        try:
            status, out, _ = soak.router.route(
                SCAN_PATH, raw,
                headers={TENANT_HEADER: tenant})
            try:
                payload = json.loads(out or b"{}")
            except ValueError:
                payload = {}
            if not isinstance(payload, dict):
                payload = {}
            error = "" if status == 200 else \
                f"status {status}: {payload.get('code', '')}"
            result = _ScanResult(status, payload, error)
            span.end("ok" if status == 200 else "error")
            soak.book_scan(result, span.trace_id,
                           time.monotonic() - t0)
            req.finish(result)
        except Exception as e:    # noqa: BLE001 — a scan worker
            # must always resolve its future; anything else wedges
            # the watch loop's in-flight table at drain
            span.end("error")
            soak.book_scan(_ScanResult(0, {}, repr(e)),
                           span.trace_id, time.monotonic() - t0)
            req.finish(_ScanResult(0, {}, repr(e)))

    def close(self) -> None:
        self.pool.shutdown(wait=True)


class SoakRunner:
    """One scenario, one fleet, one verdict."""

    def __init__(self, scenario: Scenario, replicas: int = 3,
                 mode: str = "inproc", token: str = "",
                 epoch_s: float = 0.5, service_ms: float = 5.0,
                 max_concurrent: int = 4,
                 slo_availability: float = 0.995,
                 max_inflight: int = 64):
        if mode not in ("inproc", "subprocess"):
            raise ValueError(f"unknown soak mode {mode!r}")
        self.scenario = scenario
        self.n_replicas = max(1, replicas)
        self.mode = mode
        self.token = token
        self.epoch_s = max(0.05, epoch_s)
        self.service_ms = service_ms
        self.max_concurrent = max_concurrent
        self.slo_availability = slo_availability
        self.max_inflight = max_inflight
        self.registry = SyntheticRegistry(scenario.spec.registry)
        # local obs plane: tracer + recorder + the SLO engine whose
        # trip transitions dump evidence (replica engines carry the
        # federated verdict; this one carries the dumps)
        import tempfile
        from ..obs.recorder import FlightRecorder
        from ..obs.slo import SLO, SloEngine
        from ..obs.trace import Tracer
        self._tmpdir = tempfile.mkdtemp(prefix="soak-")
        self.recorder = FlightRecorder(
            dump_dir=self._tmpdir + "/dumps")
        self.tracer = Tracer(enabled=True, recorder=self.recorder)
        self.engine = SloEngine(
            [SLO(name="availability", kind="availability",
                 objective=slo_availability)],
            recorder=self.recorder)
        self.audit = ResourceAudit()
        self._lock = threading.Lock()
        self._key = 0
        self.counters = {"pushed": 0, "push_accepted": 0,
                         "push_malformed": 0, "storm_envelopes": 0,
                         "scans_ok": 0, "scans_failed": 0,
                         "scans_shed": 0, "degraded": 0,
                         "memo_hits": 0, "kills": 0,
                         "scale_ups": 0, "scale_downs": 0,
                         "hot_swaps": 0}
        self.verdicts: list = []     # (t_real, slo_ok, complete)
        self._ok_series: list = []   # (t_real, ok, accepted)
        self._waiters: list = []
        self.controller = None
        self.router = None
        self.prober = None
        self.loop = None
        self.source = None
        self.submitter = None
        self._fed_state = {"key": None, "fed": None}

    # ---- bookkeeping hooks ----

    def next_key(self) -> int:
        with self._lock:
            self._key += 1
            return self._key

    def book_scan(self, result: _ScanResult, trace_id: str,
                  latency_s: float) -> None:
        """Terminal bookkeeping for one routed scan: soak counters
        plus the local SLO engine (ok/failed/timed_out classes feed
        burn; 429/503 are shed — transient by the tree's contract,
        they never count against availability)."""
        with self._lock:
            if result.status == 200:
                self.counters["scans_ok"] += 1
                if result.memo_hit:
                    self.counters["memo_hits"] += 1
                if result.degraded:
                    self.counters["degraded"] += 1
            elif result.status in (429, 503):
                self.counters["scans_shed"] += 1
            else:
                self.counters["scans_failed"] += 1
        if result.status == 200:
            self.engine.record("ok", latency_s,
                               trace_id=trace_id)
        elif result.status == 408:
            self.engine.record("timed_out", latency_s,
                               trace_id=trace_id)
        elif result.status != 429:
            # 503 included: a router "no routable replica" during a
            # brownout IS the user-visible outage — counting it bad
            # here makes the local engine trip (and dump evidence)
            # exactly when the fleet fails its users
            self.engine.record("failed", latency_s,
                               trace_id=trace_id)

    # ---- fleet lifecycle ----

    def _setup_fleet(self) -> None:
        from ..router.scaler import (SimReplicaController,
                                     SubprocessReplicaController)
        ROUTER_METRICS.reset()
        # elastic lifecycle (docs/serving.md): every replica shares
        # one memo-tier directory, so scale_up steps exercise the
        # real prewarm walk (join warming, stage owned ranges, flip
        # ready) and scale_down steps run the drain handoff
        memo_dir = self._tmpdir + "/memo"
        if self.mode == "inproc":
            self.controller = SimReplicaController(
                prefix="soak",
                service_ms=self.service_ms,
                max_concurrent=self.max_concurrent,
                seed=self.scenario.spec.seed,
                slo_availability=self.slo_availability,
                memo_dir=memo_dir)
        else:
            self.controller = SubprocessReplicaController(
                prefix="soak", extra_args=[
                    "--service-ms", str(self.service_ms),
                    "--max-concurrent", str(self.max_concurrent),
                    "--seed", str(self.scenario.spec.seed),
                    "--slo-availability",
                    str(self.slo_availability),
                    "--memo-dir", memo_dir])
        self.router = ScanRouter(token=self.token)
        for _ in range(self.n_replicas):
            name, url = self.controller.start()
            self.router.add_replica(name, url)
        self.prober = HealthProber(self.router, interval_s=0.2,
                                   timeout_s=1.0)
        self.prober.start()
        self.source = WebhookSource(
            resolver=self.registry.resolver(), maxsize=8192,
            tenant="watch")
        self.submitter = RouterSubmitRunner(
            self, max_workers=self.max_inflight)
        self.loop = WatchLoop(
            self.submitter, self.source,
            config=WatchConfig(debounce_s=0.05,
                               max_inflight=self.max_inflight,
                               submit_retries=2,
                               checkpoint_path=self._tmpdir
                               + "/cursor.json"),
            options=object())
        self._register_probes()

    def _register_probes(self) -> None:
        # gated series are the leak signals: process self-stats
        # (added by the audit itself) plus structures that must
        # QUIESCE, not just stay under a cap
        self.audit.add_probe(
            "watch_backlog",
            lambda: len(self.loop._pending)
            + len(self.loop._inflight))
        self.audit.add_probe(
            "cursor_ack_window",
            lambda: self.loop.cursor.stats()["ack_window"])
        # cap-bounded structures: they legitimately grow TOWARD
        # their caps all run long (AFFINITY_CAP LRU, DUMP_CAP FIFO
        # — both regression-test-enforced), so the flat-after-warmup
        # test can't gate them; the audit tracks them for the report
        self.audit.add_probe(
            "router_affinity",
            lambda: self.router.stats()["affinity_entries"],
            gate=False)
        self.audit.add_probe(
            "recorder_dump_files",
            lambda: self.recorder.stats().get("dump_files", 0),
            gate=False)
        # corpus-bounded structures: recorded for visibility, never
        # gated (they saturate at corpus size, which a short run
        # only ever approaches from below)
        self.audit.add_probe(
            "registry_index",
            lambda: len(self.registry._by_digest), gate=False)
        self.audit.add_probe("replica_warm_digests",
                             self._probe_replica("warm_digests"),
                             gate=False)
        self.audit.add_probe(
            "replica_idempotency",
            self._probe_replica("idempotency_entries"),
            gate=False)
        self.audit.add_probe("replica_rss_bytes",
                             self._probe_replica_rss)
        # high-water RSS rides the same sampler; the ratchet is
        # monotone by design (procstats.peak ratchet), so it informs
        # the report but never gates the bounded-growth verdict
        self.audit.add_probe("replica_peak_rss_bytes",
                             self._probe_replica_peak_rss,
                             gate=False)

    def _replica_metrics(self) -> list:
        import urllib.request
        out = []
        for h in self.router.replicas():
            try:
                with urllib.request.urlopen(
                        h.url + "/metrics", timeout=1.0) as resp:
                    out.append(json.loads(resp.read() or b"{}"))
            except Exception:    # noqa: BLE001 — dead replicas are
                # expected mid-chaos; the sampler degrades
                continue
        return out

    def _probe_replica(self, key: str):
        def probe():
            rows = self._replica_metrics()
            if not rows:
                return -1
            return max(int(r.get(key, 0)) for r in rows)
        return probe

    def _probe_replica_rss(self):
        rows = self._replica_metrics()
        vals = [int((r.get("process") or {}).get("rss_bytes", -1))
                for r in rows]
        vals = [v for v in vals if v > 0]
        return max(vals) if vals else -1

    def _probe_replica_peak_rss(self):
        rows = self._replica_metrics()
        vals = [int((r.get("process") or {}).get(
            "peak_rss_bytes", -1)) for r in rows]
        vals = [v for v in vals if v > 0]
        return max(vals) if vals else -1

    def _teardown_fleet(self) -> None:
        for w in self._waiters:
            w.join(timeout=10.0)
        if self.prober is not None:
            self.prober.stop()
        if self.submitter is not None:
            self.submitter.close()
        if self.controller is not None:
            for name in list(getattr(self.controller, "replicas",
                                     None)
                             or getattr(self.controller, "procs",
                                        {})):
                try:
                    self.controller.stop(name)
                except Exception:   # noqa: BLE001 — already dead
                    pass

    # ---- federation verdicts ----

    def _fleet_verdict(self) -> dict:
        from ..obs.federate import Federator
        peers = [(h.name, h.url) for h in self.router.replicas()]
        key = tuple(peers)
        if key != self._fed_state["key"]:
            self._fed_state["key"] = key
            self._fed_state["fed"] = Federator(
                peers, token=self.token, timeout_s=1.0) \
                if peers else None
        fed = self._fed_state["fed"]
        if fed is None:
            return {"slo_ok": True, "complete": False}
        fleet = fed.fleet_slo({}, fed.collect())
        # the front's own engine is authoritative for user-visible
        # availability: a brownout ejects the erroring replicas
        # within a few requests (breakers), after which the outage
        # is router-side 503s the replica engines never see
        local_ok = all(v["ok"] for v in self.engine.verdicts())
        return {"slo_ok": local_ok
                and bool(fleet.get("slo_ok", True)),
                "complete": bool(fleet.get("complete", False)),
                "replicas": fleet.get("replicas", 0)}

    def _fleet_invoice(self) -> dict:
        """The per-tenant invoice at quiesce: the same federated
        ``GET /costs`` rollup the router front serves
        (obs/cost.py:federated_costs), plus the totals-match
        identity the report gates on — the invoice's per-tenant
        device-seconds must sum to the fleet ledger's attributed
        total."""
        from ..obs.cost import federated_costs
        inv = federated_costs(
            [(h.name, h.url) for h in self.router.replicas()],
            token=self.token)
        tenant_sum = sum(float(v.get("device_s", 0.0))
                         for v in (inv.get("tenants") or
                                   {}).values())
        fleet_total = float(inv.get("attributed_device_s", 0.0))
        inv["tenant_device_s"] = round(tenant_sum, 6)
        inv["totals_match"] = abs(tenant_sum - fleet_total) \
            <= max(1e-6, 1e-4 * max(fleet_total, 1.0))
        return inv

    # ---- step execution ----

    def _post_chaos(self, url: str, doc: dict) -> None:
        import urllib.request
        req = urllib.request.Request(
            url + "/chaos", data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=2.0):
                pass
        except Exception as e:   # noqa: BLE001 — a chaos POST to a
            # replica that just died is chaos doing its job
            log.warning("chaos POST to %s failed: %r", url, e)

    def _broadcast_chaos(self, doc: dict) -> None:
        for h in self.router.replicas():
            self._post_chaos(h.url, doc)

    def _routable_names(self) -> list:
        return [h.name for h in self.router.replicas()
                if not h.draining]

    def _do_kill(self) -> None:
        victims = self._routable_names()
        if len(victims) <= 1:
            log.warning("kill step skipped: fleet too small")
            return
        victim = victims[-1]
        log.info("soak: killing replica %s", victim)
        self.controller.kill(victim)
        with self._lock:
            self.counters["kills"] += 1

        def remove_later():
            time.sleep(1.0)
            self.router.remove_replica(victim)
        t = threading.Thread(target=remove_later, daemon=True,
                             name="soak-kill-reaper")
        t.start()
        self._waiters.append(t)

    def _do_scale_up(self) -> None:
        # the real join lifecycle: the new replica gets the current
        # ring membership, computes its post-join ranges, prewarms
        # out of the shared memo tier, and joins the ring WARMING —
        # the prober admits it when its /healthz flips ready
        members = self._routable_names()
        name, url = self.controller.start(ring_members=members)
        self.router.add_replica(
            name, url,
            warming=bool(self.controller.prewarm_enabled))
        ROUTER_METRICS.inc("scale_ups")
        with self._lock:
            self.counters["scale_ups"] += 1

    def _do_scale_down(self) -> None:
        victims = self._routable_names()
        if len(victims) <= 1:
            log.warning("scale-down skipped: fleet too small")
            return
        victim = victims[-1]
        self.router.mark_draining(victim)
        self.controller.drain(victim)
        ROUTER_METRICS.inc("scale_downs")
        ROUTER_METRICS.inc("drains_started")
        with self._lock:
            self.counters["scale_downs"] += 1
        # drain handoff: hand the victim's hot-digest set to its
        # ring successors while its in-flight work finishes —
        # best-effort, never blocks the drain
        from ..router.lifecycle import run_handoff
        run_handoff(self.router, victim, timeout_s=2.0)

        def quiesce():
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                h = self.router.replica(victim)
                if h is None:
                    return
                if h.inflight == 0 and h.probed_inflight == 0:
                    break
                time.sleep(0.05)
            self.controller.stop(victim)
            self.router.remove_replica(victim)
            ROUTER_METRICS.inc("drain_kills")
        t = threading.Thread(target=quiesce, daemon=True,
                             name="soak-drain-waiter")
        t.start()
        self._waiters.append(t)

    def _do_hot_swap(self, real_duration: float) -> None:
        """Rolling DB generation bump: one replica at a time across
        the window — the memo hot-swap pattern at fleet scale."""
        replicas = [(h.name, h.url)
                    for h in self.router.replicas()]
        if not replicas:
            return
        gap = real_duration / max(1, len(replicas))
        with self._lock:
            self.counters["hot_swaps"] += 1
            gen = self.counters["hot_swaps"]

        def roll():
            for i, (name, url) in enumerate(replicas):
                if i:
                    time.sleep(gap)
                self._post_chaos(url, {"db_generation": gen})
        t = threading.Thread(target=roll, daemon=True,
                             name="soak-hot-swap")
        t.start()
        self._waiters.append(t)

    def _do_storm(self, step) -> None:
        """A registry push burst shaped by the step's composed
        ``event-storm`` fault spec: ``storm_events`` envelopes over
        ``storm_digests`` distinct images (tag churn included) with
        ``storm_malformed`` malformed envelopes interleaved —
        the ``faults/`` scenario, materialized registry-side."""
        import random
        spec = step.fault_spec()
        n = spec.storm_events if spec and spec.storm_events else 128
        n_digests = max(1, (spec.storm_digests if spec else 0) or 8)
        n_malformed = max(0, spec.storm_malformed if spec else 0)
        rng = random.Random(spec.seed if spec
                            else self.scenario.spec.seed)
        # a deterministic image subset far from the popular head
        images = [((i * 2654435761) + rng.randrange(1 << 16))
                  % self.scenario.spec.registry.images
                  for i in range(n_digests)]
        malformed_at = set(rng.sample(
            range(n + n_malformed), n_malformed)) \
            if n_malformed else set()
        sent = bad = 0
        for slot in range(n + n_malformed):
            if slot in malformed_at:
                env = rng.choice([
                    {"events": "not-a-list"},
                    {"events": [{"action": "push",
                                 "target": {}}]},
                    ["not", "an", "envelope"],
                    {"events": [{"action": "push",
                                 "target": {"repository": "r"}}]},
                ])
                bad += 1
            else:
                env = self.registry.notification(
                    images[sent % n_digests],
                    event_id=f"storm-{slot}")
                sent += 1
            res = self.source.push_notification(env)
            with self._lock:
                self.counters["storm_envelopes"] += 1
                self.counters["push_accepted"] += \
                    res.get("accepted", 0)
                self.counters["push_malformed"] += \
                    res.get("malformed", 0)

    def _run_step(self, step) -> None:
        comp = self.scenario.spec.compression
        real_dur = step.duration / comp
        if step.kind == "storm":
            self._do_storm(step)
        elif step.kind == "kill":
            self._do_kill()
        elif step.kind == "scale_up":
            self._do_scale_up()
        elif step.kind == "scale_down":
            self._do_scale_down()
        elif step.kind == "hot_swap":
            self._do_hot_swap(real_dur)
        elif step.kind in ("brownout", "flaky", "cache_outage"):
            knob = {"brownout": "error_rate",
                    "flaky": "drop_rate",
                    "cache_outage": "cache_error_rate"}[step.kind]
            rate = step.value or 1.0
            # flaky scopes its drops to ONE replica (a bad NIC, not
            # a fleet event — same scoping as the replica-flaky
            # fault spec): failover replays absorb a single flaky
            # member, whereas fleet-wide drops open every breaker
            # and the cooldown aftermath trips the SLO outside the
            # designed window. Brownouts stay fleet-wide — that IS
            # the designed correlated failure.
            victims = None
            if step.kind == "flaky":
                live = sorted(self._routable_names())
                victims = live[:1]

            def window(knob=knob, rate=rate, dur=real_dur,
                       victims=victims):
                if victims is None:
                    self._broadcast_chaos({knob: rate})
                else:
                    for h in self.router.replicas():
                        if h.name in victims:
                            self._post_chaos(h.url, {knob: rate})
                time.sleep(dur)
                # reset fleet-wide either way: a scoped victim may
                # have been replaced mid-window; clearing a knob on
                # a healthy replica is a no-op
                self._broadcast_chaos({knob: 0.0})
            t = threading.Thread(target=window, daemon=True,
                                 name=f"soak-{step.kind}")
            t.start()
            self._waiters.append(t)

    # ---- the run ----

    def _push_arrival(self, image_index: int) -> None:
        env = self.registry.notification(image_index)
        res = self.source.push_notification(env)
        with self._lock:
            self.counters["pushed"] += 1
            self.counters["push_accepted"] += \
                res.get("accepted", 0)
            self.counters["push_malformed"] += \
                res.get("malformed", 0)

    def _timeline(self, sched: dict) -> None:
        """Walk arrivals and steps on the compressed clock. Behind
        schedule = push immediately (open loop never stalls)."""
        comp = self.scenario.spec.compression
        events = [(a[0] / comp, "arrival", a[1])
                  for a in sched["arrivals"]]
        events += [(st["t"] / comp, "step", st)
                   for st in sched["steps"]]
        events.sort(key=lambda e: (e[0], e[1]))
        t0 = time.monotonic()
        for due, kind, payload in events:
            delay = due - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            if kind == "arrival":
                self._push_arrival(payload)
            else:
                from .scenario import Step
                self._run_step(Step(**payload))

    def _disruption_windows(self) -> list:
        """Real-time [start, end] spans when throughput is expected
        to wobble (steps + margin) — excluded from the sustained-ips
        measurement."""
        comp = self.scenario.spec.compression
        out = []
        for st in self.scenario.spec.steps:
            start = st.t / comp - _STEADY_MARGIN_S
            end = st.t / comp + max(st.duration / comp, 0.5) \
                + _STEADY_MARGIN_S
            out.append((start, end))
        return out

    def _sustained_ips(self) -> dict:
        """Goodput (ok) and offered (accepted) rates over epochs
        wholly outside every disruption window — the steady-state
        throughput the full-soak bench gates against the direct
        router storm at equivalent N."""
        windows = self._disruption_windows()
        total_dt = total_ok = total_acc = 0.0
        for (t_a, ok_a, acc_a), (t_b, ok_b, acc_b) in zip(
                self._ok_series, self._ok_series[1:]):
            if any(t_a < end and t_b > start
                   for start, end in windows):
                continue
            total_dt += t_b - t_a
            total_ok += ok_b - ok_a
            total_acc += acc_b - acc_a
        if total_dt <= 0:
            return {"ips": 0.0, "offered_ips": 0.0,
                    "seconds": 0.0, "scans": 0}
        return {"ips": round(total_ok / total_dt, 2),
                "offered_ips": round(total_acc / total_dt, 2),
                "seconds": round(total_dt, 2),
                "scans": int(total_ok)}

    def run(self) -> dict:
        sched = self.scenario.schedule()
        spec = self.scenario.spec
        real_total = spec.duration_s / spec.compression
        wall_start = time.time()
        t_mono = time.monotonic()
        self._setup_fleet()
        loop_stats: dict = {}

        def pump():
            loop_stats.update(
                self.loop.run(max_wall_s=real_total + 60.0))
        loop_thread = threading.Thread(target=pump, daemon=True,
                                       name="soak-watch-pump")
        loop_thread.start()
        timeline = threading.Thread(
            target=self._timeline, args=(sched,), daemon=True,
            name="soak-timeline")
        timeline.start()
        try:
            # epoch sampler: audit + federated verdict + ok-rate
            while timeline.is_alive():
                time.sleep(self.epoch_s)
                now = time.monotonic() - t_mono
                self.audit.sample()
                v = self._fleet_verdict()
                self.verdicts.append(
                    (round(now, 3), v["slo_ok"], v["complete"]))
                snap = ROUTER_METRICS.snapshot()
                self._ok_series.append(
                    (now, snap["ok"], snap["accepted"]))
            timeline.join()
            for w in list(self._waiters):
                w.join(timeout=max(15.0, real_total))
            # quiesce: no more pushes; drain the loop through the
            # fleet, then take the final books
            self.source.close()
            loop_thread.join(timeout=120.0)
            self.audit.sample()
            v = self._fleet_verdict()
            self.verdicts.append(
                (round(time.monotonic() - t_mono, 3),
                 v["slo_ok"], v["complete"]))
            self.engine.verdicts()   # final trip eval → dumps
            return self._report(sched, loop_stats, wall_start,
                                time.monotonic() - t_mono)
        finally:
            self._teardown_fleet()

    # ---- the verdicts ----

    def _trip_analysis(self) -> dict:
        comp = self.scenario.spec.compression
        designed = [
            {"kind": st.kind, "t": st.t,
             "real_start": round(st.t / comp, 3),
             "real_end": round((st.t + st.duration) / comp
                               + _STEADY_MARGIN_S, 3)}
            for st in self.scenario.spec.steps if st.expect_trip]
        first_designed = min((d["real_start"] for d in designed),
                             default=None)
        trips = [t for t, ok, _ in self.verdicts if not ok]
        first_trip = trips[0] if trips else None
        # grace: federation staleness means a trip can surface one
        # epoch late, never early
        early_trip = (first_trip is not None
                      and (first_designed is None
                           or first_trip
                           < first_designed - 1e-9))
        missed_trip = bool(designed) and first_trip is None
        return {"expected": designed,
                "first_trip_t": first_trip,
                "tripped": first_trip is not None,
                "early_trip": early_trip,
                "missed_trip": missed_trip,
                "trips_exact": not early_trip and not missed_trip,
                "dumps": self.engine.dumps,
                "dump_dir": self.recorder.dump_dir}

    def _report(self, sched, loop_stats, wall_start,
                wall_s) -> dict:
        from ..obs.timeline import MergedTimeline, export_tracer
        router_stats = ROUTER_METRICS.snapshot()
        watch_ok = (loop_stats.get("events", 0)
                    == loop_stats.get("scans", 0)
                    + loop_stats.get("deduped", 0)
                    + loop_stats.get("shed", 0))
        lost = router_stats.get("lost", 0)
        books_ok = watch_ok and lost == 0
        trip = self._trip_analysis()
        audit_v = self.audit.verdict()
        invoice = self._fleet_invoice()
        peak_rss = [v for v in self.audit.series(
            "replica_peak_rss_bytes") if v > 0]
        replica_rows = sorted(self._replica_metrics(),
                              key=lambda r: r.get("name", ""))
        merged = MergedTimeline(
            [export_tracer(self.tracer, process="soak-front")])
        with self._lock:
            counters = dict(self.counters)
        stable = {
            "scenario": sched["name"],
            "seed": sched["seed"],
            "schedule_digest": self.scenario.digest(),
            "arrivals": len(sched["arrivals"]),
            "steps": len(sched["steps"]),
            "expected_trips": [d["kind"] for d in
                               trip["expected"]],
            "events_pushed": counters["pushed"]
            + counters["storm_envelopes"],
            "malformed": counters["push_malformed"],
            "books_balanced": books_ok,
            "lost": lost,
            "trips_exact": trip["trips_exact"],
            "audit_ok": audit_v["ok"],
            "invoice_totals_match": invoice["totals_match"],
        }
        from ..router.lifecycle import LIFECYCLE_METRICS
        return {
            "schema": REPORT_SCHEMA,
            "stable": stable,
            "scenario": {"name": sched["name"],
                         "seed": sched["seed"],
                         "digest": self.scenario.digest(),
                         "duration_s": sched["duration_s"],
                         "compression": sched["compression"],
                         "registry": self.registry.stats()},
            "books": {"router": router_stats,
                      "watch": loop_stats,
                      "watch_balanced": watch_ok,
                      "lost": lost,
                      "balanced": books_ok,
                      "counters": counters},
            "slo": {"verdict_epochs": len(self.verdicts),
                    "trip": trip,
                    "local": self.engine.snapshot()},
            "audit": audit_v,
            "costs": invoice,
            "throughput": {"sustained": self._sustained_ips(),
                           "scans_ok": counters["scans_ok"]},
            "fleet": {"mode": self.mode,
                      "replicas_start": self.n_replicas,
                      "replicas_end": len(replica_rows),
                      "replicas": replica_rows,
                      "peak_rss_bytes": int(max(peak_rss))
                      if peak_rss else -1,
                      # handoff counters booked by THIS process's
                      # run_handoff; per-replica prewarm counters
                      # ride the replica rows above
                      "lifecycle": LIFECYCLE_METRICS.snapshot()},
            "timeline": merged.report(),
            "wall": {"started_unix": round(wall_start, 3),
                     "duration_s": round(wall_s, 3)},
        }


def stable_view(report: dict) -> str:
    """The byte-identical-across-same-seed-runs slice of a report,
    canonically serialized (the determinism gate compares these)."""
    return json.dumps(report.get("stable") or {}, sort_keys=True,
                      separators=(",", ":"))


def run_soak(scenario: Scenario, replicas: int = 3,
             mode: str = "inproc", report_path: str = "",
             **kwargs) -> dict:
    """Build, run, optionally persist. The report is dumped with
    ``sort_keys`` so same-seed runs diff cleanly."""
    runner = SoakRunner(scenario, replicas=replicas, mode=mode,
                        **kwargs)
    report = runner.run()
    if report_path:
        with open(report_path, "w", encoding="utf-8") as f:
            json.dump(report, f, sort_keys=True, indent=2)
            f.write("\n")
    return report
