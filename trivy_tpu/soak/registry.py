"""Seeded synthetic registry: a content-addressed layer graph with
realistic reuse, scaled to 10⁵–10⁶ *distinct* layer identities.

PR 9's warm-fleet builder (``bench.py make_warm_fleet``) materializes
tarballs where ~80% of layers are drawn from a shared pool — the
reuse pattern that makes content-addressed memoization pay. That
works to a few hundred images; a million-image registry cannot touch
disk. This generator keeps the same reuse *shape* but is index-bound:
every manifest is a pure function of ``(seed, image index)``, layer
digests are derived identities, and nothing exists until the run
asks for it — corpus size costs an integer, not a filesystem.

The outputs speak the tree's existing protocols verbatim:

* :meth:`SyntheticRegistry.notification` emits Docker Registry v2
  push envelopes that ``watch.source.parse_notification`` accepts
  unchanged — tag-push streams feed the watch loop's
  ``WebhookSource`` directly;
* :meth:`SyntheticRegistry.scan_body` emits the twirp ``Scan`` body
  the router keys and the sim replica warms on (``blob_ids[0]`` is
  the base layer — the consistent-hash route key);
* :meth:`SyntheticRegistry.resolver` is a ``watch.source`` resolver
  mapping refs to virtual ``soak://`` targets the soak runner
  resolves back through the registry.
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass

from ..watch.source import MANIFEST_MEDIA_TYPES

# virtual scan-target scheme: the soak runner's submit path resolves
# these back through the registry index instead of the filesystem
PATH_SCHEME = "soak://"


@dataclass(frozen=True)
class RegistrySpec:
    """Shape of the synthetic registry — all derivation is seeded,
    so two specs with equal fields ARE the same registry."""

    seed: int = 20260807
    layers: int = 100_000        # distinct layer identities
    images: int = 20_000         # distinct manifests
    reuse: float = 0.8           # share of layer slots drawn from
                                 # the hot base pool (PR 9's ratio)
    max_layers_per_image: int = 12
    tenants: tuple = ("acme", "globex", "initech")
    # popularity weights for the tenant mix (normalized on use)
    tenant_weights: tuple = (6, 3, 1)
    # fraction of images that are hostile (guard-quarantine trickle)
    hostile_rate: float = 0.0

    def __post_init__(self):
        if self.layers < 1 or self.images < 1:
            raise ValueError("layers and images must be >= 1")
        if not 0.0 <= self.reuse <= 1.0:
            raise ValueError(f"reuse {self.reuse} not in [0, 1]")
        if len(self.tenants) != len(self.tenant_weights):
            raise ValueError("one weight per tenant required")


class SyntheticRegistry:
    """Index-bound content-addressed registry over a RegistrySpec.

    ``manifest(i)`` is deterministic and cheap; the only growing
    state is the digest→index map for manifests a run actually
    emitted (bounded by distinct images touched, and sampled by the
    leak audit)."""

    def __init__(self, spec: RegistrySpec = None):
        self.spec = spec or RegistrySpec()
        s = self.spec
        # the hot base pool: small relative to the identity space,
        # skewed so low indices are most popular (alpine/debian base
        # layers in real registries)
        self.base_pool = max(4, min(s.layers // 64, 4096))
        self._by_digest: dict = {}   # manifest digest -> image index

    # ---- derived identities ----

    def layer_digest(self, j: int) -> str:
        return "sha256:" + hashlib.sha256(
            f"{self.spec.seed}:layer:{j}".encode()).hexdigest()

    def _image_rng(self, i: int) -> random.Random:
        return random.Random(
            f"{self.spec.seed}:image:{i}".encode())

    def layers_for(self, i: int) -> tuple:
        """The layer-digest tuple of image ``i``: the first slot and
        ``reuse`` of the rest come from the popularity-skewed base
        pool; the remainder are image-unique identities drawn from
        the full space — so distinct-layer count scales with
        ``spec.layers`` while cross-image reuse stays realistic."""
        s = self.spec
        rng = self._image_rng(i)
        n = 1 + rng.randrange(s.max_layers_per_image)
        out = []
        unique_space = max(1, s.layers - self.base_pool)
        for slot in range(n):
            if slot == 0 or rng.random() < s.reuse:
                # popularity skew: square the draw so low indices
                # dominate (the shared base-image pattern)
                j = int(rng.random() ** 2 * self.base_pool)
            else:
                j = self.base_pool + \
                    (i * s.max_layers_per_image + slot) \
                    % unique_space
            out.append(self.layer_digest(j))
        # a manifest never lists the same layer twice
        seen: set = set()
        return tuple(d for d in out
                     if not (d in seen or seen.add(d)))

    def tenant_for(self, i: int) -> str:
        s = self.spec
        rng = self._image_rng(i)
        total = sum(s.tenant_weights)
        pick = rng.random() * total
        for t, wt in zip(s.tenants, s.tenant_weights):
            pick -= wt
            if pick < 0:
                return t
        return s.tenants[-1]

    def is_hostile(self, i: int) -> bool:
        if self.spec.hostile_rate <= 0:
            return False
        return self._image_rng(i).random() < self.spec.hostile_rate

    def manifest(self, i: int) -> dict:
        """Image ``i`` as a manifest record. Content-addressed: the
        digest is the sha256 of the canonical layer list + repo, so
        identical content always carries the identical identity."""
        s = self.spec
        i = i % s.images
        layers = self.layers_for(i)
        tenant = self.tenant_for(i)
        repo = f"{tenant}/app-{i % max(1, s.images // 8)}"
        digest = "sha256:" + hashlib.sha256(
            ("\n".join(layers) + "\n" + repo).encode()).hexdigest()
        self._by_digest[digest] = i
        return {"index": i, "repository": repo,
                "tag": f"v{i % 7}", "digest": digest,
                "tenant": tenant, "layers": layers,
                "hostile": self.is_hostile(i)}

    def by_digest(self, digest: str) -> dict:
        """Manifest for a digest this registry emitted. Raises
        KeyError for digests it never minted (a malformed or foreign
        event — the watch loop sheds it as unresolvable)."""
        return self.manifest(self._by_digest[digest])

    # ---- protocol adapters ----

    def notification(self, i: int, event_id: str = "",
                     traceparent: str = "") -> dict:
        """One Docker Registry v2 push-notification envelope for
        image ``i`` — byte-compatible with
        ``watch.source.parse_notification``."""
        m = self.manifest(i)
        doc = {"events": [{
            "id": event_id or f"soak-{self.spec.seed}-{i}",
            "action": "push",
            "target": {"mediaType": MANIFEST_MEDIA_TYPES[0],
                       "repository": m["repository"],
                       "tag": m["tag"],
                       "digest": m["digest"]}}]}
        if traceparent:
            doc["traceparent"] = traceparent
        return doc

    def resolver(self):
        """A ``watch.source`` resolver: refs resolve to virtual
        ``soak://<digest>`` targets (only for digests this registry
        minted — anything else is unresolvable and sheds)."""
        def resolve(ref: str, digest: str = ""):
            if digest in self._by_digest:
                return PATH_SCHEME + digest
            return ""
        return resolve

    def resolve_path(self, path: str) -> dict:
        """``soak://<digest>`` → manifest (KeyError if foreign)."""
        if not path.startswith(PATH_SCHEME):
            raise KeyError(path)
        return self.by_digest(path[len(PATH_SCHEME):])

    def scan_body(self, manifest: dict,
                  idempotency_key: str = "") -> dict:
        """The twirp ``Scan`` body for one manifest — same shape as
        the router bench's requests, so route keys, sim warm state
        and idempotent replay behave identically."""
        body = {"idempotency_key": idempotency_key,
                "target": f"{manifest['repository']}:"
                          f"{manifest['tag']}",
                "artifact_id": "sha256:art-"
                               + manifest["digest"][-12:],
                "blob_ids": list(manifest["layers"]),
                "tenant": manifest["tenant"]}
        if manifest.get("hostile"):
            body["hostile"] = True
        return body

    def stats(self) -> dict:
        """Reuse/shape sample for reports (deterministic for a given
        spec): distinct layers across the first 256 manifests, and
        the measured base-pool share."""
        s = self.spec
        sample = min(256, s.images)
        distinct: set = set()
        slots = base_hits = 0
        base = {self.layer_digest(j)
                for j in range(self.base_pool)}
        for i in range(sample):
            for d in self.layers_for(i):
                distinct.add(d)
                slots += 1
                if d in base:
                    base_hits += 1
        return {"images": s.images, "layers": s.layers,
                "base_pool": self.base_pool,
                "sample_images": sample,
                "sample_distinct_layers": len(distinct),
                "sample_base_share":
                    round(base_hits / max(1, slots), 4),
                "indexed_digests": len(self._by_digest)}
