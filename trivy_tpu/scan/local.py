"""Local scan driver (reference: pkg/scanner/local/scan.go:78-175).

ApplyLayers → OS + language vuln detection (the batched interval
kernel) → secrets/misconf results → FillInfo enrichment.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..applier import apply_layers
from ..db import AdvisoryStore
from ..db.compiled import CompiledDB
from ..detect.batch import (PairJob, ResidentPairJob, detect_pairs,
                            dispatch_jobs)
from ..detect.enrich import fill_info
from ..detect.library import _TYPES as LIB_TYPES
from ..detect.library import _fixed_versions, normalize_pkg_name
from ..detect.ospkg.drivers import DRIVERS, format_src_version
from ..types import (OS, DetectedVulnerability, Result, ResultClass,
                     Vulnerability)
from ..types.common import SEVERITIES
from ..utils import get_logger

log = get_logger("scan.local")

# pre-defined targets for aggregated package types (scan.go pkgTargets)
_PKG_TARGETS = {
    "python-pkg": "Python",
    "node-pkg": "Node.js",
    "gemspec": "Ruby",
    "jar": "Java",
}


@dataclass
class ScanTarget:
    name: str
    artifact_id: str
    blob_ids: list


@dataclass
class PreparedScan:
    """Phase-1 output: everything needed to finish once the batched
    interval kernel returns."""

    target: ScanTarget
    options: object
    detail: object
    jobs: list
    eosl: bool
    pkg_results: list
    # findings-memo state (trivy_tpu.memo): the per-package queries
    # _vuln_jobs recorded, and the hit/miss partition plan finish()
    # resolves — both None when no memo is attached
    queries: Optional[list] = None
    memo_plan: object = None
    # requester identity (server tenant scope) — the impact index
    # records it per image so hot-swap push re-scans stay
    # tenant-scoped
    tenant: str = ""


class LocalScanner:
    def __init__(self, cache, store: Optional[AdvisoryStore] = None,
                 memo=None, tenant: str = ""):
        self.cache = cache
        self.store = store or AdvisoryStore()
        self.compiled: Optional[CompiledDB] = \
            store if isinstance(store, CompiledDB) else None
        # memo: trivy_tpu.memo.FindingsMemo — per-layer detection
        # verdicts served without device dispatch when the exact
        # question was answered before (docs/performance.md)
        self.memo = memo
        self.tenant = tenant

    def scan(self, target: ScanTarget, options: ScanOptions) -> tuple:
        """Returns (results, os) — single-target convenience around
        prepare + one kernel dispatch + finish."""
        prepared = self.prepare(target, options)
        detected = dispatch_jobs(prepared.jobs,
                                 backend=options.backend)
        return self.finish(prepared, detected)

    def prepare(self, target: ScanTarget,
                options: ScanOptions) -> PreparedScan:
        """ApplyLayers + advisory name-join → pair jobs. No kernel
        work happens here, so a batch runner can merge many targets'
        jobs into one dispatch — and with streaming ingest the
        runner calls prepare per image as soon as ITS layers have
        analyzed, overlapping the join with later images' in-flight
        fetches. The ``join`` phase span lives here, not in the
        callers, so idle attribution (host_pack_bound) sees the
        squash/name-join identically on the direct and scheduled
        paths."""
        from ..obs.trace import phase_span
        with phase_span("join", blobs=len(target.blob_ids)):
            return self._prepare(target, options)

    def _prepare(self, target: ScanTarget,
                 options: ScanOptions) -> PreparedScan:
        blobs = [self.cache.get_blob(b) for b in target.blob_ids]
        detail = apply_layers(blobs)

        if options.scan_removed_packages:
            # packages installed-then-deleted in the Dockerfile:
            # reconstructed from RUN history at inspect time, merged
            # with installed packages taking priority by name
            # (ref local/scan.go:181-182,523-536 mergePkgs)
            info = self.cache.get_artifact(target.artifact_id)
            history = getattr(info, "history_packages", None) or []
            present = {p.name for p in detail.packages}
            detail.packages.extend(
                p for p in history if p.name not in present)

        # repository fallback BEFORE the "none" default — a
        # distroless alpine has packages and an apk repositories
        # stream but no release file (ref local/scan.go:82-97,
        # where the Repository assignment overwrites the "none"
        # default unconditionally)
        if detail.os is None and detail.repository is not None:
            detail.os = OS(family=detail.repository.family,
                           name=detail.repository.release)
        if detail.os is None and detail.packages:
            detail.os = OS(family="none")

        pkg_results: list = []
        if options.list_all_packages:
            r = self._os_pkgs_result(target.name, detail)
            if r is not None:
                pkg_results.append(r)
            pkg_results.extend(self._lang_pkgs_results(detail))

        jobs, eosl = ([], False)
        queries = [] if self.memo is not None else None
        if "vuln" in options.security_checks:
            jobs, eosl = self._vuln_jobs(detail, options,
                                         queries=queries)
        prepared = PreparedScan(target=target, options=options,
                                detail=detail, jobs=jobs, eosl=eosl,
                                pkg_results=pkg_results,
                                queries=queries,
                                tenant=self.tenant)
        if self.memo is not None and jobs:
            # hit/miss partition: verdicts answered before are
            # served at finish; only novel queries keep their jobs
            # for the device dispatch (docs/performance.md)
            prepared.memo_plan = self.memo.partition(
                prepared, blobs, detail, options, db=self.store)
            plan = prepared.memo_plan
            if plan is not None:
                # cost attribution (obs/cost.py): memo hits are
                # device work this tenant did NOT pay for — the
                # invoice shows them next to the device-seconds
                # the misses went on to cost
                from ..obs.cost import COST_LEDGER
                COST_LEDGER.charge(
                    self.tenant,
                    memo_hits=int(getattr(plan, "queries_hit", 0)
                                  or 0),
                    memo_misses=int(getattr(plan, "queries_miss",
                                            0) or 0))
        return prepared

    def finish(self, prepared: PreparedScan,
               detected: list) -> tuple:
        """Assemble results from the detected pair payloads."""
        options = prepared.options
        detail = prepared.detail
        results: list = []

        if prepared.memo_plan is not None:
            # record the novel queries' verdicts, append the served
            # hits — the hit payloads are THIS scan's own job
            # payloads, so results are byte-identical to a cold run
            detected = self.memo.resolve(prepared.memo_plan,
                                         detected)
            prepared.memo_plan = None

        if "vuln" in options.security_checks:
            if detail.os is not None:
                detail.os.eosl = prepared.eosl
            vuln_results = self._vuln_results(
                prepared.target.name, detail, detected,
                options.vuln_type)
            results.extend(self._fill_pkgs(prepared.pkg_results,
                                           vuln_results))
        else:
            results.extend(prepared.pkg_results)

        if "config" in options.security_checks:
            results.extend(self._misconf_results(detail))

        if "secret" in options.security_checks:
            results.extend(self._secret_results(detail))

        if "license" in options.security_checks:
            results.extend(self._license_results(
                detail, getattr(options, "license_categories", None)))

        # module-collected custom resources ride a Result of class
        # "custom" so post-scanners can read them (ref
        # local/scan.go:154-163)
        if detail.custom_resources:
            results.append(Result(
                target="", class_=ResultClass.CUSTOM,
                custom_resources=list(detail.custom_resources)))

        for r in results:
            fill_info(self.store, r.vulnerabilities)

        # post-scan hook chain (ref local/scan.go:170-174 post.Scan)
        from .post import post_scan
        results = post_scan(results)

        return results, detail.os

    # --- vulnerabilities ---

    def _vuln_jobs(self, detail, options,
                   queries: Optional[list] = None) -> tuple:
        jobs: list = []
        eosl = False
        if queries is not None:
            from ..memo.findings import MemoQuery

        cdb = self.compiled
        if "os" in options.vuln_type and detail.os is not None \
                and detail.packages:
            driver = DRIVERS.get(detail.os.family)
            if driver is not None:
                eosl = not driver.is_supported(detail.os.name)
                bucket = driver.bucket(detail.os.name,
                                       detail.repository)
                for pkg in detail.packages:
                    installed = driver.installed(pkg)
                    qstart = len(jobs)
                    if cdb is not None:
                        for row in cdb.candidate_rows(
                                bucket, driver.src_name(pkg)):
                            adv = cdb.rows_meta[row][2]
                            if not driver.adv_match(
                                    detail.os.name, pkg, adv):
                                continue
                            jobs.append(ResidentPairJob(
                                cdb=cdb, row=row,
                                grammar=driver.grammar,
                                pkg_version=installed,
                                report_unfixed=driver.report_unfixed,
                                payload=("os", None, self._ospkg_vuln(
                                    driver, pkg, installed, adv))))
                    else:
                        for adv in self.store.get(
                                bucket, driver.src_name(pkg)):
                            if not driver.adv_match(detail.os.name,
                                                    pkg, adv):
                                continue
                            jobs.append(self._ospkg_job(
                                driver, pkg, installed, adv))
                    if queries is not None and len(jobs) > qstart:
                        queries.append(MemoQuery(
                            kind="os", bucket=bucket,
                            name=driver.src_name(pkg),
                            grammar=driver.grammar,
                            installed=installed,
                            report_unfixed=driver.report_unfixed,
                            pkg=pkg, start=qstart, end=len(jobs),
                            os_name=detail.os.name,
                            family=detail.os.family))
            elif detail.os.family not in ("none", ""):
                log.warning("unsupported os: %s", detail.os.family)

        if "library" in options.vuln_type:
            for app in detail.applications:
                if app.type not in LIB_TYPES:
                    continue
                eco, grammar = LIB_TYPES[app.type]
                for lib in app.libraries:
                    name = normalize_pkg_name(eco, lib.name)
                    qstart = len(jobs)
                    if cdb is not None:
                        for row in cdb.candidate_rows_prefix(
                                f"{eco}::", name):
                            adv = cdb.rows_meta[row][2]
                            jobs.append(ResidentPairJob(
                                cdb=cdb, row=row, grammar=grammar,
                                pkg_version=lib.version,
                                payload=("lib",
                                         (app.type, app.file_path),
                                         self._lib_vuln(lib, adv))))
                    else:
                        for adv in self.store.get_advisories(
                                f"{eco}::", name):
                            jobs.append(self._lib_job(
                                app, grammar, lib, adv))
                    if queries is not None and len(jobs) > qstart:
                        queries.append(MemoQuery(
                            kind="lib", bucket=f"{eco}::",
                            name=name, grammar=grammar,
                            installed=lib.version,
                            report_unfixed=True, pkg=lib,
                            start=qstart, end=len(jobs)))
        return jobs, eosl

    def _vuln_results(self, target: str, detail,
                      detected: list, vuln_type: list) -> list:
        os_vulns: list = []
        app_vulns: dict = {}
        for payload in detected:
            kind, key, vuln = payload
            if kind == "os":
                os_vulns.append(vuln)
            else:
                app_vulns.setdefault(key, []).append(vuln)

        results = []
        # the os-pkgs result is emitted whenever a known distro was
        # detected, even with zero findings (ref scan.go:243-271
        # scanOSPkgs returns a Result unless the OS is unknown or
        # unsupported; empty results are never filtered out)
        # gated on the os vuln type, like scanVulnerabilities
        # dispatch — `--vuln-type library` must not emit the husk
        has_driver = ("os" in vuln_type
                      and detail.os is not None
                      and DRIVERS.get(detail.os.family) is not None)
        if os_vulns or has_driver:
            target_name = target
            if detail.os is not None and detail.os.family and \
                    detail.os.family != "none":
                target_name = (f"{target} ({detail.os.family} "
                               f"{detail.os.name})")
            results.append(Result(
                target=target_name,
                class_=ResultClass.OSPKG,
                type=detail.os.family if detail.os else "",
                vulnerabilities=sorted(
                    os_vulns, key=lambda v: (v.pkg_name,
                                             v.vulnerability_id)),
            ))
        for app in detail.applications:
            key = (app.type, app.file_path)
            vulns = app_vulns.get(key)
            if not vulns:
                continue
            target_name = app.file_path or \
                _PKG_TARGETS.get(app.type, "")
            results.append(Result(
                target=target_name,
                class_=ResultClass.LANGPKG,
                type=app.type,
                vulnerabilities=sorted(
                    vulns, key=lambda v: (v.pkg_name,
                                          v.vulnerability_id)),
            ))
        return results

    def _ospkg_vuln(self, driver, pkg, installed,
                    adv) -> DetectedVulnerability:
        v = DetectedVulnerability(
            vulnerability_id=adv.vulnerability_id,
            vendor_ids=adv.vendor_ids,
            pkg_id=pkg.id,
            pkg_name=pkg.name,
            installed_version=installed,
            fixed_version=driver.fixed_version(adv),
            layer=pkg.layer,
            ref=pkg.ref,
            data_source=adv.data_source,
        )
        if driver.severity_source and adv.severity:
            v.severity_source = driver.severity_source
            v.vulnerability = Vulnerability(
                severity=str(SEVERITIES[adv.severity])
                if 0 <= adv.severity < 5 else "UNKNOWN")
        return v

    def _ospkg_job(self, driver, pkg, installed, adv) -> PairJob:
        v = self._ospkg_vuln(driver, pkg, installed, adv)
        return PairJob(
            grammar=driver.grammar,
            pkg_version=installed,
            fixed_version=adv.fixed_version,
            affected_version=adv.affected_version,
            report_unfixed=driver.report_unfixed,
            kind="ospkg",
            payload=("os", None, v),
        )

    def _lib_vuln(self, lib, adv) -> DetectedVulnerability:
        return DetectedVulnerability(
            vulnerability_id=adv.vulnerability_id,
            pkg_id=lib.id,
            pkg_name=lib.name,
            pkg_path=lib.file_path,
            installed_version=lib.version,
            fixed_version=_fixed_versions(adv),
            layer=lib.layer,
            ref=lib.ref,
            data_source=adv.data_source,
        )

    def _lib_job(self, app, grammar, lib, adv) -> PairJob:
        v = self._lib_vuln(lib, adv)
        return PairJob(
            grammar=grammar,
            pkg_version=lib.version,
            vulnerable=adv.vulnerable_versions,
            patched=adv.patched_versions,
            unaffected=adv.unaffected_versions,
            payload=("lib", (app.type, app.file_path), v),
        )

    # --- other result classes ---

    def _os_pkgs_result(self, target, detail) -> Optional[Result]:
        if not detail.packages or detail.os is None:
            return None
        pkgs = sorted(detail.packages, key=lambda p: p.name)
        return Result(
            target=f"{target} ({detail.os.family} {detail.os.name})",
            class_=ResultClass.OSPKG,
            type=detail.os.family,
            packages=pkgs,
        )

    def _lang_pkgs_results(self, detail) -> list:
        out = []
        for app in detail.applications:
            if not app.libraries:
                continue
            target = app.file_path or _PKG_TARGETS.get(app.type, "")
            out.append(Result(target=target,
                              class_=ResultClass.LANGPKG,
                              type=app.type,
                              packages=app.libraries))
        return out

    def _fill_pkgs(self, pkg_results, vuln_results) -> list:
        """Merge package listings into matching vuln results
        (scan.go fillPkgsInVulns)."""
        if not pkg_results:
            return vuln_results
        out = []
        used = set()
        for vr in vuln_results:
            for i, pr in enumerate(pkg_results):
                if (pr.class_, pr.target) == (vr.class_, vr.target):
                    vr.packages = pr.packages
                    used.add(i)
                    break
            out.append(vr)
        for i, pr in enumerate(pkg_results):
            if i not in used:
                out.append(pr)
        return out

    def _secret_results(self, detail) -> list:
        out = []
        for secret in detail.secrets:
            out.append(Result(
                target=secret.file_path,
                class_=ResultClass.SECRET,
                secrets=secret.findings,
            ))
        return out

    def _misconf_results(self, detail) -> list:
        """misconfsToResults (ref local/scan.go:337-371): flatten each
        file's failures/warnings/successes into status-tagged
        DetectedMisconfigurations."""
        out = []
        for mc in detail.misconfigurations:
            detected = []
            for f in mc.failures:
                detected.append(_to_detected_misconf(
                    f, "CRITICAL", "FAIL", mc.layer,
                    traces=mc.traces))
            for w in mc.warnings:
                detected.append(_to_detected_misconf(
                    w, "MEDIUM", "FAIL", mc.layer,
                    traces=mc.traces))
            # the per-file trace rides every failure; an all-pass
            # file carries it once on its first success — exactly
            # the case where "clean" must be distinguishable from
            # "couldn't evaluate" — instead of duplicating the
            # whole list onto every PASS row
            file_traces = mc.traces if not (
                mc.failures or mc.warnings) else []
            for s in mc.successes:
                detected.append(_to_detected_misconf(
                    s, "UNKNOWN", "PASS", mc.layer,
                    traces=file_traces))
                file_traces = []
            for e in mc.exceptions:
                detected.append(_to_detected_misconf(
                    e, "UNKNOWN", "EXCEPTION", mc.layer,
                    traces=mc.traces))
            out.append(Result(
                target=mc.file_path,
                class_=ResultClass.CONFIG,
                type=mc.file_type,
                misconfigurations=detected,
            ))
        out.sort(key=lambda r: r.target)
        return out


    def _license_results(self, detail, categories) -> list:
        """scanLicenses (ref local/scan.go:372-396 + 145-149): OS
        package licenses, per-application licenses, and loose-file
        classifier findings, each category-mapped to a severity."""
        from ..licensing import LicenseScanner
        from ..types.report import DetectedLicense

        scanner = LicenseScanner(categories or None)
        results = []

        os_licenses = []
        for pkg in detail.packages:
            for lic in pkg.licenses:
                category, severity = scanner.scan(lic)
                os_licenses.append(DetectedLicense(
                    severity=severity, category=category,
                    pkg_name=pkg.name, name=lic, confidence=1.0))
        results.append(Result(
            target="OS Packages", class_=ResultClass.LICENSE,
            licenses=os_licenses))

        for app in detail.applications:
            app_licenses = []
            for lib in app.libraries:
                for lic in lib.licenses:
                    category, severity = scanner.scan(lic)
                    app_licenses.append(DetectedLicense(
                        severity=severity, category=category,
                        pkg_name=lib.name, name=lic,
                        confidence=1.0))
            target = app.file_path or _PKG_TARGETS.get(app.type, "")
            results.append(Result(
                target=target, class_=ResultClass.LICENSE,
                licenses=app_licenses))

        file_licenses = []
        for lf in detail.licenses:
            for finding in lf.findings:
                category, severity = scanner.scan(finding.name)
                file_licenses.append(DetectedLicense(
                    severity=severity, category=category,
                    file_path=lf.file_path, name=finding.name,
                    confidence=finding.confidence,
                    link=finding.link))
        results.append(Result(
            target="Loose File License(s)",
            class_=ResultClass.LICENSE_FILE,
            licenses=file_licenses))
        return results


def _to_detected_misconf(res, default_severity: str, status: str,
                         layer, traces=None):
    """toDetectedMisconfiguration (ref local/scan.go:398-452)."""
    from ..types.report import DetectedMisconfiguration

    severity = res.severity or default_severity
    msg = (res.message or "").strip() or "No issues found"
    references = list(res.references)
    primary_url = ""
    if not res.namespace or res.namespace.startswith("builtin."):
        primary_url = ("https://avd.aquasec.com/misconfig/"
                       f"{res.id.lower()}")
        if primary_url not in references:
            references.append(primary_url)
    if not primary_url and references:
        primary_url = references[0]
    return DetectedMisconfiguration(
        type=res.type, id=res.id, avd_id=res.avd_id,
        title=res.title, description=res.description,
        message=msg, namespace=res.namespace, query=res.query,
        resolution=res.recommended_actions,
        severity=severity, primary_url=primary_url,
        references=references, status=status, layer=layer,
        cause_metadata=res.cause_metadata,
        traces=list(traces or []))
