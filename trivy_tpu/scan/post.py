"""Post-scan hook registry (reference: pkg/scanner/post/
post_scan.go:11-45).

Hooks run after every scan over the assembled results — the mount
point the reference uses for WASM post-scanners; here any object with
``name``/``version``/``post_scan(results) -> results`` registers, and
the module system (trivy_tpu.module) plugs its post-scanners in
through this registry.
"""

from __future__ import annotations

from ..utils import get_logger

log = get_logger("scan.post")

_SCANNERS: dict = {}


def register_post_scanner(s) -> None:
    _SCANNERS[s.name] = s


def deregister_post_scanner(name: str) -> None:
    _SCANNERS.pop(name, None)


def post_scanner_versions() -> dict:
    return {name: s.version for name, s in _SCANNERS.items()}


def post_scan(results: list) -> list:
    """Hook errors abort the scan, like the reference's
    post.Scan (post_scan.go:35-44)."""
    for name in sorted(_SCANNERS):
        results = _SCANNERS[name].post_scan(results)
    return results
