"""Scan orchestration: applier → detectors → results → report.

Reference: pkg/scanner (scan.go) + pkg/scanner/local (scan.go:78-175).
"""

from .local import LocalScanner, ScanTarget
from .filter import filter_results

__all__ = ["LocalScanner", "ScanTarget", "filter_results"]
