"""Result filtering (reference: pkg/result/filter.go:31-).

Severity filter, --ignore-unfixed, .trivyignore id list, and the
uniqueness pass (filter.go shouldOverwrite: for duplicate
(ID, pkg, path, version) keep the entry that has a fixed version).
OPA Rego ignore policies are handled by the policy hook when provided.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from ..types import SEVERITIES, Severity


class IgnorePolicyError(Exception):
    """A user ignore-policy failed to load or raised while
    evaluating a finding."""


def load_ignore_policy(path: str):
    """--ignore-policy: a Python file defining ``ignore(finding) ->
    bool`` over the finding's JSON dict (the analog of the
    reference's Rego ``data.trivy.ignore`` query, filter.go:162-219;
    Python predicate instead of OPA — same contract, same hook).

    TRUST DIFFERENCE vs the reference: Rego is evaluated in a
    sandbox; this policy file is ``exec``ed with full interpreter
    rights (as is a module loaded by module/__init__.py). Treat
    policy files like code you run, not like configuration."""
    if not path:
        return None
    import types as _types
    with open(path, encoding="utf-8") as f:
        source = f.read()
    mod = _types.ModuleType("trivy_ignore_policy")
    try:
        exec(compile(source, path, "exec"), mod.__dict__)
    except Exception as e:               # noqa: BLE001
        raise IgnorePolicyError(f"{path}: {e!r}")
    fn = getattr(mod, "ignore", None)
    if not callable(fn):
        raise IgnorePolicyError(
            f"ignore policy {path} must define ignore(finding)")

    def predicate(finding):
        try:
            return bool(fn(finding.to_dict()))
        except Exception as e:           # noqa: BLE001
            raise IgnorePolicyError(f"ignore() raised: {e!r}")
    return predicate


def load_ignore_file(path: str = ".trivyignore") -> list:
    if not path or not os.path.exists(path):
        return []
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.append(line.split()[0])
    return out


def filter_results(results: list, severities: list,
                   ignore_unfixed: bool = False,
                   ignored_ids: Optional[list] = None,
                   policy: Optional[Callable] = None,
                   include_non_failures: bool = False) -> list:
    sev_names = {str(s) if isinstance(s, Severity) else s
                 for s in severities}
    ignored = set(ignored_ids or [])
    sev_rank = {str(s): i for i, s in enumerate(SEVERITIES)}

    for r in results:
        r.vulnerabilities = _filter_vulns(
            r.vulnerabilities, sev_names, ignore_unfixed, ignored,
            policy)
        # BySeverity ordering (ref types/vulnerability.go:44-57,
        # applied after filtering at filter.go:47): package, then
        # installed version, then severity DESCENDING, then id
        r.vulnerabilities.sort(
            key=lambda v: (v.pkg_name, v.installed_version,
                           -sev_rank.get(v.severity, 0),
                           v.vulnerability_id))
        r.misconf_summary, r.misconfigurations = _filter_misconfs(
            r.misconfigurations, sev_names, ignored,
            include_non_failures, policy)
        r.secrets = [s for s in r.secrets
                     if s.severity in sev_names
                     and s.rule_id not in ignored
                     and not (policy is not None and policy(s))]
        r.licenses = [lic for lic in r.licenses
                      if lic.severity in sev_names
                      and lic.name not in ignored
                      and not (policy is not None and policy(lic))]
    return results


def _filter_misconfs(misconfs: list, sev_names: set, ignored: set,
                     include_non_failures: bool,
                     policy=None) -> tuple:
    """filterMisconfigurations (filter.go:124-154): severity/id
    filter, PASS/EXCEPTION dropped unless requested, and a
    pass/fail/exception summary."""
    from ..types.report import MisconfSummary
    summary = MisconfSummary()
    filtered = []
    for m in misconfs:
        if getattr(m, "severity", "") not in sev_names:
            continue
        if getattr(m, "id", "") in ignored or \
                getattr(m, "avd_id", "") in ignored:
            continue
        if policy is not None and policy(m):
            continue
        status = getattr(m, "status", "")
        if status == "FAIL":
            summary.failures += 1
        elif status == "PASS":
            summary.successes += 1
        elif status == "EXCEPTION":
            summary.exceptions += 1
        if status != "FAIL" and not include_non_failures:
            continue
        filtered.append(m)
    if not (summary.failures or summary.successes or
            summary.exceptions):
        return None, []
    return summary, filtered


def _filter_vulns(vulns: list, sev_names: set, ignore_unfixed: bool,
                  ignored: set, policy) -> list:
    unique: dict = {}
    for v in vulns:
        if v.severity not in sev_names:
            continue
        if ignore_unfixed and not v.fixed_version:
            continue
        if v.vulnerability_id in ignored:
            continue
        if policy is not None and policy(v):
            continue
        key = (v.vulnerability_id, v.pkg_name, v.pkg_path,
               v.installed_version)
        old = unique.get(key)
        unique[key] = v if old is None else _merge_duplicate(old, v)
    return list(unique.values())


_REDHAT_SOURCES = {"redhat", "redhat-oval"}


def _is_redhat(v) -> bool:
    if getattr(v, "severity_source", "") == "redhat":
        return True
    ds = getattr(v, "data_source", None)
    return ds is not None and \
        getattr(ds, "id", "") in _REDHAT_SOURCES


def _merge_duplicate(old, new):
    """Duplicate (ID, pkg, path, version) handling. Red Hat pairs
    get the reference detector's same-CVE merge (redhat.go uniqVulns:
    several RHSAs can fix one CVE — report the NEWEST FixedVersion
    per the rpm comparer and the UNION of vendor ids, so neither
    advisory's RHSA link is dropped); everything else keeps
    shouldOverwrite semantics — prefer the entry carrying a fix."""
    if _is_redhat(old) and _is_redhat(new):
        winner, loser = old, new
        if old.fixed_version != new.fixed_version:
            if not old.fixed_version:
                winner, loser = new, old
            elif new.fixed_version:
                try:
                    from ..vercmp import get_comparer
                    rpm = get_comparer("rpm")
                    if rpm.parse(new.fixed_version) > \
                            rpm.parse(old.fixed_version):
                        winner, loser = new, old
                except ValueError:
                    pass            # unparseable: keep first
        if loser.vendor_ids:
            winner.vendor_ids = sorted(
                set(winner.vendor_ids) | set(loser.vendor_ids))
        return winner
    # shouldOverwrite: prefer the entry carrying a fix
    if not old.fixed_version and new.fixed_version:
        return new
    return old
