"""Zero-dependency per-request tracing (docs/observability.md).

Dapper-style spans over the scan pipeline: every admitted request
gets a root ``scan`` span whose children bracket the stages it moved
through — ``queue_wait`` → ``analyze`` (host) → ``coalesce`` (with
batch id, padding bucket and occupancy) → ``device`` (one span per
dispatch attempt, so bisect retries and quarantine probes are
visible as siblings) → ``host_fallback`` (quarantine only) →
``report``. Fault injections, guard-budget trips and breaker
degradations land as span EVENTS on whatever span is active.

Identifiers follow the W3C/OTel shape (hex trace/span ids) but the
wire format is the Chrome trace-event JSON Perfetto loads directly
(``to_chrome``): complete spans become ``"ph": "X"`` duration events
keyed by the thread that ran them, span events become ``"ph": "i"``
instants.

A :class:`Tracer` is one tracing domain. The module-level default
(:func:`get_tracer`) is what the scheduler, the batch runner and the
RPC server share unless a test injects its own; disabling a tracer
(``Tracer(enabled=False)``) turns every ``start_span`` into a shared
no-op span, which is the differential arm the ``obs`` bench measures
overhead against.

Everything here is import-light on purpose: no trivy_tpu imports at
module scope, so the logging layer and the guard/fault seams can
reach :func:`add_event` without cycles.
"""

from __future__ import annotations

import contextvars
import os
import re
import threading
import time

_ID_RE = re.compile(r"[0-9a-f]{8,64}")

# spans per trace / concurrently open traces are bounded so a request
# source that never completes (or a hostile trace_id storm) cannot
# grow the tracer without limit
MAX_SPANS_PER_TRACE = 4096
MAX_OPEN_TRACES = 1024
# distinct span NAMES tracked as /metrics histograms: each name is a
# label value on trivy_tpu_trace_span_seconds, so a hostile or buggy
# caller minting names must fold into "other" instead of growing the
# exposition without bound (same policy sched/tenant.py applies to
# tenant labels)
MAX_PHASE_NAMES = 64


def new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def _clean_trace_id(trace_id) -> str:
    """Externally supplied trace ids (RPC bodies) are only honored in
    the canonical lowercase-hex shape — anything else gets a fresh id
    (the id is later used as a flight-recorder file name, so this is
    a security boundary, not just hygiene). fullmatch, not match: $
    would admit a trailing newline into the file name."""
    trace_id = (trace_id or "").lower()
    return trace_id if _ID_RE.fullmatch(trace_id) else ""


_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "trivy_tpu_active_span", default=None)


def current_span():
    """The span active on this thread/context, or None."""
    return _ACTIVE.get()


def add_event(name: str, **attrs) -> None:
    """Record an event on the active span; no-op without one. The
    guard budgets, the fault injector and the resilient cache call
    this — they never need a tracer handle."""
    span = _ACTIVE.get()
    if span is not None:
        span.event(name, **attrs)


class _PhaseSpanCtx:
    """Context manager behind :func:`phase_span`: opens a child of
    the active span (activated, so nested phases chain), ends it on
    exit — with status "error" when the body raised."""

    __slots__ = ("name", "attrs", "span", "_token")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.span = NOOP_SPAN
        self._token = None

    def __enter__(self):
        parent = _ACTIVE.get()
        if parent is not None and not parent.noop:
            self.span = parent.tracer.child(parent, self.name,
                                            **self.attrs)
            self._token = _ACTIVE.set(self.span)
        return self.span

    def __exit__(self, exc_type, *exc):
        if self._token is not None:
            _ACTIVE.reset(self._token)
        self.span.end("error" if exc_type is not None else None)


def phase_span(name: str, **attrs) -> _PhaseSpanCtx:
    """``with phase_span("pack"):`` — bracket a pipeline phase as a
    child of whatever span is active on this thread, or do nothing
    when none is. This is how deep seams (segment packing, H2D
    uploads, resident-DB staging) show up in Perfetto without
    threading a tracer handle through every call chain
    (docs/performance.md)."""
    return _PhaseSpanCtx(name, attrs)


def activate_or_null(span):
    """``with activate_or_null(sp):`` — activate ``span`` on this
    thread, or do nothing when there is none. The async slot
    runtime hops threads (hostpool packers, ring drain) and carries
    the launching batch's span along this way."""
    import contextlib
    return span.activate() if span is not None \
        else contextlib.nullcontext()


class _SpanContext:
    __slots__ = ("span", "_token")

    def __init__(self, span):
        self.span = span
        self._token = None

    def __enter__(self):
        self._token = _ACTIVE.set(self.span)
        return self.span

    def __exit__(self, *exc):
        _ACTIVE.reset(self._token)


class Span:
    """One timed operation: wall-anchored start, monotonic duration,
    typed attributes, instant events. ``end`` is idempotent and
    hands the finished span to its tracer."""

    noop = False
    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "start_wall", "start_mono", "end_mono", "attrs",
                 "events", "status", "tid", "is_root")

    def __init__(self, tracer, name: str, trace_id: str,
                 parent_id=None, attrs=None):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        # a propagated (remote) parent makes a LOCAL root whose
        # parent_id points into another process's trace: is_root, not
        # parent_id, decides completion bookkeeping from here on
        self.is_root = parent_id is None
        self.name = name
        self.start_wall = time.time()
        self.start_mono = time.monotonic()
        self.end_mono = None
        self.attrs = dict(attrs) if attrs else {}
        self.events = []
        self.status = "ok"
        self.tid = threading.get_ident()

    def set(self, key: str, value) -> None:
        self.attrs[key] = value

    def event(self, name: str, **attrs) -> None:
        self.events.append((time.monotonic(), name, attrs))

    def activate(self) -> _SpanContext:
        """``with span.activate():`` — publish as the thread's
        current span (log correlation + add_event routing)."""
        return _SpanContext(self)

    @property
    def duration_s(self) -> float:
        if self.end_mono is None:
            return 0.0
        return max(0.0, self.end_mono - self.start_mono)

    def end(self, status=None) -> None:
        if self.end_mono is not None:
            return
        self.end_mono = time.monotonic()
        if status and status != "ok":
            self.status = status
        self.tracer._finish(self)


class _NoopSpan:
    """Shared do-nothing span returned by a disabled tracer."""

    noop = True
    trace_id = ""
    span_id = ""
    parent_id = None
    is_root = False
    name = ""
    attrs: dict = {}
    events: list = []
    status = "ok"
    start_mono = 0.0
    end_mono = 0.0
    duration_s = 0.0

    def set(self, key, value):
        pass

    def event(self, name, **attrs):
        pass

    def end(self, status=None):
        pass

    def activate(self):
        return _NOOP_CTX


class _NoopCtx:
    def __enter__(self):
        return NOOP_SPAN

    def __exit__(self, *exc):
        pass


_NOOP_CTX = _NoopCtx()
NOOP_SPAN = _NoopSpan()


class Tracer:
    """One tracing domain: creates spans, collects completed traces
    into the flight recorder, optionally exports each completed
    trace as Perfetto-loadable JSON, and derives per-span-name
    latency histograms for ``/metrics``."""

    def __init__(self, enabled: bool = True, recorder=None,
                 export_dir: str = "", phase_metrics: bool = True):
        self.enabled = enabled
        self.export_dir = export_dir
        self.epoch_wall = time.time()
        self.epoch_mono = time.monotonic()
        self._lock = threading.Lock()
        self._spans: dict = {}    # open trace_id -> [finished Span]
        # propagated traces can have several concurrently-open LOCAL
        # roots on one trace_id (N scans sharing a fleet trace): the
        # bucket completes when the LAST root ends, and a bad status
        # on any earlier root still forces the dump
        self._open_roots: dict = {}   # trace_id -> open root count
        self._dirty: set = set()      # trace_ids owed a dump
        if recorder is None:
            from .recorder import FlightRecorder
            recorder = FlightRecorder()
        self.recorder = recorder
        # dumps triggered off-tracer (SLO burn-rate trips, operator
        # pokes) must land on the same timebase as _finish's dumps
        recorder.epoch_mono = self.epoch_mono
        self._phase = {} if phase_metrics else None
        self.n_spans = 0
        self.n_traces = 0
        self.n_exported = 0

    # --- span creation ---

    def start_span(self, name: str, trace_id: str = "",
                   parent=None, attrs=None, remote_parent: str = ""):
        if not self.enabled:
            return NOOP_SPAN
        if parent is not None:
            if parent.noop:
                return NOOP_SPAN
            span = Span(self, name, parent.trace_id,
                        parent_id=parent.span_id, attrs=attrs)
            req = parent.attrs.get("request")
            if req is not None and "request" not in span.attrs:
                span.attrs["request"] = req
            return span
        span = Span(self, name,
                    _clean_trace_id(trace_id) or new_trace_id())
        rp = _clean_trace_id(remote_parent)
        if rp:
            # a propagated parent from another process: this span is
            # still a LOCAL root (it owns its bucket's completion)
            # but its parent_id links it into the fleet-wide tree
            span.parent_id = rp
        if attrs:
            span.attrs.update(attrs)
        with self._lock:
            while len(self._spans) >= MAX_OPEN_TRACES:
                # drop the oldest open trace — a root that never ends
                # must not pin its children forever
                dropped = next(iter(self._spans))
                self._spans.pop(dropped)
                self._open_roots.pop(dropped, None)
                self._dirty.discard(dropped)
            self._spans.setdefault(span.trace_id, [])
            self._open_roots[span.trace_id] = \
                self._open_roots.get(span.trace_id, 0) + 1
        return span

    def start_request(self, name: str, trace_id: str = "",
                      parent_span_id: str = ""):
        """Root span for one scan request; a propagated
        ``parent_span_id`` links it under a remote caller's span."""
        root = self.start_span("scan", trace_id=trace_id,
                               remote_parent=parent_span_id)
        root.set("request", name)
        return root

    def child(self, parent, name: str, **attrs):
        if parent is None or parent.noop:
            return NOOP_SPAN
        return self.start_span(name, parent=parent,
                               attrs=attrs or None)

    # --- completion plumbing ---

    def _finish(self, span: Span) -> None:
        if self._phase is not None and not span.is_root:
            self._observe_phase(span.name, span.duration_s,
                                span.trace_id)
        with self._lock:
            self.n_spans += 1
            if not span.is_root:
                bucket = self._spans.get(span.trace_id)
                if bucket is None:
                    # finished after its root (e.g. a sweep resolved
                    # the request mid-stage): file it with the
                    # completed trace while it is still in the ring
                    self.recorder.append(span.trace_id, span)
                elif len(bucket) < MAX_SPANS_PER_TRACE:
                    bucket.append(span)
                return
            remaining = self._open_roots.get(span.trace_id, 1) - 1
            if remaining > 0:
                # sibling roots on the same propagated trace are
                # still open: file this root like a child and keep
                # the bucket until the last one ends
                self._open_roots[span.trace_id] = remaining
                bucket = self._spans.get(span.trace_id)
                if bucket is not None and \
                        len(bucket) < MAX_SPANS_PER_TRACE:
                    bucket.append(span)
                if span.status in ("degraded", "failed", "error"):
                    self._dirty.add(span.trace_id)
                return
            self._open_roots.pop(span.trace_id, None)
            spans = self._spans.pop(span.trace_id, [])
            spans.append(span)
            self.n_traces += 1
            dirty = span.trace_id in self._dirty
            self._dirty.discard(span.trace_id)
        self._complete(span, spans, dirty=dirty)

    def _observe_phase(self, name: str, dur_s: float,
                       trace_id: str = "") -> None:
        from ..sched.metrics import LatencyHistogram
        with self._lock:
            h = self._phase.get(name)
            if h is None:
                if len(self._phase) >= MAX_PHASE_NAMES:
                    # cardinality cap: overflow names fold into one
                    # shared histogram so /metrics stays bounded
                    name = "other"
                    h = self._phase.get(name)
                if h is None:
                    h = self._phase[name] = LatencyHistogram()
            h.observe(dur_s, exemplar=trace_id)

    def _complete(self, root: Span, spans: list,
                  dirty: bool = False) -> None:
        self.recorder.add(root.trace_id, spans)
        if self.export_dir:
            try:
                self._export(root.trace_id, spans)
            except OSError:
                pass
        if dirty or root.status in ("degraded", "failed", "error"):
            # degraded/failed scans dump the full trace to disk so
            # the evidence outlives the in-memory ring ("rejected"
            # backpressure answers deliberately do NOT — a 503 storm
            # must not become a disk-write storm; the recorder also
            # caps how many dump files it keeps)
            try:
                self.recorder.dump(root.trace_id, spans,
                                   epoch_mono=self.epoch_mono)
            except (OSError, ValueError):
                pass

    def _export(self, trace_id: str, spans: list) -> None:
        self.recorder.write_doc(
            os.path.join(self.export_dir, f"trace-{trace_id}.json"),
            to_chrome(spans, self.epoch_mono, self.epoch_wall))
        self.n_exported += 1

    # --- lookup / reporting ---

    def trace(self, trace_id: str):
        """Chrome trace-event document for one trace (completed, or
        the finished spans of one still in flight), or None."""
        spans = self.recorder.get(trace_id)
        if spans is None:
            with self._lock:
                open_spans = self._spans.get(trace_id)
                spans = list(open_spans) if open_spans else None
        if spans is None:
            return None
        return to_chrome(spans, self.epoch_mono, self.epoch_wall)

    def phase_snapshot(self) -> dict:
        """{span name: raw histogram} for Prometheus exposition
        (with per-bucket trace-id exemplars)."""
        with self._lock:
            return {name: h.raw()
                    for name, h in (self._phase or {}).items()}

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "spans": self.n_spans,
                    "traces": self.n_traces,
                    "open_traces": len(self._spans),
                    "exported": self.n_exported}


def to_chrome(spans: list, epoch_mono: float = 0.0,
              epoch_wall=None) -> dict:
    """Chrome trace-event JSON (Perfetto / chrome://tracing): spans
    as complete ("X") duration events, span events as instants."""
    events = []
    for s in spans:
        end = s.end_mono if s.end_mono is not None else s.start_mono
        args = {"trace_id": s.trace_id, "span_id": s.span_id,
                "status": s.status}
        if s.parent_id:
            args["parent_id"] = s.parent_id
        args.update(s.attrs)
        tid = s.tid & 0xffff
        events.append({
            "ph": "X", "cat": "trivy_tpu", "name": s.name,
            "ts": round((s.start_mono - epoch_mono) * 1e6, 3),
            "dur": round(max(0.0, end - s.start_mono) * 1e6, 3),
            "pid": 1, "tid": tid, "args": args,
        })
        for t, name, attrs in s.events:
            events.append({
                "ph": "i", "cat": "trivy_tpu", "name": name,
                "ts": round((t - epoch_mono) * 1e6, 3),
                "s": "t", "pid": 1, "tid": tid,
                "args": dict(attrs),
            })
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if epoch_wall is not None:
        out["otherData"] = {"epoch_unix_s": round(epoch_wall, 6)}
    return out


def summarize(spans: list) -> str:
    """One-line phase breakdown: 'scan 42.1ms: queue_wait 0.2ms,
    analyze 30.0ms, device 8.1ms, report 2.3ms'."""
    root = next((s for s in spans
                 if getattr(s, "is_root", s.parent_id is None)),
                None)
    parts = [f"{s.name} {s.duration_s * 1e3:.1f}ms"
             for s in spans
             if not getattr(s, "is_root", s.parent_id is None)]
    head = (f"{root.name} {root.duration_s * 1e3:.1f}ms"
            if root is not None else "")
    if parts:
        return (head + ": " if head else "") + ", ".join(parts)
    return head


def trace_cause(tracer: Tracer, trace_id: str) -> dict:
    """FailureCause payload a degraded/failed result carries so the
    operator can pull the request's trace (served at /trace/<id>,
    dumped by the flight recorder)."""
    return {"stage": "obs", "kind": "trace",
            "message": f"trace {trace_id} captured (dump: "
                       f"{tracer.recorder.dump_path(trace_id)})"}


_TRACER = None
_TRACER_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    """The process-default tracer (created on first use, with the
    flight recorder's log ring attached to the trivy_tpu logger)."""
    global _TRACER
    if _TRACER is None:
        candidate = Tracer()
        with _TRACER_LOCK:
            if _TRACER is None:
                _TRACER = candidate
                won = candidate
            else:
                won = None
        if won is not None:
            # the handler attach takes the recorder's and logging's
            # locks — outside _TRACER_LOCK (lint: lock-discipline).
            # A racing get_tracer() may briefly see the tracer
            # before its log ring attaches; only the first
            # microseconds of log capture can miss.
            from .recorder import attach_ring_handler
            attach_ring_handler(won.recorder)
    return _TRACER
