"""Declarative SLOs with multi-window burn-rate alerting
(docs/observability.md "SLOs & burn rates").

ROADMAP item 3's autoscaling signal needs *verdicts*, not raw
histograms: "is the error budget burning fast enough that a human
(or an autoscaler) must act". This module is the standard SRE
multi-window multi-burn-rate construction over the scheduler's
request outcomes:

* an :class:`SLO` declares an **objective** over a class of events —
  ``availability`` (request resolved without failing/timing out) or
  ``latency`` (request resolved under ``threshold_s``) — scoped
  globally or to one tenant (``tenant=``) / priority class
  (``min_priority=``), riding the same identity PR-7's
  ``TenantBook`` keeps histograms for;
* the engine buckets good/bad events into a monotonic-clock ring and
  computes **burn rates** — ``(bad share over window) / (1 -
  objective)`` — over paired windows: **5m/1h** (fast, page-worthy,
  trips at burn >= 14.4 = budget gone in ~2 days) and **30m/6h**
  (slow, ticket-worthy, trips at burn >= 6). Both windows of a pair
  must agree, so a single bad burst right before a quiet hour cannot
  page;
* verdicts are served at ``GET /slo`` and exported as
  ``trivy_tpu_slo_*`` gauges; each violated SLO carries **exemplar
  trace ids** of its worst recent bad events, and a trip TRANSITION
  auto-dumps those traces through the PR-4 flight recorder — the
  evidence is on disk before anyone asks.

Only ADMITTED requests count: backpressure rejections (429/503) are
the tenancy layer's shed accounting, not availability events — an
SLO over load you refused on purpose would page on policy.

Clock discipline: the ring keys and window math are
``time.monotonic`` only (lint-enforced); wall time appears solely as
exemplar labels.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

# (label, short window s, long window s, burn-rate threshold)
FAST_WINDOWS = ("5m", 300.0, 3600.0, 14.4)
SLOW_WINDOWS = ("30m", 1800.0, 21600.0, 6.0)
WINDOW_LABELS = ("5m", "1h", "30m", "6h")

_BUCKET_S = 10.0            # ring granularity
_RING_CAP = int(21600 / _BUCKET_S) + 2     # longest window + slack
_EXEMPLARS = 8              # worst bad traces kept per SLO

_BAD_OUTCOMES = ("failed", "timed_out")


@dataclass(frozen=True)
class SLO:
    """One declarative objective. ``kind`` is ``availability``,
    ``latency`` (additionally needs ``threshold_s``), or
    ``efficiency`` — the MFU-style goodput gauge: its events are
    device MILLISECONDS (useful vs. demand-gated idle, booked by
    :meth:`SloEngine.record_device` from the cost-attribution
    plane), not request outcomes, and ``objective`` is the target
    useful share of device wall."""

    name: str
    kind: str = "availability"
    objective: float = 0.99         # good-event share target
    threshold_s: float = 0.0        # latency: good iff under this
    tenant: str = ""                # "" = all tenants
    min_priority: int = -(10 ** 9)  # scope to a priority class

    def __post_init__(self):
        if self.kind not in ("availability", "latency",
                             "efficiency"):
            raise ValueError(f"SLO {self.name!r}: unknown kind "
                             f"{self.kind!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"SLO {self.name!r}: objective must "
                             f"be in (0, 1), got {self.objective}")
        if self.kind == "latency" and self.threshold_s <= 0:
            raise ValueError(f"SLO {self.name!r}: latency SLOs "
                             f"need threshold_s > 0")

    def matches(self, tenant: str, priority: int) -> bool:
        if self.tenant and tenant != self.tenant:
            return False
        return priority >= self.min_priority

    def classify(self, outcome: str, latency_s: float):
        """True=good, False=bad, None=out of scope (cancelled
        requests are the caller's choice, not the service's)."""
        if self.kind == "efficiency":
            # device-time events only (record_device) — a request
            # outcome carries no goodput information
            return None
        if outcome == "cancelled":
            return None
        if self.kind == "availability":
            return outcome not in _BAD_OUTCOMES
        # latency: a request that never completed blew the target
        if outcome in _BAD_OUTCOMES:
            return False
        return latency_s <= self.threshold_s


def default_slos() -> list:
    """The out-of-the-box objectives: 99% of admitted requests
    resolve, 95% resolve under 30s. Deployments override via
    --slo-config (docs/serving.md)."""
    return [
        SLO(name="availability", kind="availability",
            objective=0.99),
        SLO(name="latency_p95_30s", kind="latency", objective=0.95,
            threshold_s=30.0),
    ]


def parse_slo_config(text) -> list:
    """``--slo-config`` parser, mirroring --tenant-config's inline
    grammar::

        avail:kind=availability,objective=0.999;
        lat:kind=latency,objective=0.95,threshold_s=2.5,tenant=alice

    Unknown keys and malformed values raise ValueError so a typo'd
    objective fails the run up front."""
    if isinstance(text, (list, tuple)):
        return list(text)
    text = (text or "").strip()
    if not text:
        return default_slos()
    coerce = {"kind": str, "tenant": str, "objective": float,
              "threshold_s": float, "min_priority": int}
    out = []
    for chunk in text.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, sep, rest = chunk.partition(":")
        name = name.strip()
        if not sep or not name:
            raise ValueError(f"bad slo-config entry {chunk!r} "
                             f"(want name:key=value,...)")
        kv: dict = {}
        for pair in rest.split(","):
            pair = pair.strip()
            if not pair:
                continue
            key, eq, raw = pair.partition("=")
            key = key.strip()
            if not eq or key not in coerce:
                raise ValueError(
                    f"bad slo-config entry {pair!r} for {name!r} "
                    f"(choose from {sorted(coerce)})")
            try:
                kv[key] = coerce[key](raw.strip())
            except (TypeError, ValueError):
                raise ValueError(
                    f"bad slo-config value for {name}.{key}: "
                    f"{raw!r}")
        out.append(SLO(name=name, **kv))
    if not out:
        raise ValueError("slo-config parsed to zero SLOs")
    names = [s.name for s in out]
    if len(set(names)) != len(names):
        # caught here so a typo'd config fails the CLI's clean
        # error path, not SloEngine.__init__ deep in server setup
        raise ValueError(f"duplicate SLO names: {names}")
    return out


def _trip_thresholds(kind: str, fast_burn: float,
                     slow_burn: float) -> tuple:
    """Per-kind burn thresholds. An efficiency book's burn rate is
    bounded by ``1 / (1 - objective)`` — idle share can never
    exceed 1 — so the standard 14.4/6 multipliers would be
    unreachable; an efficiency SLO trips at burn >= 1 on both
    windows of a pair, i.e. measured useful share below the
    objective sustained across the window pair."""
    if kind == "efficiency":
        return 1.0, 1.0
    return fast_burn, slow_burn


def _window_share(book, now: float, window_s: float) -> float:
    """Good share over one trailing window (the efficiency gauge
    value); 0 when the window is empty."""
    good, bad = SloEngine._window_counts(book, now, window_s)
    total = good + bad
    return good / total if total else 0.0


class _Exemplar:
    __slots__ = ("trace_id", "latency_s", "outcome", "t")

    def __init__(self, trace_id, latency_s, outcome, t):
        self.trace_id = trace_id
        self.latency_s = latency_s
        self.outcome = outcome
        self.t = t


@dataclass
class _Book:
    """Per-SLO state: the good/bad ring + trip latches."""

    slo: SLO
    ring: dict = field(default_factory=dict)  # bucket -> [good, bad]
    good: int = 0
    bad: int = 0
    exemplars: list = field(default_factory=list)
    fast_tripped: bool = False
    slow_tripped: bool = False
    trips: int = 0


class SloEngine:
    """Records outcomes, computes verdicts, dumps evidence.

    ``record`` is on the request-resolution path, so it is one dict
    update under one lock; burn-rate evaluation (which walks the
    rings) runs on ``verdicts()`` — the /slo and /metrics readers —
    and at most once per second opportunistically from ``record``
    so a trip dumps its traces even when nobody is scraping."""

    def __init__(self, slos=None, recorder=None,
                 fast_burn: float = FAST_WINDOWS[3],
                 slow_burn: float = SLOW_WINDOWS[3]):
        self.slos = list(slos) if slos is not None \
            else default_slos()
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.recorder = recorder
        self.fast_burn = fast_burn
        self.slow_burn = slow_burn
        self._lock = threading.Lock()
        self._books = {s.name: _Book(slo=s) for s in self.slos}
        self._last_eval = 0.0
        self.dumps = 0

    # --- recording ---

    def record(self, outcome: str, latency_s: float = 0.0,
               tenant: str = "", priority: int = 0,
               trace_id: str = "") -> None:
        now = time.monotonic()
        bucket = int(now / _BUCKET_S)
        with self._lock:
            for book in self._books.values():
                slo = book.slo
                if not slo.matches(tenant, priority):
                    continue
                verdict = slo.classify(outcome, latency_s)
                if verdict is None:
                    continue
                slot = book.ring.get(bucket)
                if slot is None:
                    slot = book.ring[bucket] = [0, 0]
                    while len(book.ring) > _RING_CAP:
                        book.ring.pop(next(iter(book.ring)))
                if verdict:
                    slot[0] += 1
                    book.good += 1
                else:
                    slot[1] += 1
                    book.bad += 1
                    if trace_id:
                        book.exemplars.append(_Exemplar(
                            trace_id, latency_s, outcome, now))
                        # worst-first (slowest / most recent), capped
                        book.exemplars.sort(
                            key=lambda e: (-e.latency_s, -e.t))
                        del book.exemplars[_EXEMPLARS:]
            due = now - self._last_eval >= 1.0
            if due:
                self._last_eval = now
        if due:
            self.verdicts(now=now)

    def record_device(self, useful_s: float,
                      idle_s: float = 0.0) -> None:
        """Book device goodput into every ``kind=efficiency``
        book: ``useful_s`` of attributed device wall as good
        events, ``idle_s`` of demand-gated idle (the device sat
        while admitted work waited) as bad — both in integer
        milliseconds so the ring stays count-shaped and the burn/
        federation math applies unchanged. Called by the scheduler
        at every dispatch collection (obs/cost.py); a no-op when no
        efficiency SLO is declared."""
        good_ms = max(0, int(float(useful_s) * 1000.0))
        bad_ms = max(0, int(float(idle_s) * 1000.0))
        if not good_ms and not bad_ms:
            return
        now = time.monotonic()
        bucket = int(now / _BUCKET_S)
        with self._lock:
            for book in self._books.values():
                if book.slo.kind != "efficiency":
                    continue
                slot = book.ring.get(bucket)
                if slot is None:
                    slot = book.ring[bucket] = [0, 0]
                    while len(book.ring) > _RING_CAP:
                        book.ring.pop(next(iter(book.ring)))
                slot[0] += good_ms
                slot[1] += bad_ms
                book.good += good_ms
                book.bad += bad_ms

    # --- burn-rate math ---

    @staticmethod
    def _window_counts(book: _Book, now: float,
                       window_s: float) -> tuple:
        lo = int((now - window_s) / _BUCKET_S)
        good = bad = 0
        for b, (g, bd) in book.ring.items():
            if b >= lo:
                good += g
                bad += bd
        return good, bad

    @staticmethod
    def _burn(book: _Book, now: float,
              window_s: float) -> float:
        good, bad = SloEngine._window_counts(book, now, window_s)
        total = good + bad
        if not total:
            return 0.0
        budget = 1.0 - book.slo.objective
        return (bad / total) / budget

    # --- verdicts ---

    def verdicts(self, now=None) -> list:
        """[{name, kind, objective, ok, burn{window: rate},
        fast_tripped, slow_tripped, exemplar_trace_ids, ...}] —
        the ``GET /slo`` payload. Trip TRANSITIONS dump the worst
        recent bad traces through the flight recorder."""
        if now is None:
            now = time.monotonic()
        to_dump: list = []
        out = []
        with self._lock:
            for book in self._books.values():
                slo = book.slo
                burns = {
                    "5m": self._burn(book, now, FAST_WINDOWS[1]),
                    "1h": self._burn(book, now, FAST_WINDOWS[2]),
                    "30m": self._burn(book, now, SLOW_WINDOWS[1]),
                    "6h": self._burn(book, now, SLOW_WINDOWS[2]),
                }
                fast_thr, slow_thr = _trip_thresholds(
                    slo.kind, self.fast_burn, self.slow_burn)
                fast = burns["5m"] >= fast_thr and \
                    burns["1h"] >= fast_thr
                slow = burns["30m"] >= slow_thr and \
                    burns["6h"] >= slow_thr
                if (fast and not book.fast_tripped) or \
                        (slow and not book.slow_tripped):
                    book.trips += 1
                    to_dump.extend(
                        e.trace_id for e in book.exemplars)
                book.fast_tripped = fast
                book.slow_tripped = slow
                entry = {
                    "name": slo.name,
                    "kind": slo.kind,
                    "objective": slo.objective,
                    "ok": not (fast or slow),
                    "burn": {k: round(v, 4)
                             for k, v in burns.items()},
                    "fast_tripped": fast,
                    "slow_tripped": slow,
                    "trips": book.trips,
                    "good": book.good,
                    "bad": book.bad,
                    "exemplar_trace_ids": [e.trace_id for e in
                                           book.exemplars],
                }
                if slo.kind == "latency":
                    entry["threshold_s"] = slo.threshold_s
                if slo.kind == "efficiency":
                    # the MFU-style gauge: useful share of device
                    # wall over the fast window (ms-weighted)
                    entry["efficiency"] = round(_window_share(
                        book, now, FAST_WINDOWS[1]), 4)
                if slo.tenant:
                    entry["tenant"] = slo.tenant
                out.append(entry)
        # dumps OUTSIDE the lock: recorder.dump does file IO
        for trace_id in dict.fromkeys(to_dump):
            self._dump(trace_id)
        return out

    def _dump(self, trace_id: str) -> None:
        if self.recorder is None:
            return
        try:
            self.recorder.dump(trace_id)
            self.dumps += 1
        except (OSError, ValueError):
            # evicted from the ring (or disk trouble): the verdict
            # still carries the trace id for /trace lookup
            pass

    def snapshot(self) -> dict:
        """The /metrics shape: verdict list + dump counter."""
        return {"slos": self.verdicts(), "dumps": self.dumps}

    # --- fleet federation (docs/observability.md "Fleet plane") ---

    def export_state(self, now=None) -> dict:
        """Serializable ring state for federation. Buckets are keyed
        by AGE (now_bucket - bucket) rather than the raw monotonic
        bucket index, because monotonic clocks share no epoch across
        processes — age is the only transferable coordinate, and it
        keeps the export monotonic-only per the clock rule."""
        if now is None:
            now = time.monotonic()
        now_bucket = int(now / _BUCKET_S)
        with self._lock:
            slos = []
            for book in self._books.values():
                s = book.slo
                slos.append({
                    "slo": {"name": s.name, "kind": s.kind,
                            "objective": s.objective,
                            "threshold_s": s.threshold_s,
                            "tenant": s.tenant,
                            "min_priority": s.min_priority},
                    "good": book.good,
                    "bad": book.bad,
                    "buckets": [[now_bucket - b, g, bd]
                                for b, (g, bd) in
                                book.ring.items()],
                    "exemplar_trace_ids": [e.trace_id for e in
                                           book.exemplars],
                })
        return {"bucket_s": _BUCKET_S, "slos": slos}


def merge_exports(exports: list) -> dict:
    """Sum N replicas' :meth:`SloEngine.export_state` documents by
    (SLO name, bucket age). The first export's SLO definition wins
    per name — a fleet is expected to run one config; a replica
    mid-rolling-deploy just contributes its counts."""
    merged: dict = {}
    order: list = []
    for ex in exports:
        if not isinstance(ex, dict):
            continue
        entries = ex.get("slos")
        if not isinstance(entries, list):
            continue
        for entry in entries:
            # peer documents arrive over the network: a malformed
            # entry is dropped, never allowed to poison the merge
            if not isinstance(entry, dict):
                continue
            slo = entry.get("slo") or {}
            if not isinstance(slo, dict):
                continue
            name = str(slo.get("name") or "")
            if not name:
                continue
            slot = merged.get(name)
            if slot is None:
                slot = merged[name] = {
                    "slo": dict(slo), "good": 0, "bad": 0,
                    "_ages": {}, "exemplar_trace_ids": []}
                order.append(name)
            slot["good"] += int(entry.get("good") or 0)
            slot["bad"] += int(entry.get("bad") or 0)
            for age, g, bd in entry.get("buckets") or []:
                acc = slot["_ages"].setdefault(int(age), [0, 0])
                acc[0] += int(g)
                acc[1] += int(bd)
            for tid in entry.get("exemplar_trace_ids") or []:
                if tid not in slot["exemplar_trace_ids"] and \
                        len(slot["exemplar_trace_ids"]) < \
                        _EXEMPLARS:
                    slot["exemplar_trace_ids"].append(tid)
    slos = []
    for name in order:
        slot = merged[name]
        ages = slot.pop("_ages")
        slot["buckets"] = [[a, g, bd] for a, (g, bd) in
                           sorted(ages.items())]
        slos.append(slot)
    return {"bucket_s": _BUCKET_S, "slos": slos}


def verdicts_from_export(export: dict, now=None,
                         fast_burn: float = FAST_WINDOWS[3],
                         slow_burn: float = SLOW_WINDOWS[3]) -> list:
    """Recompute the multi-window burn rates over an exported (or
    merged) bucket set — the SAME `_burn` math `verdicts()` runs, so
    a federated verdict over N replicas equals a single engine fed
    the union event stream (the unit tests prove byte-equality of
    ok/burn/good/bad). Trip latches are per-engine state and are
    reported from the merged counts' instantaneous view."""
    if now is None:
        now = time.monotonic()
    now_bucket = int(now / _BUCKET_S)
    out = []
    for entry in (export or {}).get("slos") or []:
        cfg = dict(entry.get("slo") or {})
        try:
            slo = SLO(name=str(cfg.get("name") or "slo"),
                      kind=str(cfg.get("kind") or "availability"),
                      objective=float(cfg.get("objective") or 0.99),
                      threshold_s=float(cfg.get("threshold_s")
                                        or 0.0),
                      tenant=str(cfg.get("tenant") or ""),
                      min_priority=int(cfg.get("min_priority")
                                       or -(10 ** 9)))
        except ValueError:
            continue
        book = _Book(slo=slo)
        for age, g, bd in entry.get("buckets") or []:
            slot = book.ring.setdefault(now_bucket - int(age),
                                        [0, 0])
            slot[0] += int(g)
            slot[1] += int(bd)
        burns = {
            "5m": SloEngine._burn(book, now, FAST_WINDOWS[1]),
            "1h": SloEngine._burn(book, now, FAST_WINDOWS[2]),
            "30m": SloEngine._burn(book, now, SLOW_WINDOWS[1]),
            "6h": SloEngine._burn(book, now, SLOW_WINDOWS[2]),
        }
        fast_thr, slow_thr = _trip_thresholds(
            slo.kind, fast_burn, slow_burn)
        fast = burns["5m"] >= fast_thr and burns["1h"] >= fast_thr
        slow = burns["30m"] >= slow_thr and \
            burns["6h"] >= slow_thr
        verdict = {
            "name": slo.name,
            "kind": slo.kind,
            "objective": slo.objective,
            "ok": not (fast or slow),
            "burn": {k: round(v, 4) for k, v in burns.items()},
            "fast_tripped": fast,
            "slow_tripped": slow,
            "good": int(entry.get("good") or 0),
            "bad": int(entry.get("bad") or 0),
            "exemplar_trace_ids": list(
                entry.get("exemplar_trace_ids") or []),
        }
        if slo.kind == "latency":
            verdict["threshold_s"] = slo.threshold_s
        if slo.kind == "efficiency":
            verdict["efficiency"] = round(_window_share(
                book, now, FAST_WINDOWS[1]), 4)
        if slo.tenant:
            verdict["tenant"] = slo.tenant
        out.append(verdict)
    return out
