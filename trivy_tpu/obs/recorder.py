"""Flight recorder: a bounded in-memory ring of the last N completed
traces plus the recent log tail (docs/observability.md).

The ring is always on — it costs one OrderedDict entry per completed
trace and evicts FIFO past ``capacity`` — so when a scan lands
degraded or failed the evidence is already in memory: the tracer
dumps the full span tree (with the log tail attached under
``otherData.recent_logs``) to ``dump_dir`` and the report's
FailureCauses reference the dump path.

:class:`RingLogHandler` is a stdlib logging handler that copies every
trivy_tpu log record into the recorder's deque, annotated with the
active span's trace/request ids when one is bound — the crash dump
therefore carries the log lines that led up to the failure, not just
the timings.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import tempfile
import threading
import time

# age-based dump pruning (seconds; unset/0 = off): long-lived
# servers with occasional failures keep DUMP_CAP files forever
# otherwise — a fleet of them is DUMP_CAP x N stale evidence
DUMP_MAX_AGE_ENV = "TRIVY_TPU_DUMP_MAX_AGE_S"

# total-bytes cap on the dump dir (unset/0 = off): DUMP_CAP bounds
# the file COUNT, but a soak with repeated designed SLO trips dumps
# deep traces — N files of unbounded size is still an unbounded
# dir. Oldest dumps go first; the newest dump always survives even
# when it alone exceeds the cap (evidence of the trip that just
# happened beats an empty dir)
DUMP_MAX_BYTES_ENV = "TRIVY_TPU_DUMP_MAX_BYTES"


class FlightRecorder:
    """Last-N completed traces + recent log events, thread-safe."""

    # crash-dump files kept on disk at once — a mass-expiry event
    # (every admitted request timing out) is bounded to this many
    # writes' worth of disk, FIFO-pruned
    DUMP_CAP = 64

    def __init__(self, capacity: int = 256, log_capacity: int = 512,
                 dump_dir: str = ""):
        self.capacity = max(1, capacity)
        self._lock = threading.Lock()
        self._ring: collections.OrderedDict = collections.OrderedDict()
        self.logs: collections.deque = collections.deque(
            maxlen=max(1, log_capacity))
        self._dump_dir = dump_dir
        # (path, insert monotonic, bytes) — insert-time monotonic
        # stamps keep age pruning on the monotonic clock (the wall
        # mtime would reintroduce time.time() arithmetic, which the
        # obs clock lint forbids)
        self._dump_paths: collections.deque = collections.deque()
        self.evicted = 0
        self.dumps = 0
        self.dump_bytes = 0
        self.dumps_pruned = 0
        self._clock = time.monotonic   # injectable (age-prune tests)
        # the owning Tracer's monotonic epoch — dump() subtracts it
        # so every dump in the dir shares one timebase (us since
        # tracer start), whoever triggers the dump (a failed scan,
        # an SLO burn-rate trip, an operator)
        self.epoch_mono = 0.0

    # --- dump location ---

    @property
    def dump_dir(self) -> str:
        if self._dump_dir:
            return self._dump_dir
        # uid-scoped, not a fixed world-guessable name: the dumps
        # carry log tails and request names, and a squatter owning a
        # shared path could read (or blackhole) them
        uid = getattr(os, "getuid", lambda: "")()
        return os.path.join(tempfile.gettempdir(),
                            f"trivy-tpu-traces-{uid}")

    @dump_dir.setter
    def dump_dir(self, value: str) -> None:
        self._dump_dir = value

    def dump_path(self, trace_id: str) -> str:
        return os.path.join(self.dump_dir, f"trace-{trace_id}.json")

    # --- the trace ring ---

    def add(self, trace_id: str, spans: list) -> None:
        with self._lock:
            self._ring[trace_id] = list(spans)
            self._ring.move_to_end(trace_id)
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)
                self.evicted += 1

    def append(self, trace_id: str, span) -> None:
        """Late child span for an already-completed trace (a sweep
        resolved the request mid-stage); dropped once evicted."""
        with self._lock:
            spans = self._ring.get(trace_id)
            if spans is not None:
                spans.append(span)

    def get(self, trace_id: str):
        with self._lock:
            spans = self._ring.get(trace_id)
            return list(spans) if spans is not None else None

    def trace_ids(self) -> list:
        with self._lock:
            return list(self._ring)

    def traces(self) -> list:
        """[(trace_id, [spans])] oldest → newest."""
        with self._lock:
            return [(tid, list(spans))
                    for tid, spans in self._ring.items()]

    # --- the log ring ---

    def note_log(self, entry: dict) -> None:
        self.logs.append(entry)       # deque append is atomic

    def recent_logs(self) -> list:
        return list(self.logs)

    # --- crash dumps ---

    @staticmethod
    def write_doc(path: str, doc: dict) -> None:
        """Atomic trace-file write (tmp + rename) — shared by crash
        dumps and the tracer's ``--trace-out`` exporter."""
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def dump(self, trace_id: str, spans=None,
             epoch_mono: float = None) -> str:
        """Write one trace (plus the recent log tail) as Perfetto-
        loadable JSON under ``dump_dir``; returns the path. The dir
        is created private (0700) and must be owned by this uid;
        at most ``DUMP_CAP`` dump files are kept (FIFO pruning).
        ``epoch_mono`` defaults to the owning tracer's epoch so
        every dump shares one timebase."""
        from .trace import to_chrome
        if epoch_mono is None:
            epoch_mono = self.epoch_mono
        if spans is None:
            spans = self.get(trace_id)
        if spans is None:
            raise ValueError(f"unknown trace {trace_id!r}")
        doc = to_chrome(spans, epoch_mono)
        doc.setdefault("otherData", {})["recent_logs"] = \
            self.recent_logs()
        path = self.dump_path(trace_id)
        d = os.path.dirname(path)
        os.makedirs(d, mode=0o700, exist_ok=True)
        if hasattr(os, "getuid") and \
                os.stat(d).st_uid != os.getuid():
            raise OSError(
                f"refusing to dump into {d!r}: owned by another uid")
        self.write_doc(path, doc)
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            nbytes = 0
        try:
            max_age = float(os.environ.get(DUMP_MAX_AGE_ENV,
                                           "0") or 0)
        except ValueError:
            max_age = 0.0
        try:
            max_bytes = int(float(os.environ.get(
                DUMP_MAX_BYTES_ENV, "0") or 0))
        except ValueError:
            max_bytes = 0
        now = self._clock()
        with self._lock:
            self.dumps += 1
            # re-dumping a trace replaces its entry (same file name):
            # the books must not double-count the bytes or prune the
            # live file out from under the newer entry
            for i, (p, _, b) in enumerate(self._dump_paths):
                if p == path:
                    del self._dump_paths[i]
                    self.dump_bytes -= b
                    break
            self._dump_paths.append((path, now, nbytes))
            self.dump_bytes += nbytes
            prune = []
            if max_age > 0:
                while self._dump_paths and \
                        now - self._dump_paths[0][1] > max_age:
                    prune.append(self._dump_paths.popleft())
            while len(self._dump_paths) > self.DUMP_CAP:
                prune.append(self._dump_paths.popleft())
            for _, _, b in prune:
                self.dump_bytes -= b
            if max_bytes > 0:
                # rotate by TOTAL bytes, oldest first — but never
                # the dump just written: the freshest evidence is
                # the one an operator is about to fetch
                while self.dump_bytes > max_bytes and \
                        len(self._dump_paths) > 1:
                    victim = self._dump_paths.popleft()
                    self.dump_bytes -= victim[2]
                    prune.append(victim)
            self.dumps_pruned += len(prune)
        for old, _, _ in prune:
            try:
                os.remove(old)
            except OSError:
                pass
        return path

    def stats(self) -> dict:
        with self._lock:
            return {"traces": len(self._ring),
                    "capacity": self.capacity,
                    "evicted": self.evicted,
                    "dumps": self.dumps,
                    "dump_files": len(self._dump_paths),
                    "dump_bytes": self.dump_bytes,
                    "dumps_pruned": self.dumps_pruned,
                    "logs": len(self.logs)}


class RingLogHandler(logging.Handler):
    """Copies trivy_tpu log records into the flight recorder, tagged
    with the active span's correlation ids."""

    def __init__(self, recorder: FlightRecorder):
        super().__init__(level=logging.DEBUG)
        self.recorder = recorder

    def emit(self, record) -> None:
        try:
            entry = {"t": round(record.created, 6),
                     "level": record.levelname,
                     "logger": record.name,
                     "msg": record.getMessage()}
            from .trace import current_span
            span = current_span()
            if span is not None and not span.noop:
                entry["trace_id"] = span.trace_id
                rid = span.attrs.get("request")
                if rid:
                    entry["request_id"] = rid
            self.recorder.note_log(entry)
        except Exception:           # noqa: BLE001 — logging must
            self.handleError(record)   # never take the pipeline down


_ATTACH_LOCK = threading.Lock()
_ATTACHED = False


def attach_ring_handler(recorder: FlightRecorder) -> None:
    """Attach the log ring to the trivy_tpu root logger (once)."""
    global _ATTACHED
    with _ATTACH_LOCK:
        if _ATTACHED:
            return
        from ..utils.log import attach_handler
        attach_handler(RingLogHandler(recorder))
        _ATTACHED = True
