"""Observability: per-request distributed tracing, the flight
recorder, and Prometheus text exposition (docs/observability.md).

Zero-dependency by design — spans, the ring, and the exposition
renderer are stdlib-only, so the tracing layer can thread through
the RPC client, the scheduler and the artifact seams without adding
imports the hot path pays for.
"""

from .prom import render_prometheus
from .recorder import FlightRecorder, RingLogHandler
from .trace import (NOOP_SPAN, Span, Tracer, add_event, current_span,
                    get_tracer, new_trace_id, phase_span, summarize,
                    to_chrome, trace_cause)

__all__ = [
    "FlightRecorder", "NOOP_SPAN", "RingLogHandler", "Span",
    "Tracer", "add_event", "current_span", "get_tracer",
    "new_trace_id", "phase_span", "render_prometheus", "summarize",
    "to_chrome", "trace_cause",
]
