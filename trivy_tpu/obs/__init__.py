"""Observability: per-request distributed tracing, the flight
recorder, Prometheus text exposition, the busy/idle timeline with
typed idle attribution, the sampling host profiler, and the SLO
burn-rate engine (docs/observability.md).

Zero-dependency by design — spans, the ring, the exposition
renderer, the timeline math, the profiler and the SLO windows are
stdlib-only, so the tracing layer can thread through the RPC
client, the scheduler and the artifact seams without adding imports
the hot path pays for.
"""

from .profiler import HostProfiler, device_trace, get_profiler
from .prom import render_prometheus
from .recorder import FlightRecorder, RingLogHandler
from .slo import SLO, SloEngine, default_slos, parse_slo_config
from .timeline import Timeline, from_recorder, from_tracer
from .trace import (NOOP_SPAN, Span, Tracer, add_event, current_span,
                    get_tracer, new_trace_id, phase_span, summarize,
                    to_chrome, trace_cause)

__all__ = [
    "FlightRecorder", "HostProfiler", "NOOP_SPAN", "RingLogHandler",
    "SLO", "SloEngine", "Span", "Timeline", "Tracer", "add_event",
    "current_span", "default_slos", "device_trace", "from_recorder",
    "from_tracer", "get_profiler", "get_tracer", "new_trace_id",
    "parse_slo_config", "phase_span", "render_prometheus",
    "summarize", "to_chrome", "trace_cause",
]
