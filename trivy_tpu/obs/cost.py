"""Per-tenant cost attribution and fleet goodput metering
(docs/observability.md "Cost attribution & goodput").

Every request that reaches the device carries a tenant (PR-13 trace
context); the scheduler books its RESOURCE VECTOR — device-seconds
split by kernel family (DFA secret sieve vs. the interval
bucket-ladder), host-seconds by phase (analyze, finish), candidate
bytes ingested, memo hits/misses — against that tenant in the
process-wide :data:`COST_LEDGER` at the DispatchRing/executor seam
where the wall actually passes. Shared batch wall is attributed
across the batch's requests proportionally to each request's work
volume (candidate bytes + interval jobs), so the books BALANCE by
construction: the per-tenant attributed device-seconds sum to the
scheduler's measured per-dispatch device-time integral (an identity
the ``pytest -m cost`` suite and the ``bench.py cost`` arm assert
within ±2%).

The ledger keeps two books under one lock:

* **cumulative** — per-tenant totals since process start (the
  invoice);
* **windowed** — the same vectors in 10 s age-keyed buckets
  (mirroring :meth:`obs.slo.SloEngine.export_state`): budgets read
  recent spend from them, and federation merges them across
  replicas without a shared wall-clock epoch.

Tenant names are label values, so they follow the PR-7/8
cardinality rule: at most ``max_tenants`` distinct rows, overflow
folds into ``other`` (top-K + other — the label-cardinality lint
fails any tenant-keyed book without that fold).

``GET /costs`` serves one replica's export; the router federates it
with the PR-13 Federator pattern — partial answers with a
``complete`` flag, never an error (:func:`federated_costs`).

Budgets (``--tenant-budget``) close the loop at admission: a tenant
whose windowed device-second spend exceeds its budget is throttled
(the existing 429 + Retry-After machinery) or deprioritized (its
requests drop to the budget's priority floor inside its own WFQ
lane) — grammar mirrors ``--tenant-config``
(:func:`parse_budget_config`).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

from ..utils import get_logger

log = get_logger("obs.cost")

# windowed-book resolution and retention: 10 s buckets, 1 h deep —
# enough for any budget window a --tenant-budget can declare
_BUCKET_S = 10.0
_RING_CAP = 360

# the PR-7/8 cardinality rule: at most this many distinct tenant
# rows per book; overflow folds into "other"
MAX_COST_TENANTS = 64

# the resource vector every charge books (fixed key domain — the
# prom exposition renders one bounded family per key)
VECTOR_KEYS = (
    "device_interval_s",    # interval bucket-ladder kernel wall
    "device_dfa_s",         # DFA secret-sieve kernel wall
    "host_analyze_s",       # analyze phase (apply_layers + join)
    "host_finish_s",        # finish phase (decode + assemble)
    "bytes_in",             # candidate bytes ingested
    "memo_hits",            # verdicts served without device work
    "memo_misses",          # verdicts that paid for a dispatch
    "requests",             # completed requests
)

_BUDGET_ACTIONS = ("throttle", "deprioritize")


def _zero_vec() -> dict:
    return dict.fromkeys(VECTOR_KEYS, 0.0)


def device_seconds(vec: dict) -> float:
    """Total attributed device wall in one resource vector."""
    return float(vec.get("device_interval_s", 0.0)) \
        + float(vec.get("device_dfa_s", 0.0))


@dataclass(frozen=True)
class TenantBudget:
    """One tenant's device-second allowance over a sliding window
    (``--tenant-budget``). ``action`` picks the over-budget lever:
    ``throttle`` answers 429 + Retry-After on the existing quota
    machinery; ``deprioritize`` admits the request but clamps its
    priority to ``floor`` so it yields inside its own tenant lane."""

    tenant: str
    device_s: float              # windowed device-second allowance
    window_s: float = 60.0       # sliding window the spend is read over
    action: str = "throttle"     # throttle | deprioritize
    floor: int = -100            # priority floor for deprioritize

    def __post_init__(self):
        if self.device_s <= 0:
            raise ValueError(
                f"budget for {self.tenant!r}: device_s must be > 0")
        if self.window_s <= 0:
            raise ValueError(
                f"budget for {self.tenant!r}: window_s must be > 0")
        if self.action not in _BUDGET_ACTIONS:
            raise ValueError(
                f"budget for {self.tenant!r}: unknown action "
                f"{self.action!r} (choose from {_BUDGET_ACTIONS})")


_BUDGET_FIELDS = ("device_s", "window_s", "action", "floor")


def _coerce_budget_kv(key: str, raw: str):
    raw = str(raw).strip()
    if key == "action":
        return raw
    try:
        return int(raw) if key == "floor" else float(raw)
    except ValueError:
        raise ValueError(
            f"budget key {key!r}: bad value {raw!r}")


def parse_budget_config(text) -> dict:
    """``--tenant-budget`` parser → ``{tenant: TenantBudget}``.
    Accepts either a JSON file path (``{"alice": {"device_s": 2.5,
    "window_s": 60, "action": "throttle"}}``) or an inline spec
    mirroring ``--tenant-config``::

        alice:device_s=2.5,window_s=60,action=throttle;bob:device_s=1

    Unknown keys and malformed values raise ValueError so a typo'd
    budget fails the run up front instead of silently metering
    nothing."""
    if isinstance(text, dict) and all(
            isinstance(v, TenantBudget) for v in text.values()):
        return dict(text)
    text = (text or "").strip() if isinstance(text, str) else ""
    if not text:
        return {}
    if os.path.isfile(text):
        with open(text, "r", encoding="utf-8") as f:
            try:
                doc = json.load(f)
            except ValueError as e:
                raise ValueError(
                    f"tenant budget {text!r}: invalid JSON ({e})")
        if not isinstance(doc, dict):
            raise ValueError(
                f"tenant budget {text!r}: want an object mapping "
                f"tenant -> settings")
        out: dict = {}
        for name, kv in doc.items():
            if not isinstance(kv, dict):
                raise ValueError(
                    f"budget {name!r}: want an object of settings")
            bad = set(kv) - set(_BUDGET_FIELDS)
            if bad:
                raise ValueError(
                    f"budget {name!r}: unknown keys {sorted(bad)} "
                    f"(choose from {sorted(_BUDGET_FIELDS)})")
            out[name] = TenantBudget(tenant=name, **{
                k: _coerce_budget_kv(k, str(v))
                for k, v in kv.items()})
        return out
    out = {}
    for chunk in text.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, sep, rest = chunk.partition(":")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"bad tenant-budget entry {chunk!r} "
                f"(want name:device_s=...,window_s=...)")
        kv: dict = {}
        for pair in rest.split(","):
            pair = pair.strip()
            if not pair:
                continue
            key, eq, raw = pair.partition("=")
            key = key.strip()
            if not eq or key not in _BUDGET_FIELDS:
                raise ValueError(
                    f"bad tenant-budget entry {pair!r} for "
                    f"{name!r} (choose from "
                    f"{sorted(_BUDGET_FIELDS)})")
            kv[key] = _coerce_budget_kv(key, raw)
        if "device_s" not in kv:
            raise ValueError(
                f"tenant-budget entry {name!r}: device_s is "
                f"required")
        out[name] = TenantBudget(tenant=name, **kv)
    return out


class CostLedger:
    """Per-tenant resource-vector books; every method thread-safe.

    ``enabled=False`` turns every ``charge`` into an immediate
    return — the ``bench.py cost`` arm measures metering overhead
    as the ips delta between the two settings."""

    def __init__(self, max_tenants: int = MAX_COST_TENANTS,
                 clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.max_tenants = max(1, int(max_tenants))
        self.enabled = True
        self._cum: dict = {}        # tenant -> vector
        self._ring: dict = {}       # bucket -> {tenant: vector}
        self.charges = 0            # charge() calls booked

    def reset(self) -> None:
        """Fresh books (tests and the bench's per-arm isolation)."""
        with self._lock:
            self._cum.clear()
            self._ring.clear()
            self.charges = 0

    def _slot(self, table: dict, tenant: str) -> dict:
        # top-K + other fold (PR-7/8): past the cap every new
        # tenant shares one row; len() gate + "other" constant are
        # what the label-cardinality lint checks for
        if tenant not in table and len(table) >= self.max_tenants:
            tenant = "other"
        row = table.get(tenant)
        if row is None:
            row = table[tenant] = _zero_vec()
        return row

    def charge(self, tenant: str, **amounts) -> None:
        """Book one resource-vector increment against ``tenant``.
        Unknown vector keys raise (a typo'd charge site must fail
        tests, not silently leak spend)."""
        if not self.enabled:
            return
        bad = set(amounts) - set(VECTOR_KEYS)
        if bad:
            raise ValueError(
                f"unknown cost vector keys {sorted(bad)} "
                f"(choose from {VECTOR_KEYS})")
        tenant = str(tenant or "anon")[:64]
        bucket = int(self._clock() / _BUCKET_S)
        with self._lock:
            self.charges += 1
            win = self._ring.get(bucket)
            if win is None:
                win = self._ring[bucket] = {}
                # bound the windowed book: drop buckets past the
                # retention horizon (dict insertion order is bucket
                # order on a monotonic clock)
                while len(self._ring) > _RING_CAP:
                    oldest = next(iter(self._ring))
                    if oldest == bucket:
                        break
                    del self._ring[oldest]
            for row in (self._slot(self._cum, tenant),
                        self._slot(win, tenant)):
                for k, v in amounts.items():
                    row[k] += float(v)

    # --- reads ---

    def window_device_s(self, tenant: str,
                        window_s: float) -> float:
        """Device-seconds ``tenant`` spent over the trailing
        ``window_s`` (budget admission reads this)."""
        now_bucket = int(self._clock() / _BUCKET_S)
        span = max(1, int(window_s / _BUCKET_S))
        total = 0.0
        with self._lock:
            for b in range(now_bucket - span + 1, now_bucket + 1):
                row = self._ring.get(b, {}).get(tenant)
                if row is not None:
                    total += device_seconds(row)
        return total

    def totals(self) -> dict:
        """Cumulative fleet-wide vector (all tenants summed)."""
        out = _zero_vec()
        with self._lock:
            for vec in self._cum.values():
                for k in VECTOR_KEYS:
                    out[k] += vec[k]
        return out

    def snapshot(self, aot_compile_s: float = 0.0) -> dict:
        """The ``/costs`` (and ``/metrics`` section) payload:
        per-tenant cumulative vectors plus the amortized AOT-compile
        bill — ``aot_compile_s`` (the process's total compile wall,
        COMPILE_CACHE_METRICS) split across tenants by device-second
        share, so warming costs land on whoever used the warmth."""
        with self._lock:
            tenants = {t: dict(vec)
                       for t, vec in sorted(self._cum.items())}
            charges = self.charges
        total_dev = sum(device_seconds(v) for v in tenants.values())
        totals = _zero_vec()
        for t, vec in tenants.items():
            share = device_seconds(vec) / total_dev \
                if total_dev > 0 else 0.0
            vec["aot_amortized_s"] = round(
                float(aot_compile_s) * share, 6)
            for k in VECTOR_KEYS:
                totals[k] += vec[k]
                vec[k] = round(vec[k], 6)
        for k in VECTOR_KEYS:
            totals[k] = round(totals[k], 6)
        totals["aot_amortized_s"] = round(float(aot_compile_s)
                                          if tenants else 0.0, 6)
        return {"tenants": tenants, "totals": totals,
                "charges": charges,
                "device_s": round(total_dev, 6),
                "enabled": self.enabled}

    def export_state(self) -> dict:
        """Federation export: cumulative vectors plus AGE-keyed
        windowed buckets (age 0 = the current 10 s bucket) — the
        same monotonic-only coordinate as
        :meth:`obs.slo.SloEngine.export_state`, so a federating
        front can merge replicas without any shared epoch."""
        now_bucket = int(self._clock() / _BUCKET_S)
        with self._lock:
            cum = {t: dict(vec) for t, vec in self._cum.items()}
            buckets = {}
            for b, table in self._ring.items():
                age = now_bucket - b
                if 0 <= age < _RING_CAP:
                    buckets[str(age)] = {
                        t: dict(vec) for t, vec in table.items()}
        return {"schema": 1, "bucket_s": _BUCKET_S,
                "cum": cum, "buckets": buckets}


def merge_cost_exports(exports) -> dict:
    """Sum N replicas' :meth:`CostLedger.export_state` payloads by
    (tenant) and (age, tenant) — same-age buckets across replicas
    cover the same trailing wall interval, so addition is the whole
    merge. Tenant rows past the cap fold into ``other`` (the PR-7/8
    rule holds fleet-wide, not just per replica). Malformed entries
    are dropped, never fatal."""
    cum: dict = {}
    buckets: dict = {}

    def fold(table: dict, tenant: str) -> dict:
        # top-K + other: the fleet-wide merge honors the same
        # cardinality cap as each replica's own books
        if tenant not in table and \
                len(table) >= MAX_COST_TENANTS:
            tenant = "other"
        return table.setdefault(tenant, _zero_vec())

    def add(table: dict, tenant, vec) -> None:
        if not isinstance(tenant, str) or not isinstance(vec, dict):
            return
        row = fold(table, tenant[:64])
        for k in VECTOR_KEYS:
            try:
                row[k] += float(vec.get(k, 0.0))
            except (TypeError, ValueError):
                continue
        return

    for exp in exports:
        if not isinstance(exp, dict):
            continue
        for tenant, vec in (exp.get("cum") or {}).items():
            add(cum, tenant, vec)
        for age, table in (exp.get("buckets") or {}).items():
            if not isinstance(table, dict):
                continue
            try:
                age_key = str(int(age))
            except (TypeError, ValueError):
                continue
            dst = buckets.setdefault(age_key, {})
            for tenant, vec in table.items():
                add(dst, tenant, vec)
    return {"schema": 1, "bucket_s": _BUCKET_S,
            "cum": cum, "buckets": buckets}


def balance(attributed_s: float, measured_s: float,
            tolerance: float = 0.02) -> dict:
    """The accounting identity as a verdict: attributed per-tenant
    device-seconds must reconcile with the measured per-dispatch
    device-time integral within ``tolerance``. Tiny books (< 1 ms
    both sides) are vacuously balanced — there is nothing to
    misattribute."""
    attributed_s = float(attributed_s)
    measured_s = float(measured_s)
    if measured_s < 1e-3 and attributed_s < 1e-3:
        return {"balanced": True, "attributed_s": attributed_s,
                "measured_s": measured_s, "skew": 0.0,
                "tolerance": tolerance}
    base = max(measured_s, 1e-9)
    skew = abs(attributed_s - measured_s) / base
    return {"balanced": skew <= tolerance,
            "attributed_s": round(attributed_s, 6),
            "measured_s": round(measured_s, 6),
            "skew": round(skew, 6), "tolerance": tolerance}


def fetch_costs(url: str, token: str = "",
                token_header: str = "Trivy-Token",
                timeout_s: float = 2.0) -> dict:
    """One replica's ``GET /costs`` — raises on transport/decode
    failure (the fan-out absorbs it into a down row)."""
    import urllib.request
    req = urllib.request.Request(url.rstrip("/") + "/costs")
    if token:
        req.add_header(token_header, token)
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        doc = json.loads(resp.read().decode("utf-8"))
    if not isinstance(doc, dict):
        raise ValueError("costs answer is not a JSON object")
    return doc


def federated_costs(replicas, token: str = "",
                    token_header: str = "Trivy-Token",
                    timeout_s: float = 2.0, fan_in: int = 8,
                    fetch=None) -> dict:
    """Fleet cost rollup over ``[(name, url), ...]`` — PR-13
    Federator semantics: bounded fan-in, per-peer timeout, partial
    answers with a ``complete`` flag, never an error. ``fetch(url)
    -> dict`` is injectable so unit tests exercise the merge
    without sockets."""
    fetch = fetch or (lambda u: fetch_costs(
        u, token=token, token_header=token_header,
        timeout_s=timeout_s))
    replicas = list(replicas)
    rows: list = [None] * len(replicas)
    sem = threading.Semaphore(max(1, int(fan_in)))

    def work(i: int, name: str, url: str) -> None:
        with sem:
            try:
                doc = fetch(url)
            except Exception as e:  # noqa: BLE001 — a down peer is
                # the condition federation exists to absorb: mark
                # it, answer partially
                rows[i] = {"replica": name, "up": False,
                           "complete": False, "error": repr(e)}
                return
            rows[i] = {"replica": name, "up": True,
                       "complete": bool(doc.get("complete", True)),
                       "error": "", "answer": doc}

    threads = [threading.Thread(target=work, args=(i, n, u),
                                daemon=True)
               for i, (n, u) in enumerate(replicas)]
    for t in threads:
        t.start()
    for t in threads:
        # second-layer backstop over the per-fetch timeout, so a
        # wedged socket cannot wedge the rollup
        t.join(timeout_s * 2 + 1.0)
    for i, (name, _url) in enumerate(replicas):
        if rows[i] is None:
            rows[i] = {"replica": name, "up": False,
                       "complete": False, "error": "query timeout"}

    exports = []
    measured_s = 0.0
    for row in rows:
        answer = row.get("answer")
        if not answer:
            continue
        if isinstance(answer.get("export"), dict):
            exports.append(answer["export"])
        try:
            measured_s += float(answer.get("measured_device_s", 0.0))
        except (TypeError, ValueError):
            pass
    merged = merge_cost_exports(exports)
    tenants = {}
    for t, vec in sorted(merged["cum"].items()):
        tenants[t] = {k: round(v, 6) for k, v in vec.items()}
        tenants[t]["device_s"] = round(device_seconds(vec), 6)
    attributed_s = sum(device_seconds(v)
                       for v in merged["cum"].values())
    complete = all(r["up"] and r["complete"] for r in rows) \
        if rows else True
    return {
        "tenants": tenants,
        "attributed_device_s": round(attributed_s, 6),
        "measured_device_s": round(measured_s, 6),
        "balance": balance(attributed_s, measured_s),
        "complete": complete,
        "replicas": [{k: r[k] for k in
                      ("replica", "up", "complete", "error")}
                     for r in rows],
    }


# the process-wide books every scheduler/scanner charges into
# (mirroring RING_METRICS, MEMO_METRICS et al.)
COST_LEDGER = CostLedger()
