"""Prometheus text exposition for ``GET /metrics``
(docs/observability.md).

The JSON snapshot stays the default; a scrape that sends
``Accept: text/plain`` gets the 0.0.4 text rendering instead, and
one that negotiates ``application/openmetrics-text; version=1.0.0``
gets the OpenMetrics variant — same sample lines, plus per-bucket
**trace-id exemplars** on the latency histograms and the mandatory
``# EOF`` terminator. Exemplars ride ONLY the openmetrics content
type: the plain 0.0.4 output stays byte-stable (Prometheus < 2.26
and every text-format consumer in the wild chokes on the ``#``
exemplar suffix). The input is the same nested dict
``ScanServer.metrics()`` serves as JSON — rendering is tolerant of
missing sections (a scheduler-off server still exposes
guard/admission/idempotency metrics).

Histograms use the raw bucket counts (``LatencyHistogram.raw``:
``{"bounds", "counts", "sum", "count", "exemplars"}``), exposed
cumulatively with the mandatory ``+Inf`` bucket, ``_sum`` and
``_count`` series; ``exemplars`` maps bucket index to the most
recent ``(trace_id, value, unix seconds)`` observed into it, so a
slow-bucket scrape links straight to a representative trace at
``/trace/<id>``.
"""

from __future__ import annotations

_PREFIX = "trivy_tpu"

_BREAKER_STATES = ("closed", "open", "half-open")
OPENMETRICS_CTYPE = ("application/openmetrics-text; "
                     "version=1.0.0; charset=utf-8")


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        if v == float("-inf"):
            return "-Inf"
        return repr(v)
    return str(v)


def _esc(v) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


class _Writer:
    def __init__(self):
        self.lines: list = []

    def header(self, name: str, mtype: str, help_: str) -> None:
        self.lines.append(f"# HELP {name} {help_}")
        self.lines.append(f"# TYPE {name} {mtype}")

    def sample(self, name: str, labels, value,
               suffix: str = "") -> None:
        if value is None:
            return
        if labels:
            lab = ",".join(f'{k}="{_esc(v)}"' for k, v in labels)
            self.lines.append(
                f"{name}{{{lab}}} {_fmt(value)}{suffix}")
        else:
            self.lines.append(f"{name} {_fmt(value)}{suffix}")

    def scalar(self, name: str, mtype: str, help_: str,
               value) -> None:
        if value is None:
            return
        self.header(name, mtype, help_)
        self.sample(name, None, value)


def _exemplar_suffix(h: dict, idx: int) -> str:
    """OpenMetrics exemplar for one bucket: `` # {trace_id="…"}
    value timestamp`` — empty when the bucket never saw a traced
    observation."""
    ex = (h.get("exemplars") or {}).get(idx)
    if not ex:
        return ""
    trace_id, value, ts = ex
    return (f' # {{trace_id="{_esc(trace_id)}"}} '
            f"{_fmt(float(value))} {_fmt(round(float(ts), 3))}")


def _histograms(w: _Writer, name: str, label: str, hists: dict,
                help_: str, openmetrics: bool = False) -> None:
    if not hists:
        return
    full = f"{_PREFIX}_{name}_seconds"
    w.header(full, "histogram", help_)
    for key in sorted(hists):
        h = hists[key]
        bounds, counts = h["bounds"], h["counts"]
        cum = 0
        for i, (b, c) in enumerate(zip(bounds, counts)):
            cum += c
            w.sample(full + "_bucket",
                     [(label, key), ("le", _fmt(float(b)))], cum,
                     suffix=_exemplar_suffix(h, i)
                     if openmetrics else "")
        cum += counts[len(bounds)] if len(counts) > len(bounds) else 0
        w.sample(full + "_bucket", [(label, key), ("le", "+Inf")],
                 cum,
                 suffix=_exemplar_suffix(h, len(bounds))
                 if openmetrics else "")
        w.sample(full + "_sum", [(label, key)], float(h["sum"]))
        w.sample(full + "_count", [(label, key)], h["count"])


def _process_gauges(w: _Writer, proc: dict) -> None:
    """Process self-stat gauges (obs/procstats.py) — shared by the
    replica and router renderers so the soak leak audit reads the
    same family names off every process in the fleet. ``-1`` samples
    (gauge unavailable on this platform) are skipped, not rendered:
    absence is the documented "no data" signal."""
    if not proc:
        return
    for key, name, help_ in (
            ("rss_bytes", "rss_bytes",
             "Resident set size of this process (VmRSS)."),
            ("peak_rss_bytes", "peak_rss_bytes",
             "High-water RSS across every self-stat sample this "
             "process has taken (the soak leak gate's series)."),
            ("open_fds", "open_fds",
             "Open file descriptors of this process."),
            ("threads", "threads",
             "Live interpreter threads in this process.")):
        v = proc.get(key)
        if v is None or (isinstance(v, int) and v < 0):
            continue
        w.scalar(f"{_PREFIX}_process_{name}", "gauge", help_, v)


def render_prometheus(stats: dict, phase_hists=None,
                      trace_hists=None, tenant_hists=None,
                      tracer_stats=None,
                      recorder_stats=None,
                      watch_hists=None,
                      openmetrics: bool = False) -> str:
    """Render the ``/metrics`` snapshot dict as Prometheus text.

    ``openmetrics=True`` adds histogram-bucket exemplars and the
    ``# EOF`` terminator (served under the openmetrics content
    type); False keeps the 0.0.4 output byte-stable."""
    w = _Writer()

    binfo = stats.get("build_info") or {}
    if binfo:
        # info-style identity gauge (value always 1; the labels are
        # the payload) — lets a fleet scrape tell replica versions
        # apart during a rolling deploy
        name = f"{_PREFIX}_build_info"
        w.header(name, "gauge",
                 "Build/version identity; value is always 1, the "
                 "labels carry the information.")
        w.sample(name, [("version", binfo.get("version", "")),
                        ("jax_version",
                         binfo.get("jax_version", "")),
                        ("backend", binfo.get("backend", "")),
                        ("sched", binfo.get("sched", ""))], 1)

    counters = stats.get("counters") or {}
    if counters:
        name = f"{_PREFIX}_sched_events_total"
        w.header(name, "counter",
                 "Scheduler request lifecycle events by kind.")
        for k in sorted(counters):
            w.sample(name, [("event", k)], counters[k])

    w.scalar(f"{_PREFIX}_sched_queue_depth", "gauge",
             "Admission queue depth.", stats.get("queue_depth"))
    w.scalar(f"{_PREFIX}_sched_queue_depth_max", "gauge",
             "High-water admission queue depth.",
             stats.get("queue_depth_max"))
    if "draining" in stats:
        w.scalar(f"{_PREFIX}_draining", "gauge",
                 "1 while the server refuses new work.",
                 1 if stats.get("draining") else 0)

    batch = stats.get("batch") or {}
    if batch:
        w.scalar(f"{_PREFIX}_sched_batches_total", "counter",
                 "Coalesced device batches dispatched.",
                 batch.get("count"))
        w.scalar(f"{_PREFIX}_sched_batch_items_total", "counter",
                 "Requests carried by dispatched batches.",
                 batch.get("items_total"))
        w.scalar(f"{_PREFIX}_sched_batch_candidate_bytes_total",
                 "counter", "Candidate bytes across batches.",
                 batch.get("candidate_bytes"))
        w.scalar(f"{_PREFIX}_sched_batch_occupancy", "gauge",
                 "Mean bucket occupancy (1 - padding waste).",
                 batch.get("occupancy"))
        w.scalar(f"{_PREFIX}_sched_batch_padding_waste", "gauge",
                 "Mean padding waste across batches.",
                 batch.get("padding_waste"))

    for key, help_ in (("host_busy_s",
                        "Cumulative host worker busy seconds."),
                       ("device_busy_s",
                        "Cumulative device busy seconds."),
                       ("overlap_s",
                        "Seconds host and device were busy "
                        "simultaneously.")):
        w.scalar(f"{_PREFIX}_sched_{key[:-2]}_seconds_total",
                 "counter", help_, stats.get(key))
    w.scalar(f"{_PREFIX}_sched_overlap_ratio", "gauge",
             "overlap_s / device_busy_s.",
             stats.get("overlap_ratio"))
    w.scalar(f"{_PREFIX}_uptime_seconds", "gauge",
             "Scheduler uptime.", stats.get("uptime_s"))

    dispatch = stats.get("dispatch") or {}
    if dispatch:
        # async slot runtime (docs/performance.md §8): the overlap
        # the double-buffered ring buys, observable in prod
        ring_counters = dispatch.get("counters") or {}
        name = f"{_PREFIX}_dispatch_slots_total"
        w.header(name, "counter",
                 "Dispatch-ring slot lifecycle events by kind.")
        for k in sorted(ring_counters):
            w.sample(name, [("event", k)], ring_counters[k])
        w.scalar(f"{_PREFIX}_dispatch_depth", "gauge",
                 "Device slots currently in flight "
                 "(launched, not yet collected).",
                 dispatch.get("depth"))
        w.scalar(f"{_PREFIX}_dispatch_depth_max", "gauge",
                 "High-water in-flight slot count.",
                 dispatch.get("depth_max"))
        w.scalar(f"{_PREFIX}_slot_occupancy", "gauge",
                 "Time-weighted mean in-flight slots over the "
                 "configured ring depth.",
                 dispatch.get("slot_occupancy"))
        w.scalar(f"{_PREFIX}_dispatch_overlap_ratio", "gauge",
                 "Share of slot-active wall with >= 2 slots in "
                 "flight (0 = serial ladder).",
                 dispatch.get("dispatch_overlap_ratio"))
        w.scalar(f"{_PREFIX}_dispatch_slot_wait_seconds_total",
                 "counter",
                 "Wall spent parked on a full dispatch ring.",
                 dispatch.get("slot_wait_s"))

    guard = stats.get("guard") or {}
    if guard:
        name = f"{_PREFIX}_guard_events_total"
        w.header(name, "counter",
                 "Ingest-guard counters (budget trips, malformed "
                 "archives, walked entries, ...).")
        for k in sorted(guard):
            w.sample(name, [("event", k)], guard[k])

    detect = stats.get("detect") or {}
    if detect:
        name = f"{_PREFIX}_detect_events_total"
        w.header(name, "counter",
                 "Dispatch-path counters (job dedup, cache "
                 "hits/misses, resident-DB uploads).")
        for k in sorted(detect):
            if k.endswith(("_rate", "_ratio", "amortization")) \
                    or k == "db_upload_bytes":
                continue     # derived gauges / byte totals below —
                # a byte count inside an event-count family would
                # poison any sum() over it
            w.sample(name, [("event", k)], detect[k])
        w.scalar(f"{_PREFIX}_detect_db_upload_bytes_total",
                 "counter",
                 "Bytes of advisory tables staged to HBM.",
                 detect.get("db_upload_bytes"))
        w.scalar(f"{_PREFIX}_detect_dedup_ratio", "gauge",
                 "Share of interval jobs folded away by dedup.",
                 detect.get("dedup_ratio"))
        w.scalar(f"{_PREFIX}_detect_interval_cache_hit_rate",
                 "gauge",
                 "Constraint-interval compile cache hit rate.",
                 detect.get("interval_cache_hit_rate"))
        w.scalar(f"{_PREFIX}_detect_purl_cache_hit_rate", "gauge",
                 "Purl parse cache hit rate.",
                 detect.get("purl_cache_hit_rate"))
        w.scalar(f"{_PREFIX}_detect_db_upload_amortization",
                 "gauge",
                 "Resident-table dispatches served per HBM upload.",
                 detect.get("upload_amortization"))

    secret = stats.get("secret") or {}
    if secret:
        name = f"{_PREFIX}_secret_events_total"
        w.header(name, "counter",
                 "Secret-sieve counters (files gated on-device vs "
                 "host verify, chain-gated rules, DFA uploads, "
                 "shard/decode tasks).")
        for k in sorted(secret):
            if k.endswith(("_s", "_selectivity", "amortization")) \
                    or k == "dfa_upload_bytes":
                continue     # derived gauges / seconds / bytes below
            w.sample(name, [("event", k)], secret[k])
        w.scalar(f"{_PREFIX}_secret_sieve_selectivity", "gauge",
                 "Share of scanned files that needed ANY host "
                 "verification (files_gated / files_total).",
                 secret.get("sieve_selectivity"))
        w.scalar(f"{_PREFIX}_secret_sieve_seconds_total", "counter",
                 "Cumulative wall seconds in the sieve "
                 "(pack + dispatch + decode).",
                 secret.get("sieve_s"))
        w.scalar(f"{_PREFIX}_secret_verify_tail_seconds_total",
                 "counter",
                 "Cumulative wall seconds in the CPU-exact verify "
                 "tail.", secret.get("verify_s"))
        w.scalar(f"{_PREFIX}_secret_dfa_upload_bytes_total",
                 "counter",
                 "Bytes of DFA band tables staged to HBM.",
                 secret.get("dfa_upload_bytes"))
        w.scalar(f"{_PREFIX}_secret_dfa_upload_amortization",
                 "gauge",
                 "DFA-table dispatches served per HBM upload.",
                 secret.get("dfa_upload_amortization"))

    ingest = stats.get("ingest") or {}
    if ingest:
        # streaming-ingest counters (docs/performance.md §9):
        # per-key scalars so the warm-skip and resume behavior are
        # first-class metric names, not labels
        for k, help_ in (
                ("streams", "Images opened as streaming sources."),
                ("layers_fetched",
                 "Layer blobs fetched over the streaming path."),
                ("bytes_fetched",
                 "Compressed layer bytes pulled from registries."),
                ("layers_skipped",
                 "Warm layers skipped before their blob GET."),
                ("bytes_skipped",
                 "Compressed layer bytes NOT pulled thanks to the "
                 "warm-layer skip."),
                ("range_resumes",
                 "Mid-body drops resumed with an HTTP Range GET."),
                ("full_restarts",
                 "Blob fetches rewritten from offset 0 after a "
                 "rejected Range resume."),
                ("warm_probe_outages",
                 "Warm-layer cache probes that failed and degraded "
                 "to a full pull."),
                ("cancelled_fetches",
                 "Layer fetches cancelled mid-stream by a guard "
                 "budget trip."),
                ("config_memo_hits",
                 "Image config blobs served from the digest memo "
                 "without a GET.")):
            w.scalar(f"{_PREFIX}_ingest_{k}_total", "counter",
                     help_, ingest.get(k))

    memo = stats.get("memo") or {}
    if memo:
        # findings-memo counters (docs/performance.md "Findings
        # memoization & incremental re-scan")
        for k, help_ in (
                ("hits", "Memo queries served without dispatch."),
                ("misses", "Memo queries that dispatched."),
                ("stores", "Memo entries written."),
                ("invalidations",
                 "Memo sub-entries invalidated (delta-touched at "
                 "hot swap, corrupt entries dropped)."),
                ("bytes", "Memo entry bytes written.")):
            w.scalar(f"{_PREFIX}_memo_{k}_total", "counter",
                     help_, memo.get(k))
        w.scalar(f"{_PREFIX}_memo_hit_rate", "gauge",
                 "Memo query hit rate (hits / lookups).",
                 memo.get("hit_rate"))
        name = f"{_PREFIX}_memo_events_total"
        w.header(name, "counter",
                 "Findings-memo bookkeeping (layer hits, corrupt "
                 "drops, degraded backend ops, delta re-match).")
        for k in ("layer_hits", "corrupt", "lookup_errors",
                  "store_errors", "migrated_entries",
                  "rematch_jobs", "rematch_entries", "swaps"):
            if k in memo:
                w.sample(name, [("event", k)], memo[k])
        # advisory-delta observability (docs/serving.md "CVE impact
        # queries & push re-scans"): how much of the memo tier a DB
        # hot swap actually touched
        for k, help_ in (
                ("delta_touched",
                 "Advisory keys touched by hot-swap deltas."),
                ("delta_rematched",
                 "Memo sub-records re-matched against the new "
                 "generation."),
                ("delta_invalidated",
                 "Memo sub-records invalidated outright (recompute "
                 "on next scan).")):
            w.scalar(f"{_PREFIX}_{k}_total", "counter", help_,
                     memo.get(k))

    lifecycle = stats.get("lifecycle") or {}
    if lifecycle:
        # elastic-lifecycle counters (docs/serving.md "Elastic
        # lifecycle"): prewarm walk progress, drain-handoff flow,
        # and the warming admission gate
        for k, help_ in (
                ("prewarm_keys",
                 "Memo keys staged by pre-join prewarm walks."),
                ("prewarm_bytes",
                 "Memo payload bytes staged by prewarm walks."),
                ("prewarm_seconds",
                 "Wall seconds spent in prewarm walks."),
                ("prewarm_deadline_exceeded",
                 "Prewarm walks cut short by the deadline."),
                ("prewarm_runs", "Prewarm walks started."),
                ("prewarm_cold_joins",
                 "Joins that went cold (partial or failed "
                 "prewarm)."),
                ("handoff_published",
                 "Hot digests published by draining replicas."),
                ("handoff_prefetched",
                 "Handoff digests adopted by ring successors."),
                ("handoff_abandoned",
                 "Handoff digests no successor adopted.")):
            w.scalar(f"{_PREFIX}_{k}_total", "counter", help_,
                     lifecycle.get(k))
        w.scalar(f"{_PREFIX}_warming", "gauge",
                 "1 while this replica prewarms before admission.",
                 1 if lifecycle.get("warming") else 0)
        hot = lifecycle.get("hot") or {}
        if hot:
            w.scalar(f"{_PREFIX}_hot_digests", "gauge",
                     "Digests in the bounded hot working-set book.",
                     hot.get("entries"))

    ccache = stats.get("compile_cache") or {}
    if ccache:
        # AOT compile-cache counters (docs/serving.md "Elastic
        # lifecycle"): manifest hit/miss split + on-disk footprint
        for k, help_ in (
                ("hits",
                 "Precompiles whose keyed shape an earlier boot "
                 "already compiled."),
                ("misses",
                 "Precompiles that paid a fresh compile."),
                ("bytes",
                 "On-disk bytes in the persistent compilation "
                 "cache.")):
            w.scalar(f"{_PREFIX}_compile_cache_{k}", "counter"
                     if k != "bytes" else "gauge", help_,
                     ccache.get(k))
        w.scalar(f"{_PREFIX}_compile_cache_seconds_total",
                 "counter", "Wall seconds spent in boot "
                 "precompiles.", ccache.get("seconds"))

    watch = stats.get("watch") or {}
    if watch:
        # watch-loop event dispositions + admission verdicts
        # (docs/serving.md "Continuous scanning & admission
        # control"): every valid event ends in exactly one of
        # scans/deduped/shed — the three totals plus events must
        # balance, which makes them alertable
        for k, help_ in (
                ("events", "Push events admitted by the watch "
                 "loop."),
                ("deduped", "Events folded into a pending or "
                 "in-flight scan of the same digest."),
                ("scans", "Debounced scan submissions."),
                ("shed", "Events shed by admission backpressure "
                 "or unresolvable references."),
                ("malformed", "Malformed registry notifications "
                 "counted and dropped at the parse boundary."),
                ("impact_rescans", "High-priority re-scans pushed "
                 "by the impact index after a DB hot swap.")):
            w.scalar(f"{_PREFIX}_watch_{k}_total", "counter",
                     help_, watch.get(k))
        name = f"{_PREFIX}_watch_events_detail_total"
        w.header(name, "counter",
                 "Watch-loop bookkeeping (scan outcomes, source "
                 "errors, unresolvable references).")
        for k in ("completed", "failed", "source_errors",
                  "unresolvable"):
            if k in watch:
                w.sample(name, [("event", k)], watch[k])
        for k, help_ in (
                ("allow", "Admission reviews answered allowed."),
                ("deny", "Admission reviews answered denied."),
                ("fail_open", "Images admitted fail-open after a "
                 "deadline or scan failure."),
                ("timeout", "Admission scans that missed their "
                 "deadline.")):
            w.scalar(f"{_PREFIX}_admission_{k}_total", "counter",
                     help_, watch.get(f"admission_{k}"))
        name = f"{_PREFIX}_admission_events_total"
        w.header(name, "counter",
                 "Admission bookkeeping (reviews, verdict-cache "
                 "traffic, background warm scans).")
        for k in ("admission_reviews", "admission_cache_hits",
                  "admission_cache_misses",
                  "admission_background_scans"):
            if k in watch:
                w.sample(name,
                         [("event", k[len("admission_"):])],
                         watch[k])
        w.scalar(f"{_PREFIX}_admission_cache_hit_rate", "gauge",
                 "Admission verdict-cache hit rate.",
                 watch.get("admission_cache_hit_rate"))

    impact = stats.get("impact") or {}
    if impact:
        # inverted findings index (docs/serving.md "CVE impact
        # queries & push re-scans"): slice size gauges, query/
        # maintenance totals, bookkeeping events
        for k, help_ in (
                ("entries",
                 "Memo entries currently contributing postings."),
                ("pairs",
                 "Distinct (package, CVE) postings resident."),
                ("cves", "Distinct CVE ids resident."),
                ("images", "Images with a recorded layer set.")):
            w.scalar(f"{_PREFIX}_impact_{k}", "gauge", help_,
                     impact.get(k))
        w.scalar(f"{_PREFIX}_impact_complete", "gauge",
                 "1 while the index covers the full memo tier "
                 "(the last rebuild's key scan finished).",
                 1 if impact.get("complete", True) else 0)
        w.scalar(f"{_PREFIX}_impact_queries_total", "counter",
                 "Local impact-slice queries served.",
                 impact.get("queries"))
        w.scalar(f"{_PREFIX}_impact_maintenance_seconds_total",
                 "counter",
                 "Wall seconds of write-through index maintenance "
                 "(the <2% overhead budget's numerator).",
                 impact.get("maintenance_s"))
        name = f"{_PREFIX}_impact_events_total"
        w.header(name, "counter",
                 "Impact-index bookkeeping (entry updates/drops/"
                 "renames, image-record persistence, rebuilds, "
                 "push stream).")
        for k in ("updates", "drops", "renames", "image_updates",
                  "persist_puts", "persist_skips", "rebuilds",
                  "rebuild_entries", "rebuild_degraded",
                  "push_batches", "push_images"):
            if k in impact:
                w.sample(name, [("event", k)], impact[k])

    tenants = stats.get("tenants") or {}
    if tenants:
        # per-tenant fairness/QoS books (docs/serving.md
        # "Multi-tenant QoS"): the compliant-p99-holds gate and the
        # autoscaler both read these
        name = f"{_PREFIX}_tenant_events_total"
        w.header(name, "counter",
                 "Per-tenant admission outcomes (admitted, ok, "
                 "degraded, failed, timed_out, cancelled, "
                 "rejected_rate, rejected_quota, rejected_503).")
        for t in sorted(tenants):
            for k in sorted(tenants[t].get("counters") or {}):
                w.sample(name, [("tenant", t), ("event", k)],
                         tenants[t]["counters"][k])
        for key, help_ in (
                ("shed", "Load the tenant itself absorbed as "
                 "429s (rate + quota rejections)."),):
            full = f"{_PREFIX}_tenant_{key}_total"
            w.header(full, "counter", help_)
            for t in sorted(tenants):
                w.sample(full, [("tenant", t)],
                         tenants[t].get(key))
        for key, help_ in (
                ("queue_depth", "Per-tenant queued requests."),
                ("inflight",
                 "Per-tenant admitted-but-unresolved requests."),
                ("weight", "Configured WFQ service share.")):
            full = f"{_PREFIX}_tenant_{key}"
            w.header(full, "gauge", help_)
            for t in sorted(tenants):
                if key in tenants[t]:
                    w.sample(full, [("tenant", t)],
                             tenants[t].get(key))

    slo = stats.get("slo") or {}
    if slo.get("slos"):
        # burn-rate verdicts (docs/observability.md "SLOs & burn
        # rates"): the alerting/autoscaling signal GET /slo serves
        name = f"{_PREFIX}_slo_ok"
        w.header(name, "gauge",
                 "1 while the SLO's error budget is not burning "
                 "past any alert window.")
        for v in slo["slos"]:
            w.sample(name, [("slo", v["name"])],
                     1 if v.get("ok") else 0)
        name = f"{_PREFIX}_slo_burn_rate"
        w.header(name, "gauge",
                 "Error-budget burn rate per lookback window "
                 "(1.0 = budget consumed exactly at period end).")
        for v in slo["slos"]:
            for win, rate in (v.get("burn") or {}).items():
                w.sample(name, [("slo", v["name"]),
                                ("window", win)], rate)
        name = f"{_PREFIX}_slo_events_total"
        w.header(name, "counter",
                 "SLO-classified request outcomes.")
        for v in slo["slos"]:
            w.sample(name, [("slo", v["name"]),
                            ("class", "good")], v.get("good"))
            w.sample(name, [("slo", v["name"]),
                            ("class", "bad")], v.get("bad"))
        name = f"{_PREFIX}_slo_trips_total"
        w.header(name, "counter",
                 "Burn-rate alert trips (fast or slow window).")
        for v in slo["slos"]:
            w.sample(name, [("slo", v["name"])], v.get("trips"))
        w.scalar(f"{_PREFIX}_slo_dumps_total", "counter",
                 "Flight-recorder trace dumps triggered by burn-"
                 "rate trips.", slo.get("dumps"))
        eff = [v for v in slo["slos"] if "efficiency" in v]
        if eff:
            name = f"{_PREFIX}_slo_efficiency"
            w.header(name, "gauge",
                     "Useful-device-time share over the recent "
                     "window for kind=efficiency SLOs (MFU-style "
                     "goodput).")
            for v in eff:
                w.sample(name, [("slo", v["name"])],
                         v["efficiency"])

    cost = stats.get("cost") or {}
    if cost.get("tenants") or cost.get("charges"):
        # per-tenant cost attribution (obs/cost.py,
        # docs/observability.md "Cost attribution & goodput") —
        # tenant rows are pre-folded to top-K + "other" by the
        # ledger, so the label space is bounded by construction
        ctenants = cost.get("tenants") or {}
        name = f"{_PREFIX}_cost_device_seconds_total"
        w.header(name, "counter",
                 "Attributed device-seconds by tenant and kernel "
                 "family (interval bucket-ladder vs DFA sieve).")
        for t in sorted(ctenants):
            vec = ctenants[t]
            w.sample(name, [("tenant", t),
                            ("kernel", "interval")],
                     vec.get("device_interval_s"))
            w.sample(name, [("tenant", t), ("kernel", "dfa")],
                     vec.get("device_dfa_s"))
        name = f"{_PREFIX}_cost_host_seconds_total"
        w.header(name, "counter",
                 "Attributed host-seconds by tenant and phase.")
        for t in sorted(ctenants):
            vec = ctenants[t]
            w.sample(name, [("tenant", t),
                            ("phase", "analyze")],
                     vec.get("host_analyze_s"))
            w.sample(name, [("tenant", t), ("phase", "finish")],
                     vec.get("host_finish_s"))
        name = f"{_PREFIX}_cost_bytes_in_total"
        w.header(name, "counter",
                 "Candidate bytes ingested, per tenant.")
        for t in sorted(ctenants):
            w.sample(name, [("tenant", t)],
                     ctenants[t].get("bytes_in"))
        name = f"{_PREFIX}_cost_events_total"
        w.header(name, "counter",
                 "Per-tenant memo hit/miss and completed-request "
                 "counts.")
        for t in sorted(ctenants):
            vec = ctenants[t]
            for ev in ("memo_hits", "memo_misses", "requests"):
                w.sample(name, [("tenant", t), ("event", ev)],
                         vec.get(ev))
        name = f"{_PREFIX}_cost_aot_amortized_seconds"
        w.header(name, "gauge",
                 "AOT compile wall amortized across tenants by "
                 "device-second share.")
        for t in sorted(ctenants):
            w.sample(name, [("tenant", t)],
                     ctenants[t].get("aot_amortized_s"))
        w.scalar(f"{_PREFIX}_cost_attributed_device_seconds",
                 "gauge",
                 "Sum of per-tenant attributed device-seconds.",
                 cost.get("device_s"))
        w.scalar(f"{_PREFIX}_cost_measured_device_seconds",
                 "gauge",
                 "Measured per-dispatch device-time integral the "
                 "attribution must reconcile against.",
                 cost.get("measured_device_s"))
        bal = cost.get("balance") or {}
        if bal:
            w.scalar(f"{_PREFIX}_cost_balanced", "gauge",
                     "1 while attributed and measured device time "
                     "agree within the tolerance (the accounting "
                     "identity).",
                     1 if bal.get("balanced") else 0)
            w.scalar(f"{_PREFIX}_cost_balance_skew", "gauge",
                     "Relative attributed-vs-measured skew.",
                     bal.get("skew"))

    resident = stats.get("resident") or ()
    if resident:
        # device-residency accounting (db/compiled.ResidentTables):
        # live HBM bytes + generation per staged table placement.
        # Rows aggregate per (table, placement): several live
        # instances of one table kind (tests, a swap in flight) must
        # not emit duplicate label sets — bytes sum, generation
        # reports the newest
        agg: dict = {}
        for r in resident:
            key = (r["table"], r["placement"])
            cur = agg.setdefault(key, [0, 0])
            cur[0] += r["bytes"]
            cur[1] = max(cur[1], r["generation"])
        name = f"{_PREFIX}_resident_bytes"
        w.header(name, "gauge",
                 "Bytes of device-resident tables currently staged, "
                 "per table and placement.")
        for (table, placement), (nbytes, _) in sorted(agg.items()):
            w.sample(name, [("table", table),
                            ("placement", placement)], nbytes)
        name = f"{_PREFIX}_resident_generation"
        w.header(name, "gauge",
                 "Newest staged generation (hot swaps bump it; a "
                 "stale generation on one placement means a swap "
                 "has not reached that device set).")
        for (table, placement), (_, gen) in sorted(agg.items()):
            w.sample(name, [("table", table),
                            ("placement", placement)], gen)

    idem = stats.get("idempotency") or {}
    if idem:
        w.scalar(f"{_PREFIX}_idempotency_entries", "gauge",
                 "Live idempotency-window entries.",
                 idem.get("entries"))
        w.scalar(f"{_PREFIX}_idempotency_hits_total", "counter",
                 "Duplicate Scan RPCs served from the window.",
                 idem.get("hits"))
        w.scalar(f"{_PREFIX}_idempotency_evictions_total",
                 "counter",
                 "Entries dropped by the per-tenant caps.",
                 idem.get("evictions"))

    adm = stats.get("admission") or {}
    if adm:
        w.scalar(f"{_PREFIX}_admission_max_body_bytes", "gauge",
                 "413 admission cap on request body size.",
                 adm.get("max_body_bytes"))
        w.scalar(f"{_PREFIX}_admission_max_scan_blobs", "gauge",
                 "413 admission cap on blobs per Scan.",
                 adm.get("max_scan_blobs"))

    breaker = (stats.get("cache_breaker") or {}).get("breaker") or {}
    if breaker:
        name = f"{_PREFIX}_cache_breaker_state"
        w.header(name, "gauge",
                 "Cache circuit-breaker state (1 = current).")
        state = breaker.get("state", "closed")
        for s in _BREAKER_STATES:
            w.sample(name, [("state", s)], 1 if s == state else 0)
        w.scalar(f"{_PREFIX}_cache_breaker_trips_total", "counter",
                 "Circuit-breaker trips.", breaker.get("trips"))
        w.scalar(f"{_PREFIX}_cache_fallback_ops_total", "counter",
                 "Cache ops answered by the local fallback.",
                 (stats.get("cache_breaker") or {})
                 .get("fallback_ops"))

    if tracer_stats:
        w.scalar(f"{_PREFIX}_trace_spans_total", "counter",
                 "Spans recorded by the tracer.",
                 tracer_stats.get("spans"))
        w.scalar(f"{_PREFIX}_trace_traces_total", "counter",
                 "Completed traces.", tracer_stats.get("traces"))
    if recorder_stats:
        w.scalar(f"{_PREFIX}_flight_recorder_traces", "gauge",
                 "Traces held in the flight-recorder ring.",
                 recorder_stats.get("traces"))
        w.scalar(f"{_PREFIX}_flight_recorder_evicted_total",
                 "counter", "Traces evicted from the ring.",
                 recorder_stats.get("evicted"))
        w.scalar(f"{_PREFIX}_flight_recorder_dumps_total",
                 "counter", "Crash-dump traces written to disk.",
                 recorder_stats.get("dumps"))
        w.scalar(f"{_PREFIX}_recorder_dump_bytes", "gauge",
                 "Bytes of flight-recorder dump files currently "
                 "on disk.", recorder_stats.get("dump_bytes"))
        w.scalar(f"{_PREFIX}_recorder_dumps_pruned_total",
                 "counter",
                 "Dump files pruned (DUMP_CAP count-FIFO or "
                 "TRIVY_TPU_DUMP_MAX_AGE_S age cap).",
                 recorder_stats.get("dumps_pruned"))

    _histograms(w, "sched_phase_latency", "phase", phase_hists or {},
                "Scheduler per-phase latency (queue_wait, analyze, "
                "device, finish, request).", openmetrics)
    _histograms(w, "trace_span", "span", trace_hists or {},
                "Per-phase latency derived from trace spans.",
                openmetrics)
    _histograms(w, "tenant_request", "tenant", tenant_hists or {},
                "Per-tenant request latency (admission to "
                "resolution) — the fairness/QoS signal.",
                openmetrics)
    wh = watch_hists or {}
    _histograms(w, "watch_lag", "stage",
                {"complete": wh["watch_lag"]}
                if "watch_lag" in wh else {},
                "Push-event lag: registry event arrival to scan "
                "resolution.", openmetrics)
    _histograms(w, "admission_latency", "stage",
                {"review": wh["admission_latency"]}
                if "admission_latency" in wh else {},
                "K8s admission review latency (wall time of "
                "POST /k8s/admission).", openmetrics)

    _process_gauges(w, stats.get("process") or {})

    if openmetrics:
        w.lines.append("# EOF")
    return "\n".join(w.lines) + "\n"


def render_router(stats: dict, hists=None) -> str:
    """Text exposition for the scan-router front's ``GET /metrics``
    (docs/serving.md "Scan router & autoscaling"). Separate from
    :func:`render_prometheus` on purpose: the router is a different
    process with a different metrics surface, and the replica
    servers' byte-stable exposition must not grow families it never
    serves. Input is ``RouterServer.metrics()`` — the router books
    (exactly-once terminal outcomes), per-replica gauges, ring and
    scaler state."""
    w = _Writer()
    r = stats.get("router") or {}
    p = f"{_PREFIX}_router"

    w.scalar(f"{p}_accepted_total", "counter",
             "Requests accepted for routing; each ends in exactly "
             "one terminal outcome (the books-balance invariant).",
             r.get("accepted", 0))
    w.header(f"{p}_requests_total", "counter",
             "Terminal outcomes of accepted requests.")
    for outcome in ("ok", "degraded", "timeout", "rate_limited",
                    "unavailable", "failed"):
        w.sample(f"{p}_requests_total", [("outcome", outcome)],
                 r.get(outcome, 0))
    w.scalar(f"{p}_lost", "gauge",
             "accepted - terminal; zero at quiesce, anything else "
             "is a lost request.", r.get("lost", 0))
    w.header(f"{p}_routing_total", "counter",
             "Routing mechanics by kind.")
    for kind in ("forwards", "failovers", "replays", "spills",
                 "conn_errors", "drain_redirects"):
        w.sample(f"{p}_routing_total", [("kind", kind)],
                 r.get(kind, 0))
    w.header(f"{p}_fleet_events_total", "counter",
             "Ring-churn, ejection/recovery and probe events.")
    for kind in ("ring_churn", "ejections", "recoveries", "probes",
                 "probe_failures"):
        w.sample(f"{p}_fleet_events_total", [("kind", kind)],
                 r.get(kind, 0))
    w.header(f"{p}_scaler_events_total", "counter",
             "Autoscaler decisions and drain lifecycle.")
    for kind in ("scale_ups", "scale_downs", "scale_holds",
                 "drains_started", "drain_kills"):
        w.sample(f"{p}_scaler_events_total", [("kind", kind)],
                 r.get(kind, 0))

    replicas = stats.get("replicas") or []
    w.scalar(f"{p}_replicas", "gauge",
             "Replicas on the ring.", len(replicas))
    w.scalar(f"{p}_replicas_routable", "gauge",
             "Replicas eligible for NEW work (not draining, not "
             "warming, breaker closed).",
             len(stats.get("routable") or []))
    w.header(f"{p}_replica_inflight", "gauge",
             "Router-tracked in-flight requests per replica.")
    for rep in replicas:
        w.sample(f"{p}_replica_inflight",
                 [("replica", rep.get("name", ""))],
                 rep.get("inflight", 0))
    w.header(f"{p}_replica_draining", "gauge",
             "Replica drain state (1 = no NEW work).")
    for rep in replicas:
        w.sample(f"{p}_replica_draining",
                 [("replica", rep.get("name", ""))],
                 1 if rep.get("draining") else 0)
    w.header(f"{p}_replica_warming", "gauge",
             "Replica prewarm state (1 = joined the ring, not yet "
             "admitted; flips on the first ready health probe).")
    for rep in replicas:
        w.sample(f"{p}_replica_warming",
                 [("replica", rep.get("name", ""))],
                 1 if rep.get("warming") else 0)
    w.header(f"{p}_replica_breaker_state", "gauge",
             "Circuit-breaker state per replica (one-hot).")
    for rep in replicas:
        state = (rep.get("breaker") or {}).get("state", "closed")
        for s in _BREAKER_STATES:
            w.sample(f"{p}_replica_breaker_state",
                     [("replica", rep.get("name", "")),
                      ("state", s)], 1 if s == state else 0)

    lifecycle = stats.get("lifecycle") or {}
    if lifecycle:
        # elastic-lifecycle counters booked by THIS process: the
        # autoscaler's drain-handoff orchestration (docs/serving.md
        # "Elastic lifecycle"); replica-side prewarm counters live
        # on each replica's own /metrics
        for k, help_ in (
                ("handoff_published",
                 "Hot digests pulled from draining replicas."),
                ("handoff_prefetched",
                 "Handoff digests adopted by ring successors."),
                ("handoff_abandoned",
                 "Handoff digests no successor adopted."),
                ("prewarm_keys",
                 "Memo keys staged by prewarm walks."),
                ("prewarm_bytes",
                 "Memo payload bytes staged by prewarm walks."),
                ("prewarm_seconds",
                 "Wall seconds spent in prewarm walks."),
                ("prewarm_deadline_exceeded",
                 "Prewarm walks cut short by the deadline.")):
            w.scalar(f"{_PREFIX}_{k}_total", "counter", help_,
                     lifecycle.get(k))

    w.scalar(f"{p}_affinity_entries", "gauge",
             "Cache-session affinity entries (id -> route key).",
             stats.get("affinity_entries", 0))
    # latency histograms ride the RAW bucket shape
    # (RouterMetrics.hist_snapshot), not the quantile summary the
    # JSON snapshot carries
    _histograms(w, "router_latency", "stage", hists or {},
                "Router latency: route_latency = end-to-end wall "
                "time, upstream_latency = time waiting on the "
                "upstream replica; the difference is attributed "
                "router overhead.")
    _process_gauges(w, stats.get("process") or {})
    return "\n".join(w.lines) + "\n"
