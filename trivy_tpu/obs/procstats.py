"""Process self-stats: RSS, open fds, thread count — zero-dep.

The soak harness's leak audit (trivy_tpu/soak/audit.py) needs every
process in the fleet — replica servers in both sched modes, the
router front, federated peers — to publish its own resource
footprint on ``/metrics``, so a week-compressed chaos run can assert
"no series grows without bound" without shelling out to ``ps``.

Reads ``/proc/self`` directly (Linux) and ``threading`` — no psutil,
matching the zero-dependency rule for the obs layer. On platforms
without procfs the gauges degrade to ``-1`` (absent, not zero: a
zero RSS would read as a real measurement).
"""

from __future__ import annotations

import os
import threading

_PAGE = 4096  # only used for the statm fallback

# high-water RSS observed by THIS process's own sampling (ratcheted
# on every process_self_stats call — the scrape cadence is the
# sampling cadence). The soak gate "peak RSS bounded" reads this
# through metrics federation instead of trusting whichever single
# sample a prober happened to catch.
_peak_lock = threading.Lock()
_peak_rss = -1


def reset_peak_rss() -> None:
    """Forget the high-water mark (test isolation)."""
    global _peak_rss
    with _peak_lock:
        _peak_rss = -1


def _rss_bytes() -> int:
    """Resident set size from ``/proc/self/status`` (VmRSS), with a
    ``/proc/self/statm`` fallback; -1 when neither is readable."""
    try:
        with open("/proc/self/status", encoding="ascii",
                  errors="replace") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    parts = line.split()
                    if len(parts) >= 2 and parts[1].isdigit():
                        return int(parts[1]) * 1024
    except OSError:
        pass
    try:
        with open("/proc/self/statm", encoding="ascii") as f:
            fields = f.read().split()
        if len(fields) >= 2 and fields[1].isdigit():
            return int(fields[1]) * _PAGE
    except OSError:
        pass
    return -1


def _open_fds() -> int:
    """Open file-descriptor count from ``/proc/self/fd``; -1 when
    procfs is unavailable."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def process_self_stats() -> dict:
    """One sample: ``{"rss_bytes", "open_fds", "threads",
    "peak_rss_bytes"}``.

    ``threads`` comes from :func:`threading.active_count` — the
    interpreter's view, which is what leak hunting cares about
    (a native thread the interpreter lost track of shows up in RSS
    instead). ``peak_rss_bytes`` is the ratcheted high-water of
    every sample this process has taken — the federated soak gate's
    "peak RSS bounded" series. Unavailable gauges are ``-1`` so
    renderers and the audit can tell "no data" from "zero"."""
    global _peak_rss
    rss = _rss_bytes()
    with _peak_lock:
        if rss > _peak_rss:
            _peak_rss = rss
        peak = _peak_rss
    return {
        "rss_bytes": rss,
        "open_fds": _open_fds(),
        "threads": threading.active_count(),
        "peak_rss_bytes": peak,
    }
