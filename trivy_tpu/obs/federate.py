"""Metrics/SLO federation across a replica fleet
(docs/observability.md "Fleet plane").

A :class:`Federator` holds the peer list of one federating front.
On every ``GET /metrics/federate`` scrape it pulls each peer's
snapshot JSON (``GET /metrics/snapshot``: prom text + SLO ring
export + build info), with

* **bounded fan-in** — at most ``fan_in`` concurrent pulls;
* **per-peer timeout** — one slow replica delays, never wedges, the
  scrape;
* **breaker-style skip** — a per-peer
  :class:`artifact.resilient.CircuitBreaker`: consecutive failures
  open the circuit and the peer is skipped (served from its last
  snapshot, marked stale) until the cooldown's half-open probe;
* **staleness marking** — a peer whose snapshot is older than
  ``stale_after_s`` is served but flagged, so partial federation is
  always visibly partial, never an error and never silently
  complete.

The merged exposition carries every sample under a ``replica``
label. Replica names are label values, so they follow the PR-7/8
cardinality rule: at most :data:`MAX_REPLICAS` distinct names,
overflow folds into ``other``.

Fleet SLO verdicts ride the same scrape: each peer's snapshot
carries its :meth:`SloEngine.export_state` (age-keyed buckets —
monotonic-only, no cross-process epoch needed), the front merges
them with :func:`obs.slo.merge_exports` and recomputes the
multi-window burn rates with :func:`obs.slo.verdicts_from_export` —
the same math as one engine fed the union event stream.
"""

from __future__ import annotations

import json
import re
import threading
import time

from .slo import merge_exports, verdicts_from_export

# distinct replica label values (the PR-7/8 fold rule)
MAX_REPLICAS = 64

_NAME_OK = re.compile(r"[A-Za-z0-9_.:\-]{1,64}")
_METRIC_NAME = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")


def _clean_replica(name: str) -> str:
    name = str(name or "").strip()
    if not name:
        return "other"
    name = re.sub(r"[^A-Za-z0-9_.:\-]", "_", name)[:64]
    return name if _NAME_OK.fullmatch(name) else "other"


def _esc_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _inject_replica(line: str, replica: str) -> str:
    """Rewrite one exposition sample line to carry
    ``replica="<name>"`` (first label, injected after the ``{`` or
    as a fresh label set). A line that already carries a replica
    label — a federate-of-federate — is passed through untouched."""
    if 'replica="' in line:
        return line
    m = _METRIC_NAME.match(line)
    if m is None:
        return line
    name = m.group(0)
    rest = line[len(name):]
    label = f'replica="{_esc_label(replica)}"'
    if rest.startswith("{"):
        return f"{name}{{{label},{rest[1:]}"
    return f"{name}{{{label}}}{rest}"


def merge_expositions(parts: list) -> str:
    """Merge N ``(replica, prom_text)`` pairs into one text/plain
    0.0.4 document: families are grouped contiguously (strict
    parsers require one ``# TYPE`` per family), every sample gains
    the replica label, the first-seen HELP/TYPE per family wins."""
    families: dict = {}
    order: list = []
    for replica, text in parts:
        current = None
        for line in (text or "").splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or \
                    line.startswith("# TYPE "):
                fields = line.split(None, 3)
                if len(fields) < 3:
                    continue
                name = fields[2]
                fam = families.get(name)
                if fam is None:
                    fam = families[name] = \
                        {"help": None, "type": None, "samples": []}
                    order.append(name)
                key = "help" if fields[1] == "HELP" else "type"
                if fam[key] is None:
                    fam[key] = line
                current = name
            elif line.startswith("#"):
                continue            # comments / # EOF
            else:
                m = _METRIC_NAME.match(line)
                if m is None:
                    continue
                # histogram/summary series (_bucket/_sum/_count)
                # belong to the family whose header precedes them
                name = current if current is not None and \
                    m.group(0).startswith(current) else m.group(0)
                fam = families.get(name)
                if fam is None:
                    fam = families[name] = \
                        {"help": None, "type": None, "samples": []}
                    order.append(name)
                fam["samples"].append(
                    _inject_replica(line, replica))
    out = []
    for name in order:
        fam = families[name]
        if fam["help"]:
            out.append(fam["help"])
        if fam["type"]:
            out.append(fam["type"])
        out.extend(fam["samples"])
    return "\n".join(out) + "\n"


class _Peer:
    __slots__ = ("name", "url", "breaker", "snapshot",
                 "last_ok", "fetches", "failures", "skips")

    def __init__(self, name: str, url: str, breaker):
        self.name = name
        self.url = url
        self.breaker = breaker
        self.snapshot = None      # last good snapshot JSON
        self.last_ok = None       # monotonic of last success
        self.fetches = 0
        self.failures = 0
        self.skips = 0


def parse_peers(spec) -> list:
    """``name=url,name=url`` (or an iterable of such entries) →
    [(name, url)]; a bare url gets its host:port as the name."""
    if isinstance(spec, str):
        entries = [p for p in re.split(r"[,\s]+", spec) if p]
    else:
        entries = []
        for p in (spec or []):
            if isinstance(p, (tuple, list)) and len(p) == 2:
                # already-parsed (name, url) pairs pass through
                entries.append(f"{p[0]}={p[1]}" if p[0] else
                               str(p[1]))
            elif str(p):
                entries.append(str(p))
    out = []
    for entry in entries:
        name, sep, url = entry.partition("=")
        if not sep:
            url = entry
            name = re.sub(r"^https?://", "", url).rstrip("/")
        if not re.match(r"^https?://[^/]+", url):
            # a typo'd peer list fails up front (the CLI exits 2),
            # not at the first scrape with every peer "down"
            raise ValueError(
                f"peer {entry!r}: expected name=http://host:port")
        out.append((_clean_replica(name), url.rstrip("/")))
    return out


class Federator:
    """Pull-side federation state for one front replica. Transport
    is injectable (``fetch(url) -> snapshot dict``) so unit tests
    exercise breaker/staleness logic without sockets."""

    def __init__(self, peers, token: str = "",
                 token_header: str = "Trivy-Token",
                 timeout_s: float = 2.0,
                 stale_after_s: float = 60.0,
                 fan_in: int = 8,
                 fail_threshold: int = 3,
                 cooldown_s: float = 5.0,
                 fetch=None,
                 clock=time.monotonic):
        from ..artifact.resilient import CircuitBreaker
        self.token = token
        self.token_header = token_header
        self.timeout_s = timeout_s
        self.stale_after_s = stale_after_s
        self.fan_in = max(1, int(fan_in))
        self._clock = clock
        self._fetch = fetch or self._http_fetch
        self._lock = threading.Lock()
        self.scrapes = 0
        self.last_scrape_s = 0.0
        self.peers = []
        for i, (name, url) in enumerate(parse_peers(peers)):
            # cardinality fold: peers past the cap share one label
            if i >= MAX_REPLICAS:
                name = "other"
            self.peers.append(_Peer(name, url, CircuitBreaker(
                fail_threshold=fail_threshold,
                cooldown_s=cooldown_s, clock=clock)))

    # --- transport ---

    def _http_fetch(self, url: str) -> dict:
        import urllib.request
        req = urllib.request.Request(url + "/metrics/snapshot")
        if self.token:
            req.add_header(self.token_header, self.token)
        with urllib.request.urlopen(
                req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    # --- the scrape ---

    def _pull(self, peer: _Peer) -> dict:
        now = self._clock()
        if not peer.breaker.allow():
            peer.skips += 1
            return self._row(peer, up=False, skipped=True)
        try:
            snap = self._fetch(peer.url)
            if not isinstance(snap, dict):
                raise ValueError("snapshot is not a JSON object")
        except Exception as e:  # noqa: BLE001 — any transport or
            # decode failure is the condition federation exists to
            # absorb: mark, keep the last snapshot, move on
            peer.breaker.record_failure()
            peer.failures += 1
            return self._row(peer, up=False, error=repr(e))
        peer.breaker.record_success()
        peer.fetches += 1
        peer.snapshot = snap
        peer.last_ok = now
        return self._row(peer, up=True)

    def _row(self, peer: _Peer, up: bool, skipped: bool = False,
             error: str = "") -> dict:
        now = self._clock()
        age = None if peer.last_ok is None else now - peer.last_ok
        stale = (not up) and (age is None or
                              age > self.stale_after_s)
        return {"replica": peer.name, "url": peer.url, "up": up,
                "stale": stale, "skipped": skipped,
                "age_s": round(age, 3) if age is not None else None,
                "error": error,
                "snapshot": peer.snapshot,
                "breaker": peer.breaker.state}

    def collect(self) -> list:
        """Scrape every peer with bounded fan-in; one row per peer
        in declaration order. Never raises."""
        t0 = self._clock()
        rows: list = [None] * len(self.peers)
        sem = threading.Semaphore(self.fan_in)

        def work(i: int, peer: _Peer) -> None:
            with sem:
                rows[i] = self._pull(peer)

        threads = [threading.Thread(target=work, args=(i, p),
                                    daemon=True)
                   for i, p in enumerate(self.peers)]
        for t in threads:
            t.start()
        for t in threads:
            # the per-peer fetch timeout bounds each pull; the join
            # timeout is a second-layer backstop so a wedged socket
            # cannot wedge the scrape thread
            t.join(self.timeout_s * 2 + 1.0)
        for i, peer in enumerate(self.peers):
            if rows[i] is None:
                rows[i] = self._row(peer, up=False,
                                    error="scrape timeout")
        with self._lock:
            self.scrapes += 1
            self.last_scrape_s = self._clock() - t0
        return rows

    # --- rendering ---

    def render(self, local_name: str, local_text: str,
               rows: list, fleet: dict = None) -> str:
        """The ``GET /metrics/federate`` body: local + peer
        expositions merged under replica labels, then the
        federation-meta and fleet-SLO families."""
        parts = [(_clean_replica(local_name) or "self", local_text)]
        for row in rows:
            snap = row.get("snapshot")
            if snap and isinstance(snap.get("prom"), str):
                parts.append((row["replica"], snap["prom"]))
        out = [merge_expositions(parts).rstrip("\n")]
        p = "trivy_tpu_federate"
        out.append(f"# HELP {p}_peers Configured federation peers.")
        out.append(f"# TYPE {p}_peers gauge")
        out.append(f"{p}_peers {len(self.peers)}")
        out.append(f"# HELP {p}_peer_up Peer snapshot fetch "
                   f"succeeded on the last scrape.")
        out.append(f"# TYPE {p}_peer_up gauge")
        for row in rows:
            out.append(f'{p}_peer_up{{replica='
                       f'"{_esc_label(row["replica"])}"}} '
                       f'{1 if row["up"] else 0}')
        out.append(f"# HELP {p}_peer_stale Peer served from a "
                   f"snapshot older than stale_after_s (or never "
                   f"seen).")
        out.append(f"# TYPE {p}_peer_stale gauge")
        for row in rows:
            out.append(f'{p}_peer_stale{{replica='
                       f'"{_esc_label(row["replica"])}"}} '
                       f'{1 if row["stale"] else 0}')
        out.append(f"# HELP {p}_scrape_seconds Duration of the "
                   f"last federation scrape.")
        out.append(f"# TYPE {p}_scrape_seconds gauge")
        out.append(f"{p}_scrape_seconds "
                   f"{round(self.last_scrape_s, 6)}")
        if fleet is not None:
            fp = "trivy_tpu_fleet"
            out.append(f"# HELP {fp}_slo_ok Fleet-level SLO verdict "
                       f"over the merged event buckets (1 = within "
                       f"budget).")
            out.append(f"# TYPE {fp}_slo_ok gauge")
            for v in fleet.get("slos") or []:
                out.append(f'{fp}_slo_ok{{slo='
                           f'"{_esc_label(v["name"])}"}} '
                           f'{1 if v["ok"] else 0}')
            out.append(f"# HELP {fp}_slo_burn_rate Fleet-level "
                       f"error-budget burn rate per window.")
            out.append(f"# TYPE {fp}_slo_burn_rate gauge")
            for v in fleet.get("slos") or []:
                for win, rate in (v.get("burn") or {}).items():
                    out.append(
                        f'{fp}_slo_burn_rate{{slo='
                        f'"{_esc_label(v["name"])}",window='
                        f'"{_esc_label(win)}"}} {rate}')
            out.append(f"# HELP {fp}_complete Every peer answered "
                       f"fresh on the last scrape (0 = partial "
                       f"federation).")
            out.append(f"# TYPE {fp}_complete gauge")
            out.append(f"{fp}_complete "
                       f"{1 if fleet.get('complete') else 0}")
        return "\n".join(out) + "\n"

    # --- fleet SLO ---

    def fleet_slo(self, local_export: dict, rows: list,
                  now=None) -> dict:
        """Merged fleet verdicts + per-peer freshness. ``complete``
        is False the moment ANY peer is down or stale — the
        autoscaler contract is "partial federation is visibly
        partial"."""
        exports = []
        if local_export:
            exports.append(local_export)
        for row in rows:
            snap = row.get("snapshot")
            if snap and isinstance(snap.get("slo_export"), dict):
                exports.append(snap["slo_export"])
        merged = merge_exports(exports)
        verdicts = verdicts_from_export(merged, now=now)
        complete = all(r["up"] and not r["stale"] for r in rows)
        return {
            "slos": verdicts,
            "slo_ok": all(v["ok"] for v in verdicts)
            if verdicts else True,
            "complete": complete,
            "replicas": 1 + sum(1 for r in rows
                                if r.get("snapshot") is not None),
            "peers": [{"replica": r["replica"], "up": r["up"],
                       "stale": r["stale"],
                       "skipped": r["skipped"],
                       "age_s": r["age_s"],
                       "breaker": r["breaker"]}
                      for r in rows],
        }

    def stats(self) -> dict:
        with self._lock:
            scrapes = self.scrapes
            last = self.last_scrape_s
        return {
            "peers": len(self.peers),
            "scrapes": scrapes,
            "last_scrape_s": round(last, 6),
            "per_peer": [{"replica": p.name, "url": p.url,
                          "fetches": p.fetches,
                          "failures": p.failures,
                          "skips": p.skips,
                          "breaker": p.breaker.state}
                         for p in self.peers],
        }
