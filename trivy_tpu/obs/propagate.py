"""Cross-process trace propagation + clock-offset handshake — the
fleet plane's transport layer (docs/observability.md "Fleet plane").

Two independent pieces:

* **TraceContext** — a W3C-traceparent-style context
  (``00-<trace_id>-<parent_span_id>-01``) carried across every
  process seam: RPC Scan bodies, the simhost spec file, and watch
  notification envelopes. Ids are validated with the same
  ``^[0-9a-f]{8,64}$`` discipline as :mod:`obs.trace` (fullmatch —
  they end up in flight-recorder dump file names), so a hostile
  header degrades to "no context", never to a bad id.

* **Clock-offset estimation** — a tiny monotonic-clock handshake
  (:class:`ClockServer` over TCP for sim hosts, ``GET /clock`` on
  the RPC server) plus :func:`estimate_offset`: midpoint-of-RTT over
  the minimum-RTT sample, so ``local ≈ remote + offset`` with error
  bounded by rtt/2. Monotonic only, per the PR-8/PR-12 clock rule —
  wall clocks never enter timeline math.

Import-light like obs/trace.py: stdlib only at module scope.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Optional

from .trace import _ID_RE, current_span

# body field and HTTP header the context rides in (the RPC server
# folds the header into the body exactly like the tenant header, so
# every downstream consumer reads one place)
TRACEPARENT_KEY = "traceparent"
TRACEPARENT_HEADER = "Traceparent"

_VERSION = "00"
_ZERO_SPAN = "0" * 16


def _valid_id(value: str) -> bool:
    return bool(value) and _ID_RE.fullmatch(value) is not None


@dataclass(frozen=True)
class TraceContext:
    """One propagated (trace_id, parent_span_id) pair. Either field
    may be empty; :meth:`valid` means a usable trace id rode in."""

    trace_id: str = ""
    parent_span_id: str = ""

    def valid(self) -> bool:
        return _valid_id(self.trace_id)

    def to_header(self) -> str:
        """``00-<trace_id>-<parent_span_id>-01``; an empty parent
        renders as the all-zero span id (W3C's "no parent")."""
        return "-".join((_VERSION, self.trace_id,
                         self.parent_span_id or _ZERO_SPAN, "01"))


EMPTY_CONTEXT = TraceContext()


def parse_traceparent(text) -> Optional[TraceContext]:
    """Strict parse of a traceparent value; None on anything that
    does not round-trip (wrong arity, bad hex, version ff, an id
    outside the 8–64 lowercase-hex discipline). The all-zero parent
    span id means "root" and parses to an empty parent."""
    parts = str(text or "").strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    hexdigits = "0123456789abcdef"
    if len(version) != 2 or any(c not in hexdigits for c in version):
        return None
    if version == "ff":
        return None
    if len(flags) != 2 or any(c not in hexdigits for c in flags):
        return None
    if not _valid_id(trace_id) or set(trace_id) == {"0"}:
        return None
    if span_id == _ZERO_SPAN or set(span_id) == {"0"}:
        span_id = ""
    elif not _valid_id(span_id):
        return None
    return TraceContext(trace_id=trace_id, parent_span_id=span_id)


def current_context() -> Optional[TraceContext]:
    """The active span's context, or None when no (real) span is
    active — this is what clients inject at the wire seam."""
    span = current_span()
    if span is None or span.noop or not span.trace_id:
        return None
    return TraceContext(trace_id=span.trace_id,
                        parent_span_id=span.span_id)


def inject(body: dict, span=None) -> dict:
    """Stamp the active (or given) span's context into an RPC body.
    Keeps the legacy bare ``trace_id`` field too, so a new client
    against an old server degrades to the pre-fleet behavior (same
    trace id, remote root not linked) instead of losing the id."""
    if span is not None and not getattr(span, "noop", False) \
            and span.trace_id:
        ctx = TraceContext(trace_id=span.trace_id,
                           parent_span_id=span.span_id)
    else:
        ctx = current_context()
    if ctx is not None:
        body[TRACEPARENT_KEY] = ctx.to_header()
        body.setdefault("trace_id", ctx.trace_id)
    return body


def extract(body, headers=None) -> TraceContext:
    """Pull a context out of a request: the ``traceparent`` body
    field (or header) wins; a legacy bare ``trace_id`` still yields
    an unparented context. Never raises, never returns None — a
    garbage header is an EMPTY context (fresh root), matching the
    _clean_trace_id security posture."""
    raw = ""
    if isinstance(body, dict):
        raw = str(body.get(TRACEPARENT_KEY) or "")
    if not raw and headers is not None:
        try:
            raw = str(headers.get(TRACEPARENT_HEADER) or "")
        except Exception:   # noqa: BLE001 — a headers mapping that
            raw = ""        # raises is treated as absent
    ctx = parse_traceparent(raw) if raw else None
    if ctx is not None:
        return ctx
    legacy = ""
    if isinstance(body, dict):
        legacy = str(body.get("trace_id") or "").lower()
    if _valid_id(legacy):
        return TraceContext(trace_id=legacy)
    return EMPTY_CONTEXT


# --- monotonic clock-offset handshake -----------------------------

@dataclass(frozen=True)
class OffsetEstimate:
    """``local_mono ≈ remote_mono + offset_s``, with the midpoint
    error bounded by ``error_bound_s`` (= best rtt / 2): the remote
    stamp was taken somewhere inside the probe's [t0, t1] window."""

    offset_s: float
    error_bound_s: float
    rtt_s: float
    samples: int


def estimate_offset(probe, samples: int = 8) -> OffsetEstimate:
    """Pairwise clock-offset estimate from ``samples`` round trips of
    ``probe()`` (a callable returning the peer's ``time.monotonic()``
    as float). Uses the minimum-RTT sample — the one with the
    tightest error bound — and the midpoint-of-RTT convention:
    ``offset = (t0+t1)/2 - remote``."""
    best_rtt, best_offset = None, 0.0
    n = 0
    for _ in range(max(1, int(samples))):
        t0 = time.monotonic()
        remote = float(probe())
        t1 = time.monotonic()
        n += 1
        rtt = max(0.0, t1 - t0)
        if best_rtt is None or rtt < best_rtt:
            best_rtt = rtt
            best_offset = (t0 + t1) / 2.0 - remote
    return OffsetEstimate(offset_s=best_offset,
                          error_bound_s=(best_rtt or 0.0) / 2.0,
                          rtt_s=best_rtt or 0.0, samples=n)


class ClockServer:
    """Line-oriented TCP clock responder a sim host runs so the
    coordinating process can handshake offsets while the host scans:
    every received line is answered with ``{"mono": <monotonic>}\\n``.
    Daemon threads, bounded to loopback by default, closed
    idempotently."""

    def __init__(self, addr: str = "127.0.0.1", port: int = 0):
        self._sock = socket.create_server((addr, port))
        self._sock.settimeout(0.25)
        self.addr = addr
        self.port = self._sock.getsockname()[1]
        self._closed = False
        self.requests = 0
        self._thread = threading.Thread(
            target=self._serve, name="trivy-tpu-clock", daemon=True)
        self._thread.start()

    def write_port_file(self, path: str) -> None:
        """Publish the bound port atomically (tmp + rename), so a
        parent polling the file never reads a partial write."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(self.port))
        os.replace(tmp, path)

    def _serve(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._answer, args=(conn,),
                                 daemon=True)
            t.start()

    def _answer(self, conn) -> None:
        try:
            conn.settimeout(5.0)
            buf = b""
            while not self._closed:
                chunk = conn.recv(256)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    _, buf = buf.split(b"\n", 1)
                    self.requests += 1
                    line = json.dumps(
                        {"mono": time.monotonic()}) + "\n"
                    conn.sendall(line.encode("ascii"))
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class ClockClient:
    """One persistent connection to a :class:`ClockServer`; its
    bound :meth:`probe` feeds :func:`estimate_offset` (a persistent
    connection keeps RTT jitter down versus connect-per-sample)."""

    def __init__(self, addr: str, port: int, timeout_s: float = 2.0):
        self._sock = socket.create_connection(
            (addr, int(port)), timeout=timeout_s)
        self._buf = b""

    def probe(self) -> float:
        self._sock.sendall(b"\n")
        while b"\n" not in self._buf:
            chunk = self._sock.recv(256)
            if not chunk:
                raise ConnectionError("clock server closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return float(json.loads(line.decode("ascii"))["mono"])

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def read_port_file(path: str, timeout_s: float = 10.0) -> int:
    """Poll for a :meth:`ClockServer.write_port_file` publication."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read().strip()
            if text:
                return int(text)
        except (OSError, ValueError):
            pass
        time.sleep(0.01)
    raise TimeoutError(f"clock port file {path!r} never appeared")


def http_clock_probe(url: str, token: str = "",
                     timeout_s: float = 2.0):
    """A probe() over the RPC server's ``GET /clock`` route, for
    offset handshakes between fleet replicas (returns a callable for
    :func:`estimate_offset`)."""
    import urllib.request

    def probe() -> float:
        req = urllib.request.Request(url.rstrip("/") + "/clock")
        if token:
            req.add_header("Trivy-Token", token)
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            doc = json.loads(resp.read().decode("utf-8"))
        return float(doc["mono"])

    return probe
