"""Always-on sampling host profiler (docs/observability.md "Host
profiler").

A daemon thread walks ``sys._current_frames()`` at ~49 Hz (a prime
tick, so it cannot phase-lock with 50/100 Hz periodic work) and
folds every thread's stack into a collapsed-stack counter — the
`folded` format flamegraph.pl / speedscope / inferno consume
directly. Samples land in per-second ring buckets, so
``GET /debug/profile?seconds=N`` (token-protected, mirroring
``/trace/<id>``) answers "what was the host doing for the last N
seconds" from a server that never had profiling "switched on".

Overhead is bounded three ways and *measured*: the sampler skips its
own thread, distinct-stack cardinality folds into ``<overflow>``
past ``max_stacks`` per bucket, and the cumulative sampling CPU time
is tracked in ``stats()["overhead_s"]`` — the ``timeline`` bench
config gates attributed profiler+timeline overhead under 2% of
fleet wall, and asserts findings stay byte-identical with the
profiler on vs off.

The optional **device** trace rides :func:`device_trace`: an opt-in
``jax.profiler`` hook behind ``--profile-out DIR`` (the host
profiler's folded stacks are dumped next to it as
``host_profile.folded``). Import of jax is deferred and failure-
tolerant — a CPU-only box still gets the host profile.

Clock discipline: bucket keys and sample timing are
``time.monotonic``; wall time appears nowhere in the math (lint-
enforced across ``obs/``).
"""

from __future__ import annotations

import os
import sys
import threading
import time

DEFAULT_HZ = 49.0
# per-second buckets retained — 15 minutes of history
RING_SECONDS = 900
# distinct folded stacks per bucket before folding to <overflow>
MAX_STACKS = 4096
# frames folded per stack before truncating (deep recursion guard)
MAX_DEPTH = 64


def _fold(frame) -> str:
    """One thread's stack, outermost-first, semicolon-joined:
    ``module.func;module.func;...`` (the collapsed-stack frame
    vocabulary)."""
    parts: list = []
    while frame is not None and len(parts) < MAX_DEPTH:
        code = frame.f_code
        mod = frame.f_globals.get("__name__", "") or \
            os.path.basename(code.co_filename)
        parts.append(f"{mod}.{code.co_name}")
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


class HostProfiler:
    """The sampling thread + the per-second folded-stack ring."""

    def __init__(self, hz: float = DEFAULT_HZ,
                 ring_seconds: int = RING_SECONDS,
                 max_stacks: int = MAX_STACKS):
        self.hz = max(1.0, float(hz))
        self.ring_seconds = max(1, int(ring_seconds))
        self.max_stacks = max(16, int(max_stacks))
        self._lock = threading.Lock()
        # bucket second (int monotonic) -> {folded stack: count}
        self._ring: dict = {}
        self._stop = threading.Event()
        self._thread = None
        self.samples = 0
        self.ticks = 0
        self.overhead_s = 0.0      # cumulative sampling CPU time

    # --- lifecycle ---

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "HostProfiler":
        with self._lock:
            if self.running:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="trivy-obs-profiler",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 1.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    # --- sampling ---

    @staticmethod
    def _next_tick(nxt: float, period: float, now: float) -> float:
        """Fixed-rate schedule (not fixed-sleep): a slow tick doesn't
        compound into a slower sampling rate — but missed ticks are
        DROPPED, never replayed: after a long GIL hold or blocking C
        call the sampler must not fire a zero-wait catch-up burst
        that overweights whatever runs right after the stall."""
        return max(nxt + period, now)

    def _loop(self) -> None:
        period = 1.0 / self.hz
        me = threading.get_ident()
        nxt = time.monotonic()
        while not self._stop.wait(max(0.0, nxt - time.monotonic())):
            nxt = self._next_tick(nxt, period, time.monotonic())
            t0 = time.process_time()
            try:
                self.sample_once(skip_thread=me)
            # lint: disable=bare-except-at-seam -- the ~49Hz tick
            # must never take the host down or pay logging on the
            # hot path; a failed tick self-heals next period
            except Exception:       # noqa: BLE001 — the profiler
                pass                # must never take the host down
            self.overhead_s += time.process_time() - t0

    def sample_once(self, skip_thread=None) -> int:
        """One walk over every live thread's stack; returns the
        number of stacks recorded (tests drive this directly)."""
        frames = sys._current_frames()
        sec = int(time.monotonic())
        n = 0
        with self._lock:
            bucket = self._ring.get(sec)
            if bucket is None:
                bucket = self._ring[sec] = {}
                while len(self._ring) > self.ring_seconds:
                    self._ring.pop(next(iter(self._ring)))
            for tid, frame in frames.items():
                if tid == skip_thread:
                    continue
                stack = _fold(frame)
                if stack not in bucket and \
                        len(bucket) >= self.max_stacks:
                    stack = "<overflow>"
                bucket[stack] = bucket.get(stack, 0) + 1
                n += 1
            self.ticks += 1
            self.samples += n
        return n

    # --- export ---

    def folded(self, seconds=None) -> dict:
        """{folded stack: count} over the last ``seconds`` (whole
        ring when None)."""
        with self._lock:
            if seconds is None:
                keys = list(self._ring)
            else:
                horizon = int(time.monotonic()) - max(
                    0, int(seconds)) + 1
                keys = [k for k in self._ring if k >= horizon]
            out: dict = {}
            for k in keys:
                for stack, c in self._ring[k].items():
                    out[stack] = out.get(stack, 0) + c
            return out

    def collapsed(self, seconds=None) -> str:
        """Collapsed-stack text (``stack count`` per line), heaviest
        first — feed to flamegraph.pl / speedscope as-is."""
        folded = self.folded(seconds)
        lines = [f"{stack} {count}" for stack, count in
                 sorted(folded.items(),
                        key=lambda kv: (-kv[1], kv[0]))]
        return "\n".join(lines) + ("\n" if lines else "")

    def dump(self, path: str, seconds=None) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.collapsed(seconds))
        return path

    def stats(self) -> dict:
        with self._lock:
            return {"running": self.running, "hz": self.hz,
                    "ticks": self.ticks, "samples": self.samples,
                    "buckets": len(self._ring),
                    "overhead_s": round(self.overhead_s, 6)}


_PROFILER = None
_LOCK = threading.Lock()


def get_profiler(start: bool = True) -> HostProfiler:
    """The process-wide profiler (created on first use; started
    unless ``start=False`` or ``TRIVY_TPU_PROFILE=off``)."""
    global _PROFILER
    if _PROFILER is None:
        with _LOCK:
            if _PROFILER is None:
                _PROFILER = HostProfiler()
    if start and os.environ.get("TRIVY_TPU_PROFILE", "") != "off":
        _PROFILER.start()
    return _PROFILER


class _DeviceTraceCtx:
    """Context manager behind :func:`device_trace`: jax.profiler
    around the body when available, host folded stacks dumped either
    way. ``max_seconds > 0`` bounds the capture: a daemon timer
    closes the trace and writes the artifacts after the window, so a
    long-lived body (the server's ``serve_forever``) cannot
    accumulate an unbounded device trace that only flushes at
    process exit."""

    def __init__(self, out_dir: str, max_seconds: float = 0.0):
        self.out_dir = out_dir
        self.max_seconds = max_seconds
        self._jax_trace = None
        self._timer = None
        self._done = threading.Lock()
        self._finished = False

    def __enter__(self):
        if not self.out_dir:
            return self
        os.makedirs(self.out_dir, exist_ok=True)
        get_profiler()
        try:
            import jax
            self._jax_trace = jax.profiler.trace(self.out_dir)
            self._jax_trace.__enter__()
        except Exception:           # noqa: BLE001 — no jax / no
            self._jax_trace = None  # profiler plugin: host-only
        if self.max_seconds > 0:
            self._timer = threading.Timer(self.max_seconds,
                                          self._finish)
            self._timer.daemon = True
            self._timer.start()
        return self

    def _finish(self, *exc) -> None:
        with self._done:
            if self._finished:
                return
            self._finished = True
        if self._jax_trace is not None:
            try:
                self._jax_trace.__exit__(*(exc or (None,) * 3))
            # lint: disable=bare-except-at-seam -- no jax or no
            # profiler plugin: the host-only profile is still
            # written below, which is the degraded contract
            except Exception:       # noqa: BLE001
                pass
        try:
            get_profiler(start=False).dump(
                os.path.join(self.out_dir, "host_profile.folded"))
        except OSError:
            pass

    def __exit__(self, *exc):
        if not self.out_dir:
            return
        if self._timer is not None:
            self._timer.cancel()
        self._finish(*exc)


def device_trace(out_dir: str,
                 max_seconds: float = 0.0) -> _DeviceTraceCtx:
    """``--profile-out DIR``: opt-in jax.profiler device trace (open
    in TensorBoard/Perfetto) + the host profiler's collapsed stacks
    written to ``DIR/host_profile.folded``. A falsy ``out_dir`` is a
    no-op; ``max_seconds`` bounds the capture window (0 = until the
    context exits)."""
    return _DeviceTraceCtx(out_dir, max_seconds=max_seconds)
