"""Per-device busy/idle timeline reconstruction with typed idle
attribution (docs/observability.md "Idle attribution").

ROADMAP item 4 (true async runtime) is gated on ``dispatch/device
<= 1`` — but the raw phase totals (``interval_dispatch_s`` 2x
``interval_device_s`` on the 512-image bench) say only THAT the
device idles, not WHY. This module rebuilds the device's busy/idle
timeline from the span trees the tracer already records and
attributes **every** idle instant to a typed cause, so the
async-runtime refactor lands against a measured baseline:

==================  =================================================
cause               the device was idle because ...
==================  =================================================
``upload_serialized``  a host→device table/segment upload ran
                       (``h2d_upload`` / ``db_upload`` /
                       ``dfa_upload`` spans) AND that upload span
                       never overlapped device compute — it truly
                       serialized against an idle device. An upload
                       that ran concurrently with a busy span is a
                       PIPELINED upload (the async runtime's
                       double-buffered staging) and is excluded
                       from this cause entirely: the idle it covers
                       falls through to the next matching cause
``fetch_serialized``   streaming-ingest staging ran (``fetch`` /
                       ``decompress`` spans, artifact/stream.py)
                       with zero device overlap — the same
                       pipelined-vs-serialized rule as uploads: a
                       fetch concurrent with device compute is
                       excluded from this cause entirely
``host_pack_bound``    the host was producing the next batch
                       (``pack`` / ``analyze`` / ``join`` /
                       ``memo_lookup`` / ``layer_analyze`` /
                       ``delta_rematch`` spans)
``collect_bound``      the host was consuming the previous batch
                       (``decode`` / ``report`` / ``finish`` /
                       ``memo_store`` spans)
``slot_wait``          the dispatch ring was full — the executor
                       parked waiting for the drain thread to free
                       a slot (runtime/ring.py); the pipeline is
                       collection-gated, not work-starved
``dispatch_gap``       work was admitted — an open dispatch window
                       (``device`` span) or queued work
                       (``queue_wait`` / ``coalesce``) — but no
                       tracked host phase covers the instant: pure
                       dispatch-path overhead (dedup, rank-space
                       build, result fan-out, Python glue)
``queue_empty``        no request was open at all — the scanner was
                       genuinely idle
``unknown``            a request was open but nothing tracked was
                       running (the honesty bucket; the bench gates
                       it below 5% of idle)
==================  =================================================

Causes can overlap (the host packs batch N+1 while requests queue);
each idle instant goes to the HIGHEST-priority overlapping cause, in
the order above — so the attribution is a partition: the per-cause
seconds always sum to the idle wall exactly, with no overlap and no
negative gap (property-tested in tests/test_obs_timeline.py).

Device **busy** is the union of the actual kernel-execution spans
(``device_compute``, ``dfa_scan``) — NOT the scheduler's per-request
``device`` dispatch windows, which bracket host packing and decode
too; those windows are what ``dispatch_gap`` is measured against.

Clock discipline: every timestamp here is ``time.monotonic`` (the
spans' ``start_mono``/``end_mono``). Wall clock is labels-only
throughout ``obs/`` — a wall step (NTP slew, leap smear) mid-batch
must not move a single attributed microsecond; a lint test enforces
that no ``time.time()`` arithmetic exists in this package.
"""

from __future__ import annotations

# span names that mean the device itself was executing
DEVICE_BUSY = frozenset({"device_compute", "dfa_scan"})

# cause -> the span names whose coverage attributes an idle instant
# to it, in PRIORITY order (first match wins inside a gap)
CAUSE_SPANS = (
    ("upload_serialized", frozenset({"h2d_upload", "db_upload",
                                     "dfa_upload"})),
    # streaming-ingest staging (artifact/stream.py): registry blob
    # fetch + bounded inflate. Same overlapped-span rule as uploads —
    # a fetch running while the device computes is pipelined staging,
    # excluded from this cause entirely
    ("fetch_serialized", frozenset({"fetch", "decompress"})),
    # memo_lookup (hit/miss partition) and delta_rematch (hot-swap
    # migration) are host work that gates the next dispatch;
    # memo_store is finish-side bookkeeping (trivy_tpu.memo);
    # layer_analyze is the per-layer walk+analyzer stage of the
    # streaming pipeline (a sub-phase of analyze)
    ("host_pack_bound", frozenset({"pack", "analyze", "join",
                                   "memo_lookup", "layer_analyze",
                                   "delta_rematch"})),
    ("collect_bound", frozenset({"decode", "verify", "report",
                                 "finish", "memo_store"})),
    # ring-full stalls of the async slot runtime (runtime/ring.py):
    # below collect_bound (a full ring usually IS the collect side
    # running behind) but above the catch-all dispatch_gap
    ("slot_wait", frozenset({"slot_wait"})),
    ("dispatch_gap", frozenset({"device", "queue_wait",
                                "coalesce"})),
)

# upload spans get the overlapped-upload treatment (see the table
# above): only spans in this set that never ran concurrently with a
# busy interval count toward upload_serialized
_UPLOAD_SPANS = CAUSE_SPANS[0][1]

# causes whose spans are pipelined staging when they overlap device
# compute — only the zero-busy-overlap spans keep their cause
# priority (upload_serialized since PR 11, fetch_serialized since
# the streaming-ingest PR)
_SERIALIZED_ONLY_CAUSES = frozenset({"upload_serialized",
                                     "fetch_serialized"})

# any open root ("scan") span means the scanner had work somewhere;
# idle not explained above becomes unknown instead of queue_empty
_ROOT = "scan"

CAUSES = tuple(c for c, _ in CAUSE_SPANS) + ("queue_empty",
                                             "unknown")


def _merge(intervals: list) -> list:
    """Sorted union of (start, end) intervals; empty/negative
    intervals dropped."""
    ivs = sorted((s, e) for s, e in intervals if e > s)
    out: list = []
    for s, e in ivs:
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _complement(intervals: list, lo: float, hi: float) -> list:
    """[lo, hi] minus the (merged) intervals."""
    out = []
    cur = lo
    for s, e in intervals:
        s, e = max(s, lo), min(e, hi)
        if e <= s:
            continue
        if s > cur:
            out.append((cur, s))
        cur = max(cur, e)
    if hi > cur:
        out.append((cur, hi))
    return out


def _clip(intervals: list, lo: float, hi: float) -> list:
    out = []
    for s, e in intervals:
        s, e = max(s, lo), min(e, hi)
        if e > s:
            out.append((s, e))
    return out


def _overlap_s(intervals: list, lo: float, hi: float) -> float:
    return sum(e - s for s, e in _clip(intervals, lo, hi))


class Timeline:
    """One reconstruction over a list of finished spans.

    ``attribute()`` returns the partitioned idle breakdown;
    ``report()`` the JSON-able summary the bench and ``/metrics``
    carry. The input spans only need ``name``, ``start_mono``,
    ``end_mono`` and ``attrs`` — a real ``obs.trace.Span``, or any
    duck-typed stand-in (the property tests use a namedtuple)."""

    def __init__(self, spans: list, window=None):
        done = [s for s in spans
                if getattr(s, "end_mono", None) is not None
                and not getattr(s, "noop", False)]
        self.spans = done
        if window is not None:
            self.t0, self.t1 = float(window[0]), float(window[1])
        elif done:
            self.t0 = min(s.start_mono for s in done)
            self.t1 = max(s.end_mono for s in done)
        else:
            self.t0 = self.t1 = 0.0
        by_name: dict = {}
        for s in done:
            by_name.setdefault(s.name, []).append(
                (s.start_mono, s.end_mono))
        self._busy = _merge([iv for n in DEVICE_BUSY
                             for iv in by_name.get(n, ())])
        self._cause_ivs = [
            (cause,
             _merge(self._serialized_only(
                 [iv for n in names for iv in by_name.get(n, ())]))
             if cause in _SERIALIZED_ONLY_CAUSES else
             _merge([iv for n in names
                     for iv in by_name.get(n, ())]))
            for cause, names in CAUSE_SPANS]
        self._open = _merge(by_name.get(_ROOT, []))
        # batch ids: gaps are attached to the NEXT busy interval's
        # covering dispatch span, so "why did batch 17 start late"
        # is answerable per batch
        self._batch_spans = sorted(
            ((s.start_mono, s.end_mono, s.attrs.get("batch"))
             for s in done
             if s.name == "device" and s.attrs.get("batch")
             is not None),
            key=lambda t: t[0])

    def _serialized_only(self, uploads: list) -> list:
        """Overlapped-upload rule: an upload span that ran (with
        positive measure) while the device computed is a PIPELINED
        upload — the double-buffered staging the async runtime
        exists to produce — and must not claim ``upload_serialized``
        priority over the idle instants it happens to cover. Only
        spans with zero busy overlap survive into the cause set; a
        dropped span's idle coverage falls through to the next
        matching cause, so the partition stays exact."""
        return [iv for iv in uploads
                if _overlap_s(self._busy, iv[0], iv[1]) <= 0.0]

    # --- the partition ---

    def attribute(self) -> dict:
        """{cause: seconds} — partitions the idle wall exactly."""
        out = {c: 0.0 for c in CAUSES}
        for lo, hi in self.idle_intervals():
            for cause, dur in self._attribute_gap(lo, hi):
                out[cause] += dur
        return out

    def _attribute_gap(self, lo: float, hi: float) -> list:
        return [(cause, b - a)
                for cause, a, b in self.gap_pieces(lo, hi)]

    def gap_pieces(self, lo: float, hi: float) -> list:
        """Partition one idle gap into positioned (cause, a, b)
        pieces: sweep the elementary sub-intervals between all cause
        boundaries, assigning each to its highest-priority cover.
        The positions let the fleet merge re-split pieces against
        peer busy intervals without breaking the partition."""
        pts = {lo, hi}
        for _, ivs in self._cause_ivs:
            for s, e in _clip(ivs, lo, hi):
                pts.add(s)
                pts.add(e)
        for s, e in _clip(self._open, lo, hi):
            pts.add(s)
            pts.add(e)
        edges = sorted(pts)
        out = []
        for a, b in zip(edges, edges[1:]):
            mid = (a + b) / 2.0
            cause = None
            for name, ivs in self._cause_ivs:
                if any(s <= mid < e for s, e in ivs):
                    cause = name
                    break
            if cause is None:
                cause = "unknown" if any(
                    s <= mid < e for s, e in self._open) \
                    else "queue_empty"
            out.append((cause, a, b))
        return out

    # --- intervals ---

    def busy_intervals(self) -> list:
        return _clip(self._busy, self.t0, self.t1)

    def idle_intervals(self) -> list:
        return _complement(self.busy_intervals(), self.t0, self.t1)

    # --- summaries ---

    @property
    def window_s(self) -> float:
        return max(0.0, self.t1 - self.t0)

    @property
    def busy_s(self) -> float:
        return sum(e - s for s, e in self.busy_intervals())

    @property
    def idle_s(self) -> float:
        return sum(e - s for s, e in self.idle_intervals())

    def per_batch(self) -> list:
        """[{batch, wait_s, attribution}] — each idle gap charged to
        the batch whose dispatch window it delayed (the next busy
        interval's covering ``device`` span). Gaps after the last
        batch land on batch=None."""
        busy = self.busy_intervals()
        out: dict = {}
        for lo, hi in self.idle_intervals():
            nxt = next((s for s, _ in busy if s >= hi), None)
            batch = None
            if nxt is not None:
                for s, e, b in self._batch_spans:
                    if s <= nxt < e:
                        batch = b
                        break
            slot = out.setdefault(batch, {
                "batch": batch, "wait_s": 0.0,
                "attribution": {c: 0.0 for c in CAUSES}})
            slot["wait_s"] += hi - lo
            for cause, dur in self._attribute_gap(lo, hi):
                slot["attribution"][cause] += dur
        return [out[k] for k in sorted(
            out, key=lambda b: (b is None, b))]

    def report(self, per_batch: bool = False) -> dict:
        """The JSON-able breakdown BENCH json and ``/metrics``
        carry. ``coverage`` is the share of idle wall attributed to
        a KNOWN cause (1 - unknown/idle); the bench gates it at
        >= 95% so the taxonomy cannot silently rot."""
        attr = self.attribute()
        idle = self.idle_s
        out = {
            "window_s": round(self.window_s, 6),
            "busy_s": round(self.busy_s, 6),
            "idle_s": round(idle, 6),
            "busy_ratio": round(self.busy_s / self.window_s, 4)
            if self.window_s else 0.0,
            "attribution": {c: round(v, 6)
                            for c, v in attr.items()},
            "coverage": round(1.0 - attr["unknown"] / idle, 4)
            if idle > 0 else 1.0,
            "gaps": len(self.idle_intervals()),
        }
        if per_batch:
            out["per_batch"] = [
                {"batch": b["batch"],
                 "wait_s": round(b["wait_s"], 6),
                 "attribution": {c: round(v, 6)
                                 for c, v in
                                 b["attribution"].items() if v}}
                for b in self.per_batch()]
        return out


def from_recorder(recorder, window=None) -> Timeline:
    """Timeline over every span in the flight-recorder ring — the
    fleet-run entry the bench uses (a fleet's traces all complete
    into the ring; size the ring to the fleet)."""
    spans = [s for _, trace in recorder.traces() for s in trace]
    return Timeline(spans, window=window)


def from_tracer(tracer, window=None) -> Timeline:
    return from_recorder(tracer.recorder, window=window)


# --- fleet merge (docs/observability.md "Fleet plane") -------------
#
# N processes export their spans (plus their monotonic epoch), the
# coordinator estimates pairwise clock offsets (obs/propagate.py)
# and merges everything onto ONE aligned monotonic axis. Each host
# keeps its own exact partition; the only new cause is
# ``peer_straggler`` — idle a host spent with no local explanation
# while some OTHER host's device was still busy, i.e. waiting on the
# slowest shard. It is carved out of queue_empty/unknown by
# re-splitting those pieces against the union of peer busy
# intervals, so per-host sum(causes) == idle still holds exactly.

FLEET_CAUSES = CAUSES + ("peer_straggler",)

# pieces eligible for peer_straggler reattribution: causes with a
# LOCAL explanation (uploads, host phases, ring stalls) keep their
# attribution even while a peer lags — only "nothing local was
# happening" time can be the fault of the slowest shard
_PEER_ELIGIBLE = frozenset({"queue_empty", "unknown"})


class SpanLite:
    """Deserialized exported span — duck-types the Span fields
    :class:`Timeline` reads, with the host's estimated clock offset
    already applied to both timestamps."""

    noop = False
    __slots__ = ("name", "trace_id", "span_id", "parent_id",
                 "start_mono", "end_mono", "status", "attrs",
                 "is_root")

    def __init__(self, doc: dict, offset_s: float = 0.0):
        self.name = str(doc.get("name") or "")
        self.trace_id = str(doc.get("trace_id") or "")
        self.span_id = str(doc.get("span_id") or "")
        self.parent_id = doc.get("parent_id") or None
        self.start_mono = float(doc.get("start_mono") or 0.0) \
            + offset_s
        end = doc.get("end_mono")
        self.end_mono = None if end is None \
            else float(end) + offset_s
        self.status = str(doc.get("status") or "ok")
        attrs = doc.get("attrs")
        self.attrs = dict(attrs) if isinstance(attrs, dict) else {}
        self.is_root = bool(doc.get("is_root",
                                    self.parent_id is None))


def export_spans(spans: list, process: str = "",
                 epoch_mono: float = 0.0) -> dict:
    """JSON-able export of finished spans + the process's monotonic
    epoch — the unit the simhost output file and the federate
    snapshot carry. Attrs are filtered to JSON scalars."""
    out = []
    for s in spans:
        if getattr(s, "end_mono", None) is None \
                or getattr(s, "noop", False):
            continue
        out.append({
            "name": s.name,
            "trace_id": s.trace_id,
            "span_id": s.span_id,
            "parent_id": s.parent_id,
            "start_mono": s.start_mono,
            "end_mono": s.end_mono,
            "status": getattr(s, "status", "ok"),
            "is_root": bool(getattr(s, "is_root",
                                    s.parent_id is None)),
            "attrs": {k: v for k, v in
                      getattr(s, "attrs", {}).items()
                      if isinstance(v, (str, int, float, bool))},
        })
    return {"process": str(process),
            "epoch_mono": float(epoch_mono),
            "spans": out}


def export_tracer(tracer, process: str = "") -> dict:
    """Export every completed trace in a tracer's recorder ring."""
    spans = [s for _, trace in tracer.recorder.traces()
             for s in trace]
    return export_spans(spans, process=process,
                        epoch_mono=tracer.epoch_mono)


def load_export(doc: dict, offset_s: float = 0.0) -> list:
    """Hydrate one export back into Timeline-compatible spans, with
    ``offset_s`` (local ≈ remote + offset, from
    :func:`obs.propagate.estimate_offset`) applied."""
    return [SpanLite(d, offset_s=offset_s)
            for d in (doc.get("spans") or [])]


class MergedTimeline:
    """N per-process exports on one aligned monotonic axis.

    ``exports`` are :func:`export_spans` documents; ``offsets`` are
    the per-export clock offsets mapping each host's monotonic
    timestamps onto the coordinator's axis (local ≈ remote +
    offset). The fleet window defaults to the union extent of all
    hosts' spans so trailing idle on fast hosts — the straggler
    signal — stays in frame."""

    def __init__(self, exports: list, offsets=None, window=None):
        offsets = list(offsets) if offsets is not None \
            else [0.0] * len(exports)
        if len(offsets) != len(exports):
            raise ValueError("one offset per export required")
        self.hosts = []
        for i, (doc, off) in enumerate(zip(exports, offsets)):
            name = str(doc.get("process") or f"host{i}")
            self.hosts.append((name, load_export(doc,
                                                 offset_s=off)))
        extents = [Timeline(spans) for _, spans in self.hosts]
        with_spans = [t for t in extents if t.spans]
        if window is not None:
            self.t0, self.t1 = float(window[0]), float(window[1])
        elif with_spans:
            self.t0 = min(t.t0 for t in with_spans)
            self.t1 = max(t.t1 for t in with_spans)
        else:
            self.t0 = self.t1 = 0.0
        self.timelines = [
            (name, Timeline(spans, window=(self.t0, self.t1)))
            for name, spans in self.hosts]

    @property
    def window_s(self) -> float:
        return max(0.0, self.t1 - self.t0)

    def per_host(self) -> list:
        """[{process, busy_s, idle_s, attribution, coverage,
        last_busy_end_s}] — each host's exact partition over the
        COMMON fleet window, with peer_straggler carved out of
        unexplained idle covered by some other host's busy time."""
        busy_by_host = [tl.busy_intervals()
                        for _, tl in self.timelines]
        out = []
        for i, (name, tl) in enumerate(self.timelines):
            peers_busy = _merge([iv
                                 for j, ivs in
                                 enumerate(busy_by_host)
                                 if j != i for iv in ivs])
            attr = {c: 0.0 for c in FLEET_CAUSES}
            for lo, hi in tl.idle_intervals():
                for cause, a, b in tl.gap_pieces(lo, hi):
                    if cause in _PEER_ELIGIBLE:
                        covered = _overlap_s(peers_busy, a, b)
                        attr["peer_straggler"] += covered
                        attr[cause] += (b - a) - covered
                    else:
                        attr[cause] += b - a
            busy = tl.busy_s
            idle = tl.idle_s
            last = max((e for _, e in busy_by_host[i]),
                       default=self.t0)
            out.append({
                "process": name,
                "busy_s": round(busy, 6),
                "idle_s": round(idle, 6),
                "attribution": {c: round(v, 6)
                                for c, v in attr.items()},
                "coverage": round(1.0 - attr["unknown"] / idle, 4)
                if idle > 0 else 1.0,
                "last_busy_end_s": round(last - self.t0, 6),
            })
        return out

    def report(self) -> dict:
        """Fleet summary + the per-host burn-down list (hosts sorted
        by when their device went quiet, latest first — the ROADMAP
        item-1 view of who the straggler was)."""
        hosts = self.per_host()
        idle = sum(h["idle_s"] for h in hosts)
        unknown = sum(h["attribution"]["unknown"] for h in hosts)
        fleet_attr = {c: round(sum(h["attribution"][c]
                                   for h in hosts), 6)
                      for c in FLEET_CAUSES}
        return {
            "window_s": round(self.window_s, 6),
            "hosts": hosts,
            "fleet": {
                "busy_s": round(sum(h["busy_s"] for h in hosts),
                                6),
                "idle_s": round(idle, 6),
                "attribution": fleet_attr,
                "coverage": round(1.0 - unknown / idle, 4)
                if idle > 0 else 1.0,
            },
            "burn_down": [
                {"process": h["process"],
                 "finished_at_s": h["last_busy_end_s"],
                 "busy_s": h["busy_s"],
                 "peer_straggler_s":
                     h["attribution"]["peer_straggler"]}
                for h in sorted(hosts,
                                key=lambda h:
                                -h["last_busy_end_s"])],
        }
