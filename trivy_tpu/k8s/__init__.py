"""Kubernetes scanning (reference: pkg/k8s/{commands,scanner,report}
+ the external trivy-kubernetes enumerator).

The reference enumerates cluster artifacts (workload images + raw
manifests) through the Kubernetes API and loops them SEQUENTIALLY
through the artifact runner (scanner.go:58-78). Here the enumerator
is a seam: ``ManifestClient`` walks exported/declared manifests (this
environment has no cluster API; a live client plugs into the same
``artifacts()`` contract), and the scan fans the whole artifact fleet
through ``BatchScanRunner`` — one sieve dispatch and one interval
dispatch for every image in the cluster (SURVEY §2.6's fleet case).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Optional

from ..runtime import BatchScanRunner
from ..types import Metadata, Report
from ..utils import get_logger

log = get_logger("k8s")

try:
    import yaml as yaml_mod
except ImportError:              # pragma: no cover
    yaml_mod = None

# workload kinds that carry pod specs (trivy-kubernetes artifacts.go)
WORKLOAD_KINDS = ("Pod", "Deployment", "StatefulSet", "DaemonSet",
                  "ReplicaSet", "ReplicationController", "Job",
                  "CronJob")


@dataclass
class Artifact:
    """One cluster object (ref trivy-kubernetes artifacts.Artifact)."""

    kind: str = ""
    name: str = ""
    namespace: str = ""
    images: list = field(default_factory=list)
    raw: dict = field(default_factory=dict)


@dataclass
class Resource:
    """Per-object findings (ref pkg/k8s/report/report.go:58-69)."""

    namespace: str = ""
    kind: str = ""
    name: str = ""
    results: list = field(default_factory=list)
    error: str = ""

    def to_dict(self) -> dict:
        d = {"Kind": self.kind, "Name": self.name}
        if self.namespace:
            d["Namespace"] = self.namespace
        if self.results:
            d["Results"] = [r.to_dict() for r in self.results]
        if self.error:
            d["Error"] = self.error
        return d


@dataclass
class K8sReport:
    """ref report.go:42-48."""

    cluster_name: str = ""
    vulnerabilities: list = field(default_factory=list)
    misconfigurations: list = field(default_factory=list)

    def to_dict(self) -> dict:
        d = {"ClusterName": self.cluster_name}
        if self.vulnerabilities:
            d["Vulnerabilities"] = [r.to_dict()
                                    for r in self.vulnerabilities]
        if self.misconfigurations:
            d["Misconfigurations"] = [
                r.to_dict() for r in self.misconfigurations]
        return d


def _pod_spec(doc: dict) -> dict:
    spec = doc.get("spec") or {}
    if doc.get("kind") == "CronJob":
        spec = ((spec.get("jobTemplate") or {}).get("spec") or {})
    return ((spec.get("template") or {}).get("spec")) or spec


def _images(doc: dict) -> list:
    pod = _pod_spec(doc)
    return [c.get("image", "")
            for key in ("initContainers", "containers")
            for c in pod.get(key) or [] if c.get("image")]


def images_from_object(doc: dict) -> list:
    """Pod-spec image references of one workload object — bare Pods
    and every templated WORKLOAD_KIND alike. Shared by the cluster
    scanner and the admission webhook (watch/admission.py), so both
    agree on what "the images of this object" means."""
    if not isinstance(doc, dict):
        return []
    return _images(doc)


class ManifestClient:
    """Artifact enumerator over manifest files — the stand-in for the
    live-cluster client (same ``artifacts()`` contract)."""

    def __init__(self, path: str):
        self.path = path
        self.cluster_name = os.path.basename(
            path.rstrip("/")) or path

    def _files(self):
        if os.path.isfile(self.path):
            yield self.path
            return
        for dirpath, _, names in os.walk(self.path):
            for name in sorted(names):
                if name.endswith((".yaml", ".yml", ".json")):
                    yield os.path.join(dirpath, name)

    def artifacts(self) -> list:
        out = []
        for fp in self._files():
            try:
                with open(fp, "rb") as f:
                    docs = list(yaml_mod.safe_load_all(
                        f.read().decode("utf-8", "replace")))
            except (OSError, yaml_mod.YAMLError) as e:
                log.warning("skipping %s: %s", fp, e)
                continue
            for doc in docs:
                if not isinstance(doc, dict) or "kind" not in doc:
                    continue
                meta = doc.get("metadata") or {}
                out.append(Artifact(
                    kind=doc.get("kind", ""),
                    name=meta.get("name", ""),
                    namespace=meta.get("namespace", ""),
                    images=_images(doc)
                    if doc.get("kind") in WORKLOAD_KINDS else [],
                    raw=doc))
        return out


def _sanitize_ref(ref: str) -> str:
    return re.sub(r"[/:@]", "_", ref)


def resolve_image_ref(images_dir: str, ref: str) -> Optional[str]:
    """image ref → local tarball named ``<ref with /:@ as _>.tar``
    (the zero-egress stand-in for a registry pull). ONE copy of the
    naming contract — the cluster scanner and the watch/admission
    resolvers (watch/source.dir_resolver) both call it."""
    if not images_dir:
        return None
    for cand in (f"{_sanitize_ref(ref)}.tar",
                 f"{_sanitize_ref(ref.split('/')[-1])}.tar"):
        path = os.path.join(images_dir, cand)
        if os.path.exists(path):
            return path
    return None


class K8sScanner:
    """ref pkg/k8s/scanner/scanner.go:30-78, with the sequential
    artifact loop replaced by one fleet batch over every image."""

    def __init__(self, store=None, backend: str = "tpu",
                 images_dir: str = "", security_checks=None):
        self.store = store
        self.backend = backend
        self.images_dir = images_dir
        self.security_checks = security_checks or ["vuln", "config"]

    def scan(self, client) -> K8sReport:
        artifacts = client.artifacts()
        report = K8sReport(cluster_name=client.cluster_name)

        if "config" in self.security_checks or \
                "rbac" in self.security_checks:
            report.misconfigurations = [
                self._scan_misconfig(a) for a in artifacts]

        if "vuln" in self.security_checks or \
                "secret" in self.security_checks:
            report.vulnerabilities = self._scan_images(artifacts)
        return report

    # -- misconfigs: the manifests themselves --

    def _scan_misconfig(self, artifact: Artifact) -> Resource:
        from ..misconf import scan_config_files
        from ..scan.local import _to_detected_misconf
        from ..types import ConfigFile, Result
        from ..types.report import ResultClass

        raw = yaml_mod.safe_dump(artifact.raw).encode()
        results = []
        for mc in scan_config_files([ConfigFile(
                type="yaml",
                file_path=f"{artifact.namespace or 'default'}/"
                          f"{artifact.kind}/{artifact.name}",
                content=raw)]):
            detected = [
                _to_detected_misconf(f, "CRITICAL", "FAIL", mc.layer)
                for f in mc.failures]
            detected += [
                _to_detected_misconf(s, "UNKNOWN", "PASS", mc.layer)
                for s in mc.successes]
            results.append(Result(
                target=mc.file_path, class_=ResultClass.CONFIG,
                type=mc.file_type, misconfigurations=detected))
        return Resource(namespace=artifact.namespace,
                        kind=artifact.kind, name=artifact.name,
                        results=results)

    # -- vulns: every image in the cluster, ONE batch --

    def _scan_images(self, artifacts: list) -> list:
        owners: list = []       # (artifact, ref, path|None)
        paths: list = []
        for a in artifacts:
            for ref in a.images:
                path = self._resolve(ref)
                owners.append((a, ref, path))
                if path and path not in paths:
                    paths.append(path)   # shared images scan once
        if not paths:
            return [Resource(namespace=a.namespace, kind=a.kind,
                             name=a.name,
                             error=f"image not resolvable: {ref}")
                    for a, ref, path in owners if path is None]

        runner = BatchScanRunner(store=self.store,
                                 backend=self.backend)
        options = None
        from ..types import ScanOptions
        options = ScanOptions(
            security_checks=[c for c in self.security_checks
                             if c in ("vuln", "secret")],
            backend=self.backend)
        batch = runner.scan_paths(paths, options)
        by_path = {p: r for p, r in zip(paths, batch)}

        out = []
        for a, ref, path in owners:
            if path is None:
                out.append(Resource(
                    namespace=a.namespace, kind=a.kind, name=a.name,
                    error=f"image not resolvable: {ref}"))
                continue
            res = by_path[path]
            if res.error:
                out.append(Resource(
                    namespace=a.namespace, kind=a.kind, name=a.name,
                    error=res.error))
            else:
                out.append(Resource(
                    namespace=a.namespace, kind=a.kind, name=a.name,
                    results=res.report.results))
        return out

    def _resolve(self, ref: str) -> Optional[str]:
        return resolve_image_ref(self.images_dir, ref)
