"""K8s report rendering (reference: pkg/k8s/report/{summary,table,
json}.go) — summary counts per resource, or the full per-resource
results."""

from __future__ import annotations

import json

from ..report.writer import _table

_SEVS = ("CRITICAL", "HIGH", "MEDIUM", "LOW", "UNKNOWN")


def _counts(results, attr) -> dict:
    counts = {s: 0 for s in _SEVS}
    for r in results:
        for item in getattr(r, attr, []):
            sev = getattr(item, "severity", "UNKNOWN")
            if attr == "misconfigurations" and \
                    getattr(item, "status", "") != "FAIL":
                continue
            counts[sev if sev in counts else "UNKNOWN"] += 1
    return counts


def render_summary(report) -> str:
    lines = [f"Summary Report for {report.cluster_name}", ""]
    rows = [("Namespace", "Resource",
             "Vulnerabilities C/H/M/L/U",
             "Misconfigurations C/H/M/L/U")]
    vuln_by_key = {}
    for res in report.vulnerabilities:
        key = (res.namespace, f"{res.kind}/{res.name}")
        vuln_by_key[key] = _counts(res.results, "vulnerabilities")
    misc_by_key = {}
    for res in report.misconfigurations:
        key = (res.namespace, f"{res.kind}/{res.name}")
        misc_by_key[key] = _counts(res.results, "misconfigurations")

    def fmt(c):
        if c is None:
            return "-"
        return "/".join(str(c[s]) for s in _SEVS)

    for key in sorted(set(vuln_by_key) | set(misc_by_key)):
        rows.append((key[0] or "default", key[1],
                     fmt(vuln_by_key.get(key)),
                     fmt(misc_by_key.get(key))))
    if len(rows) == 1:
        return lines[0] + "\nno resources found\n"
    lines.extend(_table(rows))
    lines.append("Severities: C=CRITICAL H=HIGH M=MEDIUM L=LOW "
                 "U=UNKNOWN")
    return "\n".join(lines) + "\n"


def render_all(report) -> str:
    """Full findings per resource via the standard table writer."""
    from ..report.writer import render_table
    from ..types import Metadata, Report
    out = [f"Full Report for {report.cluster_name}"]
    for res in report.misconfigurations + report.vulnerabilities:
        if not res.results and not res.error:
            continue
        if res.error:
            out.append(f"\n{res.kind}/{res.name}: error: "
                       f"{res.error}")
            continue
        body = render_table(Report(results=res.results))
        if body.strip():
            out.append(body.rstrip("\n"))
    return "\n".join(out) + "\n"


def write_k8s_report(report, fmt: str = "table",
                     mode: str = "summary", output=None) -> None:
    import sys
    out = output or sys.stdout
    if fmt == "json":
        json.dump(report.to_dict(), out, indent=2)
        out.write("\n")
    elif mode == "all":
        out.write(render_all(report))
    else:
        out.write(render_summary(report))


def k8s_failed(report) -> bool:
    for res in report.vulnerabilities + report.misconfigurations:
        for r in res.results:
            if r.failed():
                return True
    return False
