"""Unpackaged-executable post-handler
(reference: pkg/fanal/handler/unpackaged/unpackaged.go).

Executables that no package manager owns get their sha256 looked up
in the Rekor transparency log; a CycloneDX SBOM attestation found
there merges into the blob, so a bare Go/Rust binary dropped into an
image still reports its dependency packages. The binary analyzers
record digests as ``executable-digest`` custom resources; this
handler consumes them, queries Rekor (when ``TRIVY_REKOR_URL`` or the
artifact option configures it — zero-egress default is off), and
folds discovered applications in.
"""

from __future__ import annotations

import os

from ..types.artifact import DIGEST_RESOURCE_TYPE as DIGEST_RESOURCE
from ..utils import get_logger
from .handler import PostHandler, register_post_handler

log = get_logger("handler.unpackaged")


@register_post_handler
class UnpackagedHandler(PostHandler):
    type = "unpackaged"
    version = 1
    priority = 50        # after the system-file filter

    def __init__(self):
        self._client = None
        self._client_url = ""

    def _rekor(self):
        url = os.environ.get("TRIVY_REKOR_URL", "")
        if not url:
            return None
        if self._client is None or self._client_url != url:
            from ..rekor import Client
            self._client = Client(url)
            self._client_url = url
        return self._client

    def handle(self, blob) -> None:
        digests = [(cr.file_path, cr.data.get("digest", ""))
                   for cr in blob.custom_resources
                   if cr.type == DIGEST_RESOURCE
                   and isinstance(cr.data, dict)]
        # digests are handler plumbing, never report output
        blob.custom_resources = [
            cr for cr in blob.custom_resources
            if cr.type != DIGEST_RESOURCE]
        if not digests:
            return
        client = self._rekor()
        if client is None:
            return
        from ..rekor import RekorError, discover_sbom
        system = {f.lstrip("/") for f in blob.system_files}
        for path, digest in digests:
            if not digest or path.lstrip("/") in system:
                continue
            try:
                decoded = discover_sbom(client, digest)
            except RekorError as e:
                log.debug("rekor lookup failed for %s: %s", path, e)
                continue
            if decoded is None:
                continue
            log.info("rekor SBOM attestation found for %s", path)
            for app in decoded.applications:
                app.file_path = app.file_path or path
                blob.applications.append(app)
            blob.package_infos.extend(decoded.packages)
