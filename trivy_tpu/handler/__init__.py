"""Post-handler framework (reference: pkg/fanal/handler/handler.go).

PostHandlers run per blob after analysis, in descending priority
order; their versions feed cache keys alongside analyzer versions so
a handler change invalidates cached blobs.
"""

from .handler import (PostHandler, handler_versions, post_handle,
                      register_post_handler)
from . import gomod as _gomod  # noqa: F401  (registers on import)
from . import misconf as _misconf  # noqa: F401
from . import sysfile as _sysfile  # noqa: F401
from . import unpackaged as _unpackaged  # noqa: F401

__all__ = ["PostHandler", "register_post_handler", "post_handle",
           "handler_versions"]
