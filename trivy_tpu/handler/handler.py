"""Registry + dispatch for blob post-handlers.

Reference: pkg/fanal/handler/handler.go:21-72 — handlers are
priority-sorted (higher first) and mutate the BlobInfo in place.
"""

from __future__ import annotations

_REGISTRY: list = []


class PostHandler:
    """Subclasses set ``type``/``version``/``priority`` and implement
    ``handle(blob)`` mutating the BlobInfo."""

    type: str = ""
    version: int = 1
    priority: int = 0

    def handle(self, blob) -> None:
        raise NotImplementedError


def register_post_handler(h) -> "PostHandler":
    _REGISTRY.append(h() if isinstance(h, type) else h)
    _REGISTRY.sort(key=lambda x: -x.priority)
    return h


def registered_handlers(disabled=None) -> list:
    disabled = set(disabled or [])
    return [h for h in _REGISTRY if h.type not in disabled]


def handler_versions(disabled=None) -> dict:
    return {h.type: h.version for h in registered_handlers(disabled)}


def post_handle(blob, disabled=None) -> None:
    for h in registered_handlers(disabled):
        h.handle(blob)
