"""System-file filtering post-handler
(reference: pkg/fanal/handler/sysfile/filter.go).

Language packages whose files were installed by the OS package
manager (rpm/dpkg/apk installed-file lists) are dropped from the
blob's applications: the OS package database is authoritative for
their versions, and double-reporting produces false positives.
"""

from __future__ import annotations

from .handler import PostHandler, register_post_handler

# Distroless strips dpkg file lists; these are always OS-managed
# (filter.go defaultSystemFiles — factual constants)
DEFAULT_SYSTEM_FILES = (
    "usr/lib/python2.7/argparse.egg-info",
    "usr/lib/python2.7/lib-dynload/Python-2.7.egg-info",
    "usr/lib/python2.7/wsgiref.egg-info",
)

AFFECTED_TYPES = ("gemspec", "python-pkg", "node-pkg", "gobinary")


@register_post_handler
class SystemFileFilterHandler(PostHandler):
    type = "system-file-filter"
    version = 1
    priority = 100       # runs alongside misconf, before unpackaged

    def handle(self, blob) -> None:
        system = {f.lstrip("/") for f in blob.system_files
                  if f.lstrip("/")}
        system.update(DEFAULT_SYSTEM_FILES)
        apps = []
        for app in blob.applications:
            if app.file_path in system and \
                    app.type in AFFECTED_TYPES:
                continue
            if app.type in AFFECTED_TYPES:
                app.libraries = [
                    lib for lib in app.libraries
                    if lib.file_path.lstrip("/") not in system]
                if not app.libraries:
                    continue
            apps.append(app)
        blob.applications = apps
