"""Misconfiguration post-handler
(reference: pkg/fanal/handler/misconf/misconf.go Handle:250-324).

Runs after analysis on each blob: evaluates the built-in policies
over the collected ConfigFiles and writes the Misconfigurations into
the BlobInfo. The raw ConfigFiles are dropped afterwards, like the
reference clears them once defsec has run.
"""

from __future__ import annotations

from ..misconf import scan_config_files
from .handler import PostHandler, register_post_handler


@register_post_handler
class MisconfPostHandler(PostHandler):
    type = "misconf"
    version = 1
    priority = 100       # reference: MisconfPostHandlerPriority

    def handle(self, blob) -> None:
        if not blob.config_files:
            return
        blob.misconfigurations = scan_config_files(blob.config_files)
        blob.config_files = []
