"""go.mod / go.sum merge post-handler.

Reference: pkg/fanal/handler/gomod/gomod.go — go.sum applications are
folded into their sibling go.mod application when the go.mod predates
Go 1.17 (detected by the absence of any ``// indirect`` marker, which
only 1.17+ writes), then dropped from the blob.
"""

from __future__ import annotations

import posixpath

from .handler import PostHandler, register_post_handler


def _less_than_go117(app) -> bool:
    return not any(lib.indirect for lib in app.libraries)


def _merge_gosum(gomod_app, gosum_app) -> None:
    uniq = {lib.name: lib for lib in gomod_app.libraries}
    for lib in gosum_app.libraries:
        if lib.name in uniq:
            continue            # go.mod is preferred
        lib.indirect = True     # absent from go.mod => indirect
        uniq[lib.name] = lib
    gomod_app.libraries = list(uniq.values())


@register_post_handler
class GoModMergeHandler(PostHandler):
    type = "gomod-merge"
    version = 1
    priority = 50

    def handle(self, blob) -> None:
        by_path = {a.file_path: a for a in blob.applications
                   if a.type == "gomod"}
        apps = []
        for app in blob.applications:
            if app.type == "gomod":
                d, f = posixpath.split(app.file_path)
                if f == "go.sum":
                    continue
                if f == "go.mod" and _less_than_go117(app):
                    gosum = by_path.get(posixpath.join(d, "go.sum"))
                    if gosum is not None:
                        _merge_gosum(app, gosum)
            apps.append(app)
        blob.applications = apps
