"""Push-event sources for the watch loop (docs/serving.md
"Continuous scanning & admission control").

Three sources share one contract — ``get(timeout) -> PushEvent|None``
plus ``exhausted`` — so the loop never cares where events come from:

* :class:`WebhookSource` — the real one: a bounded queue fed by the
  server's ``POST /registry/notifications`` route with Docker
  Registry v2 notification envelopes (the ``notifications`` webhook a
  registry is configured to POST on every push);
* :class:`SyntheticSource` — a seeded Poisson arrival schedule over a
  fleet of image tarballs, with duplicate-tag bursts, for tests and
  ``bench.py --config watch``;
* :class:`TraceSource` — replays a recorded event list verbatim.

Every event carries a monotonically increasing per-source ``seq``;
the loop acks seqs as events resolve and a :class:`Cursor`
checkpoints the contiguous high-water mark, so a restarted watch
resumes where it left off instead of re-scanning the backlog.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..utils import get_logger
from .metrics import WATCH_METRICS

log = get_logger("watch.source")

# media types that mean "a manifest was pushed" (Docker Registry v2
# notification envelope, registry/notifications/event.go) — blob
# (layer) pushes also arrive and are NOT scan triggers
MANIFEST_MEDIA_TYPES = (
    "application/vnd.docker.distribution.manifest.v2+json",
    "application/vnd.docker.distribution.manifest.list.v2+json",
    "application/vnd.oci.image.manifest.v1+json",
    "application/vnd.oci.image.index.v1+json",
)


@dataclass
class PushEvent:
    """One registry push, normalized. ``digest`` is the dedupe key —
    a tag repushed five times in a burst carries the same digest and
    scans once."""

    digest: str
    ref: str = ""              # repository[:tag] for display/resolve
    path: str = ""             # resolvable scan target (tarball)
    tenant: str = ""
    priority: int = 0
    seq: int = -1              # per-source cursor position
    event_id: str = ""
    # propagated trace context (fleet plane): a traceparent on the
    # notification envelope rides every event it yields, so the scan
    # the watcher submits joins the submitter's trace
    traceparent: str = ""
    ts: float = field(default_factory=time.monotonic)


def parse_notification(body, resolver=None, tenant: str = "",
                       priority: int = 0) -> tuple:
    """Docker Registry v2 notification envelope → ``(events,
    malformed)``. Only manifest *push* actions become events;
    entries missing a digest or repository — or a non-dict envelope —
    count as malformed and are dropped (never raised: a registry
    webhook retries on non-2xx, and a poison notification must not
    wedge the stream)."""
    events, malformed = [], 0
    if not isinstance(body, dict) or \
            not isinstance(body.get("events"), list):
        WATCH_METRICS.inc("malformed")
        return events, 1
    traceparent = str(body.get("traceparent") or "")
    for ev in body["events"]:
        if not isinstance(ev, dict):
            malformed += 1
            continue
        if ev.get("action") != "push":
            continue             # pulls/deletes: ignored, not malformed
        target = ev.get("target") or {}
        media = target.get("mediaType", "")
        if media and media not in MANIFEST_MEDIA_TYPES:
            continue             # blob push: every layer fires one
        repo = target.get("repository")
        digest = target.get("digest")
        if not isinstance(repo, str) or not repo or \
                not isinstance(digest, str) or not digest:
            malformed += 1
            continue
        tag = target.get("tag") or ""
        ref = f"{repo}:{tag}" if tag else repo
        path = resolver(ref, digest) if resolver is not None else ""
        events.append(PushEvent(digest=digest, ref=ref,
                                path=path or "", tenant=tenant,
                                priority=priority,
                                event_id=str(ev.get("id") or ""),
                                traceparent=traceparent))
    if malformed:
        WATCH_METRICS.inc("malformed", malformed)
    return events, malformed


def dir_resolver(images_dir: str):
    """``--images-dir`` resolver: image ref → local tarball via the
    ``k8s --images-dir`` naming contract (one shared helper, no
    second copy to drift)."""
    from ..k8s import resolve_image_ref

    def resolve(ref: str, digest: str = ""):
        return resolve_image_ref(images_dir, ref)

    return resolve


class EventSource:
    """Base contract. ``get`` may raise on transport failure — the
    loop survives via the shared backoff policy."""

    def get(self, timeout: float = 0.05):
        raise NotImplementedError

    def take_dropped(self) -> tuple:
        """Seqs of events this source discarded before delivery
        (webhook overflow). The loop acks them so the checkpoint
        cursor never freezes on a hole no event will ever fill."""
        return ()

    @property
    def exhausted(self) -> bool:
        return False

    def resume_from(self, position: int) -> None:
        """Skip events with ``seq <= position`` (checkpoint resume).
        Non-replayable sources (webhook) only fast-forward their seq
        counter so cursor positions stay monotonic across restarts."""

    def close(self) -> None:
        pass


class WebhookSource(EventSource):
    """Bounded thread-safe queue fed by the server's
    ``POST /registry/notifications`` route. A full queue drops the
    oldest events (the registry redelivers on its own schedule;
    unbounded buffering is how a push storm becomes an OOM)."""

    def __init__(self, resolver=None, maxsize: int = 4096,
                 tenant: str = "", priority: int = 0):
        self.resolver = resolver
        self.tenant = tenant
        self.priority = priority
        self._q: deque = deque(maxlen=max(16, maxsize))
        self._cv = threading.Condition()
        self._seq = 0
        self._closed = False
        self.dropped = 0
        self._dropped_seqs: list = []

    def push_notification(self, body) -> dict:
        """Ingest one notification envelope (the HTTP route calls
        this). Returns ``{"accepted": n, "malformed": m}`` — always,
        so the webhook answers 200 and the registry never retries a
        poison envelope forever."""
        events, malformed = parse_notification(
            body, resolver=self.resolver, tenant=self.tenant,
            priority=self.priority)
        with self._cv:
            for ev in events:
                ev.seq = self._seq
                self._seq += 1
                if len(self._q) == self._q.maxlen:
                    # overflow evicts the OLDEST undelivered event;
                    # its seq is remembered so the loop can still
                    # ack it — otherwise the checkpoint cursor would
                    # freeze on the hole forever
                    self.dropped += 1
                    self._dropped_seqs.append(self._q[0].seq)
                self._q.append(ev)
            self._cv.notify_all()
        return {"accepted": len(events), "malformed": malformed,
                "dropped": self.dropped}

    def push_events(self, events) -> int:
        """Enqueue already-built :class:`PushEvent`\\ s (the impact
        push stream's entry point) with the same seq-assignment and
        bounded-overflow semantics as webhook notifications — a swap
        storm buffers bounded and folds into the loop's debounce like
        any other burst."""
        events = list(events)
        with self._cv:
            for ev in events:
                ev.seq = self._seq
                self._seq += 1
                if len(self._q) == self._q.maxlen:
                    self.dropped += 1
                    self._dropped_seqs.append(self._q[0].seq)
                self._q.append(ev)
            self._cv.notify_all()
        return len(events)

    def get(self, timeout: float = 0.05):
        with self._cv:
            if not self._q:
                self._cv.wait(timeout)
            return self._q.popleft() if self._q else None

    def take_dropped(self) -> tuple:
        with self._cv:
            out, self._dropped_seqs = tuple(self._dropped_seqs), []
            return out

    @property
    def exhausted(self) -> bool:
        with self._cv:
            return self._closed and not self._q

    def resume_from(self, position: int) -> None:
        with self._cv:
            self._seq = max(self._seq, position + 1)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


class TraceSource(EventSource):
    """Replays a recorded list of :class:`PushEvent` in order.
    Deterministic and unpaced — the unit-test workhorse."""

    def __init__(self, events: list):
        self._events = list(events)
        for i, ev in enumerate(self._events):
            if ev.seq < 0:
                ev.seq = i
        self._i = 0

    def get(self, timeout: float = 0.05):
        if self._i >= len(self._events):
            return None
        ev = self._events[self._i]
        self._i += 1
        ev.ts = time.monotonic()
        return ev

    @property
    def exhausted(self) -> bool:
        return self._i >= len(self._events)

    def resume_from(self, position: int) -> None:
        while self._i < len(self._events) and \
                self._events[self._i].seq <= position:
            self._i += 1


class SyntheticSource(EventSource):
    """Seeded open-loop arrival schedule over a fleet of tarballs:
    Poisson gaps at ``rate`` events/s, with ``dup_rate`` of events
    followed by a burst of duplicate pushes of the same digest (the
    tag-repush pattern debounce exists for). ``paced=False`` replays
    the same schedule as fast as the loop pulls — bench arms pace,
    unit tests don't."""

    def __init__(self, paths: list, rate: float = 10.0,
                 n_events: int = 0, seed: int = 20260804,
                 dup_rate: float = 0.25, burst: int = 4,
                 paced: bool = True, tenant: str = "",
                 priority: int = 0):
        import hashlib
        import random
        rng = random.Random(seed)
        n = n_events or len(paths)
        sched: list = []           # (due offset, PushEvent)
        t = 0.0
        seq = 0
        while len(sched) < n:
            t += rng.expovariate(max(rate, 1e-6))
            path = paths[rng.randrange(len(paths))]
            digest = "sha256:" + hashlib.sha256(
                path.encode()).hexdigest()
            ref = os.path.basename(path)
            k = 1
            if rng.random() < dup_rate:
                k += rng.randrange(1, max(2, burst))
            for j in range(k):
                if len(sched) >= n:
                    break
                sched.append((t + j * 0.001, PushEvent(
                    digest=digest, ref=ref, path=path,
                    tenant=tenant, priority=priority, seq=seq,
                    event_id=f"synth-{seq}")))
                seq += 1
        self._sched = sched
        self._i = 0
        self.paced = paced
        self._t0 = None

    def get(self, timeout: float = 0.05):
        if self._i >= len(self._sched):
            return None
        if self._t0 is None:
            self._t0 = time.monotonic()
        due, ev = self._sched[self._i]
        if self.paced:
            now = time.monotonic() - self._t0
            if due > now:
                time.sleep(min(timeout, due - now))
                now = time.monotonic() - self._t0
                if due > now:
                    return None
        self._i += 1
        ev.ts = time.monotonic()
        return ev

    @property
    def exhausted(self) -> bool:
        return self._i >= len(self._sched)

    def resume_from(self, position: int) -> None:
        while self._i < len(self._sched) and \
                self._sched[self._i][1].seq <= position:
            self._i += 1


def make_event_storm(spec, paths: list) -> list:
    """The ``event-storm`` fault scenario's payload: a seeded burst
    of ``storm_events`` raw notification envelopes over
    ``storm_digests`` distinct digests (duplicate-tag repushes
    included), with ``storm_malformed`` malformed envelopes
    interleaved. The harness (tests, bench) feeds these through
    ``WebhookSource.push_notification`` — debounce must collapse the
    duplicates, malformed envelopes must be counted and dropped, and
    scheduler backpressure must shed via the existing 429/503 paths
    without ever crashing the loop."""
    import hashlib
    import random
    rng = random.Random(spec.seed)
    digests = max(1, min(spec.storm_digests or 1, len(paths)))
    chosen = paths[:digests]
    out = []
    malformed_budget = max(0, spec.storm_malformed)
    n = max(1, spec.storm_events)
    malformed_at = set(rng.sample(range(n + malformed_budget),
                                  malformed_budget)) \
        if malformed_budget else set()
    i = ev = 0
    while ev < n or len(out) < n + malformed_budget:
        if i in malformed_at:
            out.append(rng.choice([
                {"events": "not-a-list"},
                {"events": [{"action": "push", "target": {}}]},
                {"events": [{"action": "push",
                             "target": {"repository": "r"}}]},
                ["not", "an", "envelope"],
            ]))
        else:
            if ev >= n:
                i += 1
                continue
            path = chosen[ev % digests]
            digest = "sha256:" + hashlib.sha256(
                path.encode()).hexdigest()
            tag = f"v{rng.randrange(3)}"     # tag churn, same digest
            out.append({"events": [{
                "id": f"storm-{ev}", "action": "push",
                "target": {"mediaType": MANIFEST_MEDIA_TYPES[0],
                           "repository": os.path.basename(path),
                           "tag": tag, "digest": digest,
                           "path": path}}]})
            ev += 1
        i += 1
    return out


def _checkpoint_crc(position: int) -> int:
    """Integrity tag for the checkpoint doc. A torn write or a
    flipped byte in ``position`` can still parse as valid JSON with
    a LARGER int — and a cursor that believes it would *skip unacked
    events* on resume, the one failure mode worse than replay."""
    import zlib
    return zlib.crc32(f"position:{int(position)}".encode())


# out-of-order ack window: seqs acked above a hole the stream never
# fills (e.g. an event lost without a drop record). Past the cap the
# oldest hole is declared abandoned and the cursor advances — a
# bounded replay-on-restart beats an unbounded set (the soak leak
# audit samples this window).
ACK_WINDOW_CAP = 65536


class Cursor:
    """Checkpointed stream position: ``ack(seq)`` as events resolve,
    ``position`` is the highest seq with every seq at or below it
    acked — a restart resumes AFTER it, never re-scanning work that
    already completed. Persistence is atomic (tmp + rename), like
    every other on-disk artifact in this tree, and the doc carries a
    CRC so a torn or bit-flipped checkpoint degrades to replay
    instead of crashing the loop or (worse) skipping unacked
    events."""

    def __init__(self, path: str = "",
                 ack_window: int = ACK_WINDOW_CAP):
        self.path = path
        self._lock = threading.Lock()
        self._pos = -1
        self._acked: set = set()
        self._ack_window = max(16, int(ack_window))
        self.abandoned = 0       # holes declared lost at the cap
        if path and os.path.exists(path):
            try:
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
                self._pos = self._validate(doc)
            except (OSError, ValueError, TypeError) as e:
                # a torn checkpoint must degrade to "replay from the
                # start" — correctness is dedupe's job, the cursor
                # only saves work
                log.warning("unreadable watch checkpoint %s: %r",
                            path, e)

    @staticmethod
    def _validate(doc) -> int:
        """Checkpoint doc → position, raising ValueError on anything
        suspect. Accepts the legacy ``{"position": N}`` shape (no
        CRC, exactly one key); any other shape must carry a matching
        ``crc`` — unknown keys or a stale/flipped tag mean the file
        was damaged in a way JSON parsing can't see."""
        if not isinstance(doc, dict):
            raise ValueError(f"checkpoint is {type(doc).__name__}, "
                             "not an object")
        pos = doc.get("position", -1)
        if isinstance(pos, bool) or not isinstance(pos, int):
            raise ValueError(f"bad checkpoint position {pos!r}")
        if set(doc) == {"position"}:
            return pos           # legacy, pre-CRC checkpoint
        if set(doc) != {"position", "crc"} or \
                doc["crc"] != _checkpoint_crc(pos):
            raise ValueError("checkpoint integrity check failed")
        return pos

    @property
    def position(self) -> int:
        with self._lock:
            return self._pos

    def stats(self) -> dict:
        """Leak-audit surface: the out-of-order window size is the
        one thing here that can grow."""
        with self._lock:
            return {"position": self._pos,
                    "ack_window": len(self._acked),
                    "abandoned": self.abandoned}

    def ack(self, seq: int) -> None:
        with self._lock:
            if seq <= self._pos:
                return
            self._acked.add(seq)
            advanced = False
            while self._pos + 1 in self._acked:
                self._pos += 1
                self._acked.discard(self._pos)
                advanced = True
            if len(self._acked) > self._ack_window:
                # a hole nothing will ever fill: advance past it to
                # the oldest acked seq (bounded memory; the skipped
                # range replays on restart, which is safe — dedupe
                # and idempotency absorb re-scans)
                jump = min(self._acked)
                log.warning(
                    "watch cursor abandoning hole %d..%d "
                    "(ack window %d over cap)", self._pos + 1,
                    jump - 1, len(self._acked))
                self.abandoned += jump - self._pos - 1
                self._pos = jump
                self._acked.discard(jump)
                while self._pos + 1 in self._acked:
                    self._pos += 1
                    self._acked.discard(self._pos)
                advanced = True
        if advanced:
            self.save()

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            doc = {"position": self._pos,
                   "crc": _checkpoint_crc(self._pos)}
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except OSError as e:        # checkpointing is best-effort
            log.warning("watch checkpoint write failed: %r", e)
