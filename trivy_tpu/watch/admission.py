"""K8s validating-admission webhook (docs/serving.md "Continuous
scanning & admission control").

``POST /k8s/admission`` takes an ``AdmissionReview`` (v1), extracts
every pod image reference from the reviewed object (any workload
kind the k8s scanner understands), resolves a scan verdict for each
within the request's deadline — the apiserver's ``?timeout=10s``
query parameter, or the configured default — and answers
allow/deny + audit annotations from the severity policy.

Latency model: the verdict cache (keyed by the findings-memo
``ctx_sig`` x image digest x policy) makes the repeat case free; a
cache miss scans through the shared scheduler, where warm memo
entries (docs/performance.md §7) make the common case a sub-second
cache hit. A miss that cannot resolve inside the deadline applies
the configured fail stance — ``open`` (allow + annotate), ``closed``
(deny), or ``408`` (surface the deadline as HTTP 408 and let the
webhook's own ``failurePolicy`` decide) — and enqueues a background
scan so the NEXT admission of that digest hits.

Invalidation: because every cached verdict is keyed by the memo
``ctx_sig`` (advisory-DB content fingerprint x rule-set x guard
config x scanner version), a ``db update`` hot swap strands the old
generation's verdicts exactly like findings entries — the next
review keys against the new context and recomputes. A swap hook on
the ``SwappableStore`` additionally drops the stranded entries so
the cache never holds unreachable generations.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from ..memo import keys as MK
from ..sched import DeadlineExceeded
from ..types.common import SEVERITIES
from ..utils import get_logger
from .metrics import WATCH_METRICS

log = get_logger("watch.admission")

SEVERITY_NAMES = tuple(str(s) for s in SEVERITIES)
ADMISSION_TENANT = "k8s-admission"
# background re-scans ride a LOW priority class: they must never
# jump a live admission's line within the tenant
BACKGROUND_PRIORITY = -50
ADMISSION_PRIORITY = 50
VERDICT_CACHE_CAP = 4096


class MalformedReview(ValueError):
    """Not an AdmissionReview we can answer (HTTP 400)."""


class AdmissionUnavailable(RuntimeError):
    """Deadline/degraded with the ``408`` fail stance: surfaced as
    HTTP 408 so the webhook's K8s-side ``failurePolicy`` decides."""


@dataclass(frozen=True)
class AdmissionPolicy:
    """``--admission-policy`` grammar: ``deny:SEV[,SEV...]`` (deny
    when any finding at one of these severities is present) or
    ``audit`` (never deny; annotations only). ``fail`` is the
    degraded/deadline stance: open | closed | 408."""

    deny: tuple = ("CRITICAL",)
    fail: str = "open"

    @classmethod
    def parse(cls, text: str = "",
              fail: str = "open") -> "AdmissionPolicy":
        text = (text or "").strip() or "deny:CRITICAL"
        if fail not in ("open", "closed", "408"):
            raise ValueError(
                f"bad admission fail stance {fail!r} "
                "(want open, closed or 408)")
        if text == "audit":
            return cls(deny=(), fail=fail)
        kind, sep, rest = text.partition(":")
        if kind != "deny" or not sep:
            raise ValueError(
                f"bad admission policy {text!r} (want "
                "'deny:SEV[,SEV...]' or 'audit')")
        sevs = tuple(s.strip().upper() for s in rest.split(",")
                     if s.strip())
        bad = [s for s in sevs if s not in SEVERITY_NAMES]
        if bad or not sevs:
            raise ValueError(
                f"bad admission severities {bad or rest!r} "
                f"(choose from {', '.join(SEVERITY_NAMES)})")
        return cls(deny=sevs, fail=fail)

    def sig(self) -> str:
        return ",".join(self.deny) or "audit"


@dataclass
class Verdict:
    """One image's cached admission answer."""

    allowed: bool
    counts: dict = field(default_factory=dict)
    detail: str = ""
    trace_id: str = ""
    source: str = "scan"        # scan | cache | fail-open

    def annotation(self) -> str:
        sevs = ",".join(f"{s}:{n}" for s, n in
                        sorted(self.counts.items(),
                               key=lambda kv: kv[0]) if n)
        base = "allow" if self.allowed else "deny"
        return f"{base}({sevs})" if sevs else \
            (base if not self.detail else f"{base}:{self.detail}")


def severity_counts(report) -> dict:
    """Severity histogram over a Report: vulnerabilities, secret
    findings, and FAILed misconfigurations all count — the policy
    speaks severities, not finding classes."""
    counts: dict = {}

    def bump(sev: str) -> None:
        sev = sev if sev in SEVERITY_NAMES else "UNKNOWN"
        counts[sev] = counts.get(sev, 0) + 1

    for r in getattr(report, "results", None) or []:
        for v in getattr(r, "vulnerabilities", None) or []:
            bump(getattr(v, "severity", "UNKNOWN"))
        for s in getattr(r, "secrets", None) or []:
            bump(getattr(s, "severity", "UNKNOWN"))
        for m in getattr(r, "misconfigurations", None) or []:
            if getattr(m, "status", "") == "FAIL":
                bump(getattr(m, "severity", "UNKNOWN"))
    return counts


def images_from_review(review) -> tuple:
    """AdmissionReview → (uid, [image refs]). Raises
    :class:`MalformedReview` on anything that is not a v1
    AdmissionReview with a reviewable object."""
    if not isinstance(review, dict) or \
            review.get("kind") != "AdmissionReview":
        raise MalformedReview("body is not an AdmissionReview")
    request = review.get("request")
    if not isinstance(request, dict) or not request.get("uid"):
        raise MalformedReview("AdmissionReview carries no request")
    obj = request.get("object")
    if not isinstance(obj, dict):
        raise MalformedReview("AdmissionReview carries no object")
    from ..k8s import images_from_object
    return str(request["uid"]), images_from_object(obj)


class VerdictCache:
    """Bounded LRU of admission verdicts keyed by
    ``memo.keys.verdict_sig(ctx, image, policy)`` — the ctx
    component is what makes a ``db update`` hot swap strand the old
    generation (satellite: invalidation exactly like findings
    entries). ``drop_ctx`` removes stranded entries eagerly when the
    holder exposes a swap hook. ``get(max_age_s=...)`` lets the
    caller bound entry age: a digest-pinned ref is content-addressed
    and caches indefinitely, but a mutable TAG ref can be repushed
    with different content, so its verdict must expire."""

    def __init__(self, cap: int = VERDICT_CACHE_CAP):
        self.cap = max(16, cap)
        self._lock = threading.Lock()
        # key -> (ctx, Verdict, monotonic stamp)
        self._d: OrderedDict = OrderedDict()

    def get(self, key: str, max_age_s=None):
        with self._lock:
            hit = self._d.get(key)
            if hit is None:
                return None
            if max_age_s is not None and \
                    time.monotonic() - hit[2] > max_age_s:
                del self._d[key]       # expired: recompute
                return None
            self._d.move_to_end(key)
            return hit[1]

    def put(self, key: str, ctx: str, verdict) -> None:
        with self._lock:
            self._d[key] = (ctx, verdict, time.monotonic())
            self._d.move_to_end(key)
            while len(self._d) > self.cap:
                self._d.popitem(last=False)

    def drop_ctx(self, ctx: str) -> int:
        with self._lock:
            dead = [k for k, (c, _, _) in self._d.items()
                    if c == ctx]
            for k in dead:
                del self._d[k]
            return len(dead)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)


class AdmissionController:
    """One controller per server/watch process. ``runner`` provides
    ``submit_path`` (scans share the process scheduler); ``store``
    is the advisory holder the CONTEXT derives from — a
    ``SwappableStore`` keeps verdicts generation-correct across
    ``db update`` hot swaps (and gets a swap hook that drops the
    stranded generation's cache entries)."""

    def __init__(self, runner, store=None, memo=None, policy=None,
                 resolver=None, default_deadline_s: float = 10.0,
                 background_rescan: bool = True,
                 security_checks=None,
                 tag_verdict_ttl_s: float = 30.0):
        self.runner = runner
        # which finding classes feed the severity policy; vuln +
        # secret by default (misconfig checks need policy modules
        # the admission path does not configure)
        self.security_checks = list(security_checks
                                    or ("vuln", "secret"))
        # the cache key folds the check set in next to the policy:
        # a vuln-only verdict must never serve a vuln+secret review
        self._policy_sig = "|".join(
            ((policy or AdmissionPolicy()).sig() or "audit",
             ",".join(sorted(self.security_checks))))
        self.store = store if store is not None \
            else getattr(runner, "store", None)
        self.memo = memo
        self.policy = policy or AdmissionPolicy()
        self.resolver = resolver
        self.default_deadline_s = default_deadline_s
        self.background_rescan = background_rescan
        # verdicts for MUTABLE tag refs (no @digest pin) expire: the
        # tag can be repushed with different content and nothing
        # here observes the push — only a digest-pinned ref is
        # content-addressed enough to cache until the next db swap
        self.tag_verdict_ttl_s = tag_verdict_ttl_s
        self.cache = VerdictCache()
        self._bg: list = []            # (key, ctx, req) futures
        self._bg_reserved = 0          # slots claimed pre-submit
        self._bg_lock = threading.Lock()
        holder = self.store
        if holder is not None and \
                hasattr(holder, "add_swap_hook"):
            holder.add_swap_hook(self._on_swap)

    # --- context ---

    def _current_db(self):
        holder = self.store
        if holder is not None and hasattr(holder, "current"):
            return holder.current()
        return holder

    def _ctx(self, db=None) -> str:
        db = db if db is not None else self._current_db()
        if self.memo is not None:
            return self.memo.ctx_for(db)
        return MK.db_fingerprint(db)

    def _on_swap(self, old_db, new_db) -> None:
        dropped = self.cache.drop_ctx(self._ctx(old_db))
        if dropped:
            log.info("db hot swap stranded %d admission verdicts",
                     dropped)

    # --- verdicts ---

    def _verdict_from_result(self, result) -> Verdict:
        report = getattr(result, "report", None)
        if report is None or getattr(result, "error", ""):
            raise RuntimeError(getattr(result, "error", "")
                               or "scan produced no report")
        counts = severity_counts(report)
        denied = any(counts.get(s, 0) for s in self.policy.deny)
        return Verdict(allowed=not denied, counts=counts)

    def _harvest_background(self) -> None:
        """Completed background scans populate the verdict cache so
        the NEXT admission of that digest hits — polled at review
        time (no reaper thread to leak)."""
        with self._bg_lock:
            live = []
            for key, ctx, req in self._bg:
                if not req.done:
                    live.append((key, ctx, req))
                    continue
                try:
                    v = self._verdict_from_result(req.result(
                        timeout=0))
                    v.trace_id = getattr(req, "trace_id", "") or ""
                    self.cache.put(key, ctx, v)
                except Exception as e:   # noqa: BLE001 — a failed
                    # background scan just means the next admission
                    # scans again
                    log.warning("background admission scan "
                                "failed: %r", e)
            self._bg = live

    def _enqueue_background(self, key: str, ctx: str,
                            path: str) -> None:
        if not self.background_rescan:
            return
        # the 64-entry backlog bound is RESERVED before submitting
        # (concurrent reviews race here — ThreadingHTTPServer), so
        # an over-bound scan never burns device time just to be
        # discarded
        with self._bg_lock:
            if len(self._bg) + self._bg_reserved >= 64:
                return
            self._bg_reserved += 1
        req = None
        try:
            req = self.runner.submit_path(
                path, self._options(), tenant=ADMISSION_TENANT,
                priority=BACKGROUND_PRIORITY)
        # lint: disable=bare-except-at-seam -- best-effort warmer:
        # it fails under exactly the backpressure it must not log-
        # storm about; the review already answered from the stance
        except Exception:            # noqa: BLE001 — backpressure on
            pass                     # a best-effort warmer is fine
        finally:
            with self._bg_lock:
                self._bg_reserved -= 1
                if req is not None:
                    self._bg.append((key, ctx, req))
        if req is not None:
            WATCH_METRICS.inc("admission_background_scans")

    def _options(self, deadline_s: float = 0.0):
        from ..types import ScanOptions
        opts = ScanOptions(backend=getattr(self.runner, "backend",
                                           "tpu"),
                           security_checks=list(
                               self.security_checks))
        if deadline_s > 0:
            opts.deadline_s = deadline_s
        return opts

    def _image_verdict(self, ref: str, ctx: str,
                       deadline: float) -> Verdict:
        pinned = "@" in ref
        digest = ref.rpartition("@")[2] if pinned else ref
        key = MK.verdict_sig(ctx, digest, self._policy_sig)
        hit = self.cache.get(
            key, max_age_s=None if pinned
            else self.tag_verdict_ttl_s)
        if hit is not None:
            WATCH_METRICS.inc("admission_cache_hits")
            hit = Verdict(allowed=hit.allowed,
                          counts=dict(hit.counts),
                          detail=hit.detail,
                          trace_id=hit.trace_id, source="cache")
            return hit
        WATCH_METRICS.inc("admission_cache_misses")
        path = self.resolver(ref, digest) \
            if self.resolver is not None else None
        if path is None:
            raise DeadlineExceeded(
                f"image {ref!r} not resolvable to a scan target")
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            WATCH_METRICS.inc("admission_timeout")
            self._enqueue_background(key, ctx, path)
            raise DeadlineExceeded(
                f"admission deadline exhausted before {ref!r}")
        req = self.runner.submit_path(
            path, self._options(deadline_s=remaining),
            tenant=ADMISSION_TENANT, priority=ADMISSION_PRIORITY)
        try:
            result = req.result()
        except DeadlineExceeded:
            WATCH_METRICS.inc("admission_timeout")
            self._enqueue_background(key, ctx, path)
            raise
        verdict = self._verdict_from_result(result)
        verdict.trace_id = getattr(req, "trace_id", "") or ""
        self.cache.put(key, ctx, verdict)
        return verdict

    # --- the review entry point (HTTP route + tests) ---

    def review(self, body: dict,
               deadline_s: float = 0.0) -> dict:
        """One AdmissionReview → the response AdmissionReview.
        Raises :class:`MalformedReview` (400) on garbage and
        :class:`AdmissionUnavailable` (408) only under the ``408``
        fail stance; every other degraded path answers a valid
        review per the configured stance."""
        t0 = time.monotonic()
        deadline = t0 + (deadline_s
                         if deadline_s and deadline_s > 0
                         else self.default_deadline_s)
        self._harvest_background()
        uid, images = images_from_review(body)
        ctx = self._ctx()
        WATCH_METRICS.inc("admission_reviews")
        verdicts: list = []            # (ref, Verdict|None, err)
        for ref in images:
            try:
                verdicts.append((ref,
                                 self._image_verdict(ref, ctx,
                                                     deadline),
                                 None))
            except Exception as e:   # noqa: BLE001 — deadline,
                # unresolvable, scan failure: the fail stance decides
                verdicts.append((ref, None, e))
        denied = [ref for ref, v, _ in verdicts
                  if v is not None and not v.allowed]
        failed = [(ref, err) for ref, v, err in verdicts
                  if v is None]
        fail = self.policy.fail
        if failed and fail == "408":
            # admission_timeout was already counted where the
            # deadline actually expired (_image_verdict) — counting
            # here too would double the total operators alert on
            raise AdmissionUnavailable(
                "; ".join(f"{ref}: {err}" for ref, err in failed))
        if failed and fail == "closed":
            denied.extend(ref for ref, _ in failed)
        if failed and fail == "open":
            WATCH_METRICS.inc("admission_fail_open", len(failed))
        allowed = not denied
        WATCH_METRICS.inc("admission_allow" if allowed
                          else "admission_deny")
        annotations = {}
        for i, (ref, v, err) in enumerate(verdicts):
            if v is not None:
                annotations[f"trivy-tpu/image-{i}"] = \
                    f"{ref}: {v.annotation()} [{v.source}]"
                if v.trace_id:
                    annotations[f"trivy-tpu/trace-{i}"] = v.trace_id
            else:
                stance = ("fail-open" if fail == "open"
                          else "fail-closed")
                annotations[f"trivy-tpu/image-{i}"] = \
                    f"{ref}: {stance} ({err})"
        annotations["trivy-tpu/policy"] = \
            f"deny:{self.policy.sig()}" if self.policy.deny \
            else "audit"
        exemplar = next((v.trace_id for _, v, _ in verdicts
                         if v is not None and v.trace_id), "")
        WATCH_METRICS.observe("admission_latency",
                              time.monotonic() - t0,
                              trace_id=exemplar)
        response = {"uid": uid, "allowed": allowed,
                    "auditAnnotations": annotations}
        if not allowed:
            reasons = denied[:4]
            response["status"] = {
                "code": 403,
                "reason": "AdmissionDenied",
                "message": "trivy-tpu admission policy "
                           f"deny:{self.policy.sig()} rejected: "
                           + ", ".join(reasons)}
        return {"apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview", "response": response}

    def stats(self) -> dict:
        with self._bg_lock:
            bg = len(self._bg)
        return {"cache_entries": len(self.cache),
                "background_pending": bg,
                "policy": (f"deny:{self.policy.sig()}"
                           if self.policy.deny else "audit"),
                "fail": self.policy.fail,
                "default_deadline_s": self.default_deadline_s}
