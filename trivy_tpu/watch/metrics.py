"""Watch/admission metrics (docs/serving.md "Continuous scanning &
admission control").

Process-wide by design, like ``memo.metrics.MEMO_METRICS``: the watch
loop and the admission controller are long-lived singletons per
process, and the numbers an operator alerts on
(``trivy_tpu_watch_{events,deduped,scans}_total``,
``trivy_tpu_admission_{allow,deny,fail_open,timeout}_total``, the
event-lag and admission-latency histograms) are cumulative totals on
``GET /metrics`` — JSON and Prometheus text alike, on both sched
modes.
"""

from __future__ import annotations

import threading

from ..sched.metrics import LatencyHistogram


class WatchMetrics:
    """Cumulative counters + latency histograms for the watch loop
    and the K8s admission webhook."""

    _KEYS = (
        # -- watch loop: every valid push event entering the loop
        #    ends in EXACTLY ONE of scans / deduped / shed (the
        #    storm-drain accounting invariant, test-enforced)
        "events", "deduped", "scans", "shed",
        # malformed notifications are counted and dropped at the
        # parse boundary — they never become events
        "malformed",
        # scan outcomes (disjoint from the event disposition above:
        # a failed scan still disposed its events as "scans")
        "completed", "failed",
        # source hiccups survived via the shared backoff policy
        "source_errors",
        # events whose image reference no resolver could map to a
        # scannable target (disposed as shed)
        "unresolvable",
        # hot-swap impact push stream: re-scan events enqueued by
        # impact/push.py (each then disposes normally as
        # scans/deduped/shed — this counts the stream's input side)
        "impact_rescans",
        # -- admission webhook verdict counters
        "admission_allow", "admission_deny", "admission_fail_open",
        "admission_timeout", "admission_reviews",
        # verdict-cache traffic (keyed by the memo ctx_sig — a db
        # hot swap strands the old generation's entries)
        "admission_cache_hits", "admission_cache_misses",
        # deadline-missed digests queued for a warm background scan
        # so the NEXT admission of that digest hits
        "admission_background_scans",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._c = {k: 0 for k in self._KEYS}
        # event lag: push-event arrival -> scan resolution; the
        # admission histogram is review() wall time. Both carry
        # trace-id exemplars (OpenMetrics exposition only).
        self._hist = {"watch_lag": LatencyHistogram(),
                      "admission_latency": LatencyHistogram()}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            # lint: disable=unbounded-label-cardinality -- counter
            # names are code-literal call sites, never
            # request-derived strings
            self._c[name] = self._c.get(name, 0) + n

    def observe(self, hist: str, seconds: float,
                trace_id: str = "") -> None:
        with self._lock:
            self._hist[hist].observe(seconds, exemplar=trace_id)

    def reset(self) -> None:
        """Test hook — production code never calls this."""
        with self._lock:
            for k in self._c:
                self._c[k] = 0
            self._hist = {"watch_lag": LatencyHistogram(),
                          "admission_latency": LatencyHistogram()}

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["lag"] = self._hist["watch_lag"].to_dict()
            out["admission_latency"] = \
                self._hist["admission_latency"].to_dict()
        lookups = (out["admission_cache_hits"]
                   + out["admission_cache_misses"])
        out["admission_cache_hit_rate"] = round(
            out["admission_cache_hits"] / lookups, 4) \
            if lookups else 0.0
        return out

    def hist_snapshot(self) -> dict:
        """Raw bucket counts + exemplars for Prometheus exposition
        (obs/prom.py renders ``trivy_tpu_watch_lag_seconds`` and
        ``trivy_tpu_admission_latency_seconds``)."""
        with self._lock:
            return {k: h.raw() for k, h in self._hist.items()}


WATCH_METRICS = WatchMetrics()
