"""The watch loop: registry push events → debounced, deduped,
bounded-in-flight scan submissions (docs/serving.md "Continuous
scanning & admission control").

One loop serves any :mod:`watch.source`; scans ride the SAME
continuous-batching scheduler as RPC and CLI traffic
(``BatchScanRunner.submit_path``), with per-source tenant identity
and priority — so the tenancy QoS layer, the SLO engine, and the
findings memo all apply to watch traffic for free.

Event accounting invariant (storm-drain test-enforced): every valid
event entering the loop ends in EXACTLY ONE of

* ``scans`` — it triggered a scan submission (which may later
  complete or fail; that is scan accounting, not event accounting);
* ``deduped`` — it was folded into a pending or in-flight scan of
  the same digest (a tag repushed 5x in a burst scans once);
* ``shed`` — admission rejected it (429/503 after bounded backoff
  honoring Retry-After) or no resolver could map it to a target.

Backpressure flows in layers: the scheduler's bounded queue sheds
via the existing typed 429/503 errors; the loop's in-flight
watermarks stop PULLING the source before that point, so a webhook
source buffers (bounded) and a paced source simply falls behind —
the loop itself never crashes and never grows unbounded state.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ..sched import QueueFullError, RateLimitedError
from ..utils import get_logger
from ..utils.backoff import full_jitter_delay
from .metrics import WATCH_METRICS
from .source import Cursor

log = get_logger("watch.loop")


@dataclass
class WatchConfig:
    """Loop tuning knobs (CLI: ``trivy-tpu watch``)."""

    # debounce window: a scan fires this long after the FIRST event
    # of a burst, folding every same-digest event that lands inside
    # the window into one submission. 0 = submit immediately (dedupe
    # still folds into in-flight scans).
    debounce_s: float = 0.25
    # in-flight watermarks: stop pulling the source at ``high``
    # outstanding scans, resume at ``low`` (0 = high // 2)
    max_inflight: int = 32
    resume_inflight: int = 0
    # bounded submit retries before an event sheds (backoff honors
    # RateLimitedError.retry_after_s, full jitter otherwise — the
    # shared utils/backoff.py policy)
    submit_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    # source-failure backoff (reconnect/retry)
    source_backoff_max_s: float = 5.0
    # per-source identity threaded into every submission
    tenant: str = "watch"
    priority: int = 0
    checkpoint_path: str = ""
    # keep the latest BatchScanResult per digest (bench/tests use it
    # for the byte-identity gate; servers leave it off)
    keep_results: bool = False

    @property
    def low_watermark(self) -> int:
        return self.resume_inflight or max(1, self.max_inflight // 2)


class _Group:
    """One pending-or-in-flight scan and the events it covers."""

    __slots__ = ("digest", "events", "first_ts", "req")

    def __init__(self, event):
        self.digest = event.digest
        self.events = [event]
        self.first_ts = event.ts
        self.req = None


class WatchLoop:
    """Single-threaded event pump: call :meth:`run` (blocking) or
    drive :meth:`step` yourself (tests). All counters mirror into
    the process-wide :data:`WATCH_METRICS`."""

    def __init__(self, runner, source, config=None, options=None):
        from ..types import ScanOptions
        self.runner = runner
        self.source = source
        self.config = config or WatchConfig()
        self.options = options or ScanOptions(
            backend=getattr(runner, "backend", "tpu"))
        self.cursor = Cursor(self.config.checkpoint_path)
        if self.cursor.position >= 0:
            source.resume_from(self.cursor.position)
        self.counters = {k: 0 for k in (
            "events", "deduped", "scans", "shed", "completed",
            "failed", "source_errors", "unresolvable")}
        self.results: dict = {}        # digest -> BatchScanResult
        self._pending: dict = {}       # digest -> _Group (debouncing)
        self._inflight: dict = {}      # digest -> _Group (submitted)
        self._paused = False           # watermark state
        self._source_attempt = 0
        self.inflight_peak = 0
        self._closed = False

    # --- counters ---

    def _count(self, name: str, n: int = 1) -> None:
        self.counters[name] += n
        WATCH_METRICS.inc(name, n)

    def stats(self) -> dict:
        return dict(self.counters,
                    pending=len(self._pending),
                    inflight=len(self._inflight),
                    inflight_peak=self.inflight_peak,
                    cursor=self.cursor.position)

    # --- event disposition ---

    def _ack_group(self, group: _Group) -> None:
        for ev in group.events:
            if ev.seq >= 0:
                self.cursor.ack(ev.seq)

    def _reap(self) -> None:
        """Harvest completed scans without blocking — the loop stays
        responsive to arrivals while results trickle in."""
        for seq in self.source.take_dropped():
            # events the source discarded before delivery (webhook
            # overflow): ack so the cursor's contiguous high-water
            # mark can pass the hole — they're counted in the
            # source's ``dropped``, not in the loop books
            self.cursor.ack(seq)
        now = time.monotonic()
        for digest in [d for d, g in self._inflight.items()
                       if g.req.done]:
            group = self._inflight.pop(digest)
            try:
                result = group.req.result(timeout=0)
                failed = bool(getattr(result, "error", ""))
            except Exception as e:      # noqa: BLE001 — deadline,
                # shutdown, or a scan error: the slot failed, the
                # loop carries on
                result, failed = None, True
                log.warning("watch scan %r failed: %r",
                            group.digest, e)
            self._count("failed" if failed else "completed")
            if result is not None and self.config.keep_results:
                self.results[digest] = result
            for ev in group.events:
                WATCH_METRICS.observe(
                    "watch_lag", max(0.0, now - ev.ts),
                    trace_id=getattr(group.req, "trace_id", "")
                    or "")
            self._ack_group(group)
        n = len(self._inflight)
        if n > self.inflight_peak:
            self.inflight_peak = n
        if self._paused and n <= self.config.low_watermark:
            self._paused = False

    def _submit(self, group: _Group) -> None:
        """Submit one debounced group; bounded retries, then shed."""
        cfg = self.config
        ev = group.events[0]
        if not ev.path:
            self._count("unresolvable")
            self._shed(group)
            return
        # propagated trace context: a traceparent on the source
        # event roots this scan under the submitter's span (fleet
        # plane); garbage parses to the empty context, i.e. a fresh
        # local trace — exactly the no-propagation behavior
        from ..obs.propagate import EMPTY_CONTEXT, parse_traceparent
        ctx = parse_traceparent(getattr(ev, "traceparent", "")) \
            or EMPTY_CONTEXT
        attempts = max(1, cfg.submit_retries)
        for attempt in range(attempts):
            retry = attempt + 1 < attempts
            try:
                group.req = self.runner.submit_path(
                    ev.path, self.options,
                    tenant=ev.tenant or cfg.tenant,
                    priority=ev.priority or cfg.priority,
                    trace_id=ctx.trace_id,
                    parent_span_id=ctx.parent_span_id)
                break
            except RateLimitedError as e:
                # no sleep after the FINAL attempt: the pump is
                # single-threaded, and a backoff nothing will retry
                # only stalls reaping and intake under overload
                if retry:
                    time.sleep(min(max(e.retry_after_s, 0.001),
                                   cfg.backoff_max_s))
            except QueueFullError:
                if retry:
                    time.sleep(full_jitter_delay(
                        attempt, cfg.backoff_base_s,
                        cfg.backoff_max_s))
            except Exception as e:   # noqa: BLE001 — scheduler
                # closed/draining mid-loop: shed, keep the loop alive
                log.warning("watch submit %r failed: %r",
                            group.digest, e)
                break
        if group.req is None:
            self._shed(group)
            return
        self._count("scans")
        self._count("deduped", len(group.events) - 1)
        self._inflight[group.digest] = group
        n = len(self._inflight)
        if n > self.inflight_peak:
            self.inflight_peak = n
        if n >= self.config.max_inflight:
            self._paused = True

    def _shed(self, group: _Group) -> None:
        """Admission (or resolution) rejected the group: the trigger
        event sheds, its folded followers stay deduped — books
        balance either way, and the cursor still advances (a shed
        event is accounted, not forgotten)."""
        self._count("shed")
        self._count("deduped", len(group.events) - 1)
        self._ack_group(group)

    def _flush_due(self, force: bool = False) -> None:
        now = time.monotonic()
        for digest in list(self._pending):
            group = self._pending[digest]
            if force or now - group.first_ts >= \
                    self.config.debounce_s:
                if not force and \
                        len(self._inflight) >= \
                        self.config.max_inflight:
                    return           # watermark: hold the group
                del self._pending[digest]
                self._submit(group)

    def _admit(self, event) -> None:
        self._count("events")
        group = self._pending.get(event.digest)
        if group is not None:
            group.events.append(event)
            return                   # disposition resolves with group
        inflight = self._inflight.get(event.digest)
        if inflight is not None:
            # same digest, same content: the running scan covers it
            self._count("deduped")
            inflight.events.append(event)
            return
        group = _Group(event)
        if self.config.debounce_s <= 0:
            self._submit(group)
        else:
            self._pending[event.digest] = group

    # --- the pump ---

    def step(self, timeout: float = 0.05) -> bool:
        """One iteration: reap, flush due groups, maybe pull one
        event. Returns False once the source is exhausted AND
        nothing is pending or in flight."""
        self._reap()
        self._flush_due()
        if self.source.exhausted and not self._pending:
            if not self._inflight:
                return False
            time.sleep(min(timeout, 0.02))
            return True
        if self._paused:
            time.sleep(min(timeout, 0.02))
            return True
        try:
            event = self.source.get(timeout)
            self._source_attempt = 0
        except Exception as e:       # noqa: BLE001 — transport
            # hiccup: reconnect/retry with the shared backoff policy,
            # never crash the loop
            self._count("source_errors")
            delay = full_jitter_delay(
                self._source_attempt, 0.05,
                self.config.source_backoff_max_s)
            self._source_attempt += 1
            log.warning("watch source error (retry in %.2fs): %r",
                        delay, e)
            time.sleep(delay)
            return True
        if event is not None:
            self._admit(event)
        elif self._pending or self._inflight:
            # no arrival this tick but work is debouncing or in
            # flight: don't spin on sources whose get() returns
            # immediately (trace replay after exhaustion)
            time.sleep(min(timeout, 0.01))
        return True

    def run(self, max_wall_s: float = 0.0) -> dict:
        """Pump until the source exhausts (or ``max_wall_s``
        elapses), then drain. Returns the final counters."""
        deadline = time.monotonic() + max_wall_s if max_wall_s \
            else None
        while not self._closed and self.step():
            if deadline is not None and \
                    time.monotonic() >= deadline:
                break
        return self.drain()

    def drain(self, timeout_s: float = 120.0) -> dict:
        """Flush every pending group, wait out in-flight scans,
        checkpoint, and return the counters."""
        self._flush_due(force=True)
        deadline = time.monotonic() + timeout_s
        while self._inflight and time.monotonic() < deadline:
            self._reap()
            if self._inflight:
                time.sleep(0.01)
        self._reap()
        self.cursor.save()
        return self.stats()

    def close(self) -> None:
        self._closed = True
