"""Continuous-scanning subsystem (docs/serving.md "Continuous
scanning & admission control").

Two event-driven front-ends over one core loop:

* ``trivy-tpu watch`` — subscribe to registry push events (Docker
  Registry v2 notification webhooks, or a seeded synthetic source)
  and keep the fleet scanned: dedupe/debounce per image digest,
  bounded in-flight watermarks, checkpointed cursor, submissions
  through the shared continuous-batching scheduler with per-source
  tenant identity;
* ``POST /k8s/admission`` — a K8s ValidatingWebhookConfiguration-
  compatible endpoint answering deadline-bounded allow/deny verdicts
  from severity policy, with a verdict cache keyed by the findings-
  memo ``ctx_sig`` so ``db update`` hot swaps invalidate admission
  answers exactly like findings entries.
"""

from .admission import (AdmissionController, AdmissionPolicy,
                        AdmissionUnavailable, MalformedReview,
                        Verdict, VerdictCache, images_from_review,
                        severity_counts)
from .loop import WatchConfig, WatchLoop
from .metrics import WATCH_METRICS, WatchMetrics
from .source import (Cursor, EventSource, PushEvent,
                     SyntheticSource, TraceSource, WebhookSource,
                     dir_resolver, make_event_storm,
                     parse_notification)

__all__ = [
    "AdmissionController", "AdmissionPolicy", "AdmissionUnavailable",
    "Cursor", "EventSource", "MalformedReview", "PushEvent",
    "SyntheticSource", "TraceSource", "Verdict", "VerdictCache",
    "WATCH_METRICS", "WatchConfig", "WatchLoop", "WatchMetrics",
    "WebhookSource", "dir_resolver", "images_from_review",
    "make_event_storm", "parse_notification", "severity_counts",
]
