"""Client/server mode (reference: rpc/ + pkg/rpc).

The wire contract keeps the reference's Twirp shape — POST
``/twirp/trivy.scanner.v1.Scanner/Scan`` and
``/twirp/trivy.cache.v1.Cache/{PutArtifact,PutBlob,MissingBlobs,
DeleteBlobs}`` with JSON bodies (Twirp's JSON protocol), token-header
auth, and the same split of work: the client inspects artifacts
locally and pushes BlobInfos; the server owns the cache, the
TPU-resident advisory DB (hot-swappable mid-stream), and detection.
"""

from .client import RemoteCache, RemoteScanner
from .server import ScanServer, serve

__all__ = ["RemoteCache", "RemoteScanner", "ScanServer", "serve"]
