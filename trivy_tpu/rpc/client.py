"""RPC client (reference: pkg/rpc/client/client.go + retry.go).

``trivy-tpu image --server URL``: the client inspects the artifact
locally (analyzers + secret scanning run client-side), pushes
BlobInfos to the server's cache, and asks the server to run
detection against its DB — the client needs no advisory store at all
(run.go:269-271). Transient failures retry with exponential backoff
×10, like retry.go:16-41 does on twirp.Unavailable.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Optional

from ..types import Result
from ..types.convert import os_from_dict, result_from_dict
from ..utils import get_logger
from .server import CACHE_PREFIX, DEFAULT_TOKEN_HEADER, SCANNER_PREFIX

log = get_logger("rpc.client")

MAX_RETRIES = 10
BACKOFF_BASE_S = 0.2


class RPCError(RuntimeError):
    def __init__(self, code, msg):
        super().__init__(f"rpc error {code}: {msg}")
        self.code = code


class _Client:
    def __init__(self, base_url: str, token: str = "",
                 token_header: str = DEFAULT_TOKEN_HEADER,
                 custom_headers: Optional[dict] = None,
                 max_retries: int = MAX_RETRIES,
                 backoff_base_s: float = BACKOFF_BASE_S,
                 timeout_s: float = 300.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.token_header = token_header
        self.custom_headers = custom_headers or {}
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.timeout_s = timeout_s
        # trace_id of the most recent Scan call (RemoteScanner):
        # lets a CLI client surface "see /trace/<id> on the server"
        self.last_trace_id = ""

    def call(self, path: str, body: dict) -> dict:
        """POST with exponential-backoff retry on transient errors
        only (connection refused / 5xx — retry.go retries only
        twirp.Unavailable)."""
        data = json.dumps(body).encode()
        last_err = None
        for attempt in range(self.max_retries):
            if attempt:
                time.sleep(self.backoff_base_s * (2 ** (attempt - 1)))
            req = urllib.request.Request(
                self.base_url + path, data=data, method="POST",
                headers={"Content-Type": "application/json",
                         **self.custom_headers})
            if self.token:
                req.add_header(self.token_header, self.token)
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                detail = e.read().decode("utf-8", "replace")
                if e.code >= 500:           # transient: retry
                    last_err = RPCError(e.code, detail)
                    log.debug("retrying %s after %d: %s",
                              path, e.code, detail)
                    continue
                raise RPCError(e.code, detail)
            except (urllib.error.URLError, OSError,
                    ConnectionError) as e:
                last_err = RPCError("unavailable", str(e))
                log.debug("retrying %s after %s", path, e)
                continue
        raise last_err


class RemoteCache(_Client):
    """Cache service client — satisfies the local cache interface the
    artifact layer uses, so inspection code is oblivious to the wire
    (reference: NopCache(RemoteCache), run.go:296-299)."""

    def missing_blobs(self, artifact_id: str, blob_ids: list) -> tuple:
        out = self.call(CACHE_PREFIX + "MissingBlobs",
                        {"artifact_id": artifact_id,
                         "blob_ids": list(blob_ids)})
        return (out.get("missing_artifact", False),
                out.get("missing_blob_ids") or [])

    def put_artifact(self, artifact_id: str, info) -> None:
        self.call(CACHE_PREFIX + "PutArtifact",
                  {"artifact_id": artifact_id,
                   "artifact_info": info.to_dict()})

    def put_blob(self, blob_id: str, blob) -> None:
        self.call(CACHE_PREFIX + "PutBlob",
                  {"diff_id": blob_id,
                   "blob_info": blob.to_dict()})

    def delete_blobs(self, blob_ids: list) -> None:
        self.call(CACHE_PREFIX + "DeleteBlobs",
                  {"blob_ids": list(blob_ids)})

    def get_blob(self, blob_id: str):
        """The wire cache is write-only from the client side (the
        server scans its own copy)."""
        return None

    def get_artifact(self, artifact_id: str):
        return None


class RemoteScanner(_Client):
    """Scanner service client — the remote analog of
    LocalScanner.scan (reference: pkg/rpc/client client.go:64-94)."""

    def scan(self, target, options) -> tuple:
        """``target`` is a ScanTarget — same call shape as
        LocalScanner.scan, so the CLI swaps drivers freely
        (scanner.Driver in the reference).

        Every Scan carries a fresh idempotency key shared by all
        retry attempts of THIS call: if a response is lost after the
        server enqueued the scan, the retry replays the first
        enqueue's outcome instead of double-enqueuing into the
        scheduler.

        It also carries a client-generated ``trace_id`` (Dapper-style
        propagation, docs/observability.md): the server roots this
        request's span tree under it, so the caller can pull the
        trace from ``GET /trace/<id>`` — the id is logged at debug
        and kept on ``self.last_trace_id``. Retries reuse the same
        id: they are attempts at ONE logical request."""
        import uuid
        self.last_trace_id = uuid.uuid4().hex
        log.debug("scan %r trace_id=%s", target.name,
                  self.last_trace_id)
        out = self.call(SCANNER_PREFIX + "Scan", {
            "idempotency_key": uuid.uuid4().hex,
            "trace_id": self.last_trace_id,
            "target": target.name,
            "artifact_id": target.artifact_id,
            "blob_ids": list(target.blob_ids),
            "options": {
                "vuln_type": list(options.vuln_type),
                "security_checks": list(options.security_checks),
                "list_all_packages": options.list_all_packages,
                "scan_removed_packages":
                    options.scan_removed_packages,
                "backend": getattr(options, "backend", "tpu"),
            },
        })
        results = [result_from_dict(r)
                   for r in out.get("results") or []]
        return results, os_from_dict(out.get("os"))
