"""RPC client (reference: pkg/rpc/client/client.go + retry.go).

``trivy-tpu image --server URL``: the client inspects the artifact
locally (analyzers + secret scanning run client-side), pushes
BlobInfos to the server's cache, and asks the server to run
detection against its DB — the client needs no advisory store at all
(run.go:269-271). Transient failures retry with exponential backoff
×10, like retry.go:16-41 does on twirp.Unavailable.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Optional

from ..types import Result
from ..types.convert import os_from_dict, result_from_dict
from ..utils import get_logger
from ..utils.backoff import full_jitter_delay, parse_retry_after
from .server import (CACHE_PREFIX, DEFAULT_TOKEN_HEADER,
                     SCANNER_PREFIX, TENANT_HEADER)

log = get_logger("rpc.client")

MAX_RETRIES = 10
BACKOFF_BASE_S = 0.2
BACKOFF_MAX_S = 5.0
# a server-sent Retry-After is honored up to this cap (it is the
# server's authoritative shed hint, so it is NOT clamped to the
# jitter backoff's 5s ceiling — a 20s quota-drain hint must not
# collapse into futile 5s retries); the request deadline still caps
# the whole loop below
RETRY_AFTER_CAP_S = 60.0


class RPCError(RuntimeError):
    def __init__(self, code, msg):
        super().__init__(f"rpc error {code}: {msg}")
        self.code = code


class _Client:
    def __init__(self, base_url: str, token: str = "",
                 token_header: str = DEFAULT_TOKEN_HEADER,
                 custom_headers: Optional[dict] = None,
                 max_retries: int = MAX_RETRIES,
                 backoff_base_s: float = BACKOFF_BASE_S,
                 backoff_max_s: float = BACKOFF_MAX_S,
                 timeout_s: float = 300.0,
                 tenant: str = ""):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.token_header = token_header
        self.custom_headers = custom_headers or {}
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.timeout_s = timeout_s
        # tenant identity sent on every call (Trivy-Tenant header);
        # empty = the server's shared anonymous tenant
        self.tenant = tenant
        # trace_id of the most recent Scan call (RemoteScanner):
        # lets a CLI client surface "see /trace/<id> on the server"
        self.last_trace_id = ""
        # replica that served the most recent call, when a scan
        # router fronted it (Trivy-Routed-Replica header / the Scan
        # body's routed_replica field); "" when talking to a single
        # server directly
        self.last_routed_replica = ""
        # retry accounting: total retry sleeps taken, and how many
        # of them were server 429 rate-limit shed (docs/serving.md
        # "Multi-tenant QoS") vs transient 5xx/connection failures
        self.counters = {"retries": 0, "rate_limited": 0}

    def _delay(self, attempt: int, retry_after: str = "") -> float:
        """One retry delay: the server's ``Retry-After`` when it
        sent one (a 429's shed hint is authoritative, capped only at
        RETRY_AFTER_CAP_S), else full jitter on an exponential base
        — a retrying fleet must not re-synchronize onto the
        overloaded server (same policy as artifact/registry.py's
        registry client; shared pieces in utils/backoff.py)."""
        hint = parse_retry_after(retry_after)
        if hint is not None:
            return min(hint, RETRY_AFTER_CAP_S)
        return full_jitter_delay(attempt, self.backoff_base_s,
                                 self.backoff_max_s)

    def call(self, path: str, body: dict,
             deadline_s: float = 0.0) -> dict:
        """POST with bounded retries on transient errors only:
        connection refused, 5xx (retry.go retries only
        twirp.Unavailable), and 429 rate-limit shed — honoring the
        server's ``Retry-After``. ``deadline_s`` caps the whole
        retry loop: backing off past the request's own deadline
        would only return an answer nobody is waiting for."""
        data = json.dumps(body).encode()
        last_err = None
        t0 = time.monotonic()
        for attempt in range(self.max_retries):
            req = urllib.request.Request(
                self.base_url + path, data=data, method="POST",
                headers={"Content-Type": "application/json",
                         **self.custom_headers})
            if self.token:
                req.add_header(self.token_header, self.token)
            if self.tenant:
                req.add_header(TENANT_HEADER, self.tenant)
            retry_after = ""
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    self.last_routed_replica = resp.headers.get(
                        "Trivy-Routed-Replica") or ""
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                detail = e.read().decode("utf-8", "replace")
                if e.code == 429:
                    # per-tenant shed: transient by contract — the
                    # server told us exactly how long to back off.
                    # The JSON body's retry_after_s is preferred
                    # (sub-second precision); the Retry-After
                    # header (integer delta-seconds per RFC 9110)
                    # is the fallback
                    self.counters["rate_limited"] += 1
                    retry_after = (e.headers.get("Retry-After")
                                   if e.headers else "") or ""
                    try:
                        body_hint = json.loads(detail).get(
                            "retry_after_s")
                        if body_hint is not None:
                            retry_after = str(float(body_hint))
                    except (ValueError, AttributeError):
                        pass
                    last_err = RPCError(e.code, detail)
                    log.debug("rate-limited on %s (retry-after=%s)",
                              path, retry_after)
                elif e.code == 503:
                    # transient by contract (drain/unavailable or
                    # queue-full shed) — and when a router or a
                    # draining server sent a Retry-After, honor it
                    # exactly like a 429's: header as the fallback,
                    # the JSON body's retry_after_s (sub-second
                    # precision) preferred
                    retry_after = (e.headers.get("Retry-After")
                                   if e.headers else "") or ""
                    try:
                        body_hint = json.loads(detail).get(
                            "retry_after_s")
                        if body_hint is not None:
                            retry_after = str(float(body_hint))
                    except (ValueError, AttributeError):
                        pass
                    last_err = RPCError(e.code, detail)
                    log.debug("retrying %s after 503 "
                              "(retry-after=%s)", path, retry_after)
                elif e.code >= 500:         # transient: retry
                    last_err = RPCError(e.code, detail)
                    log.debug("retrying %s after %d: %s",
                              path, e.code, detail)
                else:
                    raise RPCError(e.code, detail)
            except (urllib.error.URLError, OSError,
                    ConnectionError) as e:
                last_err = RPCError("unavailable", str(e))
                log.debug("retrying %s after %s", path, e)
            if attempt + 1 >= self.max_retries:
                break
            delay = self._delay(attempt, retry_after)
            if deadline_s and deadline_s > 0:
                remaining = deadline_s - (time.monotonic() - t0)
                if remaining <= 0:
                    break           # out of deadline: fail now
                delay = min(delay, remaining)
            self.counters["retries"] += 1
            time.sleep(delay)
        raise last_err


class RemoteCache(_Client):
    """Cache service client — satisfies the local cache interface the
    artifact layer uses, so inspection code is oblivious to the wire
    (reference: NopCache(RemoteCache), run.go:296-299)."""

    def missing_blobs(self, artifact_id: str, blob_ids: list) -> tuple:
        out = self.call(CACHE_PREFIX + "MissingBlobs",
                        {"artifact_id": artifact_id,
                         "blob_ids": list(blob_ids)})
        return (out.get("missing_artifact", False),
                out.get("missing_blob_ids") or [])

    def put_artifact(self, artifact_id: str, info) -> None:
        self.call(CACHE_PREFIX + "PutArtifact",
                  {"artifact_id": artifact_id,
                   "artifact_info": info.to_dict()})

    def put_blob(self, blob_id: str, blob) -> None:
        self.call(CACHE_PREFIX + "PutBlob",
                  {"diff_id": blob_id,
                   "blob_info": blob.to_dict()})

    def delete_blobs(self, blob_ids: list) -> None:
        self.call(CACHE_PREFIX + "DeleteBlobs",
                  {"blob_ids": list(blob_ids)})

    def get_blob(self, blob_id: str):
        """The wire cache is write-only from the client side (the
        server scans its own copy)."""
        return None

    def get_artifact(self, artifact_id: str):
        return None


class RemoteScanner(_Client):
    """Scanner service client — the remote analog of
    LocalScanner.scan (reference: pkg/rpc/client client.go:64-94)."""

    def scan(self, target, options) -> tuple:
        """``target`` is a ScanTarget — same call shape as
        LocalScanner.scan, so the CLI swaps drivers freely
        (scanner.Driver in the reference).

        Every Scan carries a fresh idempotency key shared by all
        retry attempts of THIS call: if a response is lost after the
        server enqueued the scan, the retry replays the first
        enqueue's outcome instead of double-enqueuing into the
        scheduler.

        It also carries a client-generated ``trace_id`` (Dapper-style
        propagation, docs/observability.md): the server roots this
        request's span tree under it, so the caller can pull the
        trace from ``GET /trace/<id>`` — the id is logged at debug
        and kept on ``self.last_trace_id``. Retries reuse the same
        id: they are attempts at ONE logical request.

        Fleet propagation (obs/propagate.py): when the caller has an
        active local span, its context rides a ``traceparent`` field
        and the server's root becomes a true CHILD of that span —
        one tree spanning both processes. Without one, a fresh id is
        minted exactly as before."""
        import uuid

        from ..obs.propagate import current_context
        ctx = current_context()
        self.last_trace_id = ctx.trace_id if ctx is not None \
            else uuid.uuid4().hex
        log.debug("scan %r trace_id=%s", target.name,
                  self.last_trace_id)
        deadline_s = float(getattr(options, "deadline_s", 0.0)
                           or 0.0)
        body = {
            "idempotency_key": uuid.uuid4().hex,
            "trace_id": self.last_trace_id,
            "target": target.name,
            "artifact_id": target.artifact_id,
            "blob_ids": list(target.blob_ids),
            "options": {
                "vuln_type": list(options.vuln_type),
                "security_checks": list(options.security_checks),
                "list_all_packages": options.list_all_packages,
                "scan_removed_packages":
                    options.scan_removed_packages,
                "backend": getattr(options, "backend", "tpu"),
            },
        }
        if ctx is not None:
            body["traceparent"] = ctx.to_header()
        if deadline_s:
            body["deadline_s"] = deadline_s
        if self.tenant:
            body["tenant"] = self.tenant
        # the retry loop is capped at the request's own deadline —
        # a 429's Retry-After is honored, but never past the point
        # where the answer would arrive too late to matter
        out = self.call(SCANNER_PREFIX + "Scan", body,
                        deadline_s=deadline_s)
        # behind a scan router the response says which backend
        # replica served it (body field; the header is the fallback
        # call() already captured) — callers log it for debugging
        # ring placement
        routed = str(out.get("routed_replica") or "")
        if routed:
            self.last_routed_replica = routed
            log.debug("scan %r served by replica %s", target.name,
                      routed)
        results = [result_from_dict(r)
                   for r in out.get("results") or []]
        return results, os_from_dict(out.get("os"))
